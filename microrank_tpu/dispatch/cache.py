"""Persistent compile cache wiring + the warmup-program manifest.

First-call compile of the fused rank program costs ~1.7 s per process
cold (BENCH_r05's compile_ms); the compiled XLA executable is a pure
function of the HLO, so a restarted serve/stream process re-paying it is
waste. Two mechanisms close the gap:

* the **persistent compilation cache** (``jax_compilation_cache_dir``):
  compiled programs land on disk keyed by HLO hash and reload in
  milliseconds. ``configure_compile_cache`` is the ONE wiring point —
  the CLI, the serve/stream entry points and the bench all call it; the
  directory resolves ``MICRORANK_JIT_CACHE`` (env) over
  ``RuntimeConfig.compile_cache_dir`` over the user-cache default. The
  min-compile-time/min-entry-size gates are zeroed: jax's defaults only
  persist compilations slower than 1 s, which would skip most of this
  framework's windows-shaped programs and every CPU run.

* the **warmup manifest** (``warmup_manifest.json`` next to the cache):
  the on-disk cache only helps when the program is *requested*, and a
  restarted process doesn't know which occupancies/kernels it compiled
  last time until traffic arrives. Serve and stream record the program
  shapes they warmed/dispatched; a restart replays the manifest at
  startup — every trace hits the persistent cache, so the whole replay
  costs milliseconds and the first real window/request pays nothing.

``CompileCacheProbe`` turns cache behavior into metrics: it counts the
cache directory's entries around each observed compile — the entry
count growing is a miss (a fresh compile persisted), unchanged is a hit
(pure reload) — feeding ``microrank_compile_cache_events_total``.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import List, Optional

from ..utils.logging import get_logger

log = get_logger("microrank_tpu.dispatch.cache")

WARMUP_MANIFEST_NAME = "warmup_manifest.json"

_configured_dir: Optional[str] = None


def resolve_cache_dir(runtime=None) -> str:
    """Cache directory precedence: MICRORANK_JIT_CACHE env >
    RuntimeConfig.compile_cache_dir > the user-cache default."""
    env = os.environ.get("MICRORANK_JIT_CACHE")
    if env:
        return env
    if runtime is not None and getattr(runtime, "compile_cache_dir", None):
        return str(runtime.compile_cache_dir)
    return os.path.join(
        os.path.expanduser("~"), ".cache", "microrank_tpu", "jit"
    )


def configure_compile_cache(runtime=None) -> Optional[str]:
    """Point jax's persistent compilation cache at the resolved
    directory (idempotent; best-effort — a broken cache must never take
    the pipeline down). Returns the directory, or None on failure."""
    global _configured_dir
    try:
        import jax

        cache_dir = resolve_cache_dir(runtime)
        if _configured_dir == cache_dir:
            return cache_dir
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        if _configured_dir is not None:
            # jax binds its persistent-cache backend to the FIRST dir it
            # touches; switching dirs mid-process (config-driven
            # reconfiguration, tests) needs an explicit reset or writes
            # keep landing in the old directory. Best-effort private
            # API — absent on older jax, where the first dir wins.
            try:
                from jax._src import compilation_cache as _cc

                _cc.reset_cache()
            except Exception:  # pragma: no cover - jax-version dependent
                pass
        for knob, value in (
            ("jax_persistent_cache_min_compile_time_secs", 0.0),
            ("jax_persistent_cache_min_entry_size_bytes", 0),
        ):
            try:
                jax.config.update(knob, value)
            except AttributeError:  # older jax without the knob
                pass
        _configured_dir = cache_dir
        return cache_dir
    except Exception as exc:  # pragma: no cover - cache is best-effort
        log.warning("compile cache unavailable (%s); compiling cold", exc)
        return None


class CompileCacheProbe:
    """Hit/miss accounting over the persistent cache directory.

    jax exposes no stable cache-hit API, but the cache's on-disk entry
    count is ground truth: ``observe()`` after a (possible) compile
    reports "miss" when entries appeared since the last scan and "hit"
    otherwise, recording both into the metrics registry.
    """

    def __init__(self, cache_dir: Optional[str]):
        self.cache_dir = Path(cache_dir) if cache_dir else None
        self._entries = self._scan()
        self.hits = 0
        self.misses = 0

    def _scan(self) -> int:
        if self.cache_dir is None or not self.cache_dir.exists():
            return 0
        return sum(1 for p in self.cache_dir.rglob("*") if p.is_file())

    def observe(self) -> Optional[str]:
        """Classify the compile(s) since the last observation."""
        if self.cache_dir is None:
            return None
        from ..obs.metrics import record_compile_cache
        from ..utils.guards import assert_device_owner

        # The probe reads the cache dir the owner thread's compiles
        # write into; observing from another thread races the scan
        # against an in-flight compile (mrsan seam).
        assert_device_owner("dispatch.cache_probe")

        now = self._scan()
        event = "miss" if now > self._entries else "hit"
        self._entries = now
        if event == "hit":
            self.hits += 1
        else:
            self.misses += 1
        record_compile_cache(event)
        return event


# --------------------------------------------------------------- manifest


def _manifest_path(cache_dir) -> Path:
    return Path(cache_dir) / WARMUP_MANIFEST_NAME


def load_manifest(cache_dir: Optional[str]) -> List[dict]:
    """Entries recorded by previous processes ([] when absent/corrupt)."""
    if not cache_dir:
        return []
    path = _manifest_path(cache_dir)
    if not path.exists():
        return []
    try:
        data = json.loads(path.read_text())
        return list(data.get("programs", []))
    except (ValueError, OSError) as exc:
        log.warning("warmup manifest unreadable (%s); ignoring", exc)
        return []


def _shape_sig(shape: dict) -> tuple:
    return (
        int(shape.get("occupancy", 1)),
        tuple(tuple(int(d) for d in leaf)
              for leaf in shape.get("leaves", [])),
    )


def record_manifest_entry(
    cache_dir: Optional[str],
    pipeline: str,
    kernel: str,
    occupancies,
    shapes=None,
    max_shapes: int = 8,
) -> None:
    """Merge one warmed program shape into the manifest (occupancies
    union per (pipeline, kernel) key); best-effort.

    ``shapes`` — optional production pad-bucket records, each
    ``{"occupancy": n, "leaves": [[dims...], ...]}`` (the graph's
    padded leaf shapes, i.e. ``bucket_key(graph, kernel)[1:]``). These
    let a restart replay the EXACT jit-cache keys the previous process
    served instead of synthetic approximations; kept newest-first,
    deduped, capped at ``max_shapes`` per (pipeline, kernel).
    """
    if not cache_dir:
        return
    try:
        entries = load_manifest(cache_dir)
        occs = sorted({int(o) for o in occupancies})
        new_shapes = [
            {
                "occupancy": int(s.get("occupancy", 1)),
                "leaves": [
                    [int(d) for d in leaf] for leaf in s.get("leaves", [])
                ],
            }
            for s in (shapes or [])
        ]
        for e in entries:
            if e.get("pipeline") == pipeline and e.get("kernel") == kernel:
                merged = sorted(set(e.get("occupancies", [])) | set(occs))
                old_shapes = list(e.get("shapes", []))
                seen = set()
                merged_shapes = []
                for s in new_shapes + old_shapes:
                    sig = _shape_sig(s)
                    if sig in seen:
                        continue
                    seen.add(sig)
                    merged_shapes.append(s)
                merged_shapes = merged_shapes[: max(0, int(max_shapes))]
                if (
                    merged == e.get("occupancies")
                    and merged_shapes == old_shapes
                ):
                    return  # nothing new — skip the write
                e["occupancies"] = merged
                if merged_shapes:
                    e["shapes"] = merged_shapes
                break
        else:
            entry = {
                "pipeline": pipeline,
                "kernel": kernel,
                "occupancies": occs,
            }
            if new_shapes:
                seen = set()
                deduped = []
                for s in new_shapes:
                    sig = _shape_sig(s)
                    if sig in seen:
                        continue
                    seen.add(sig)
                    deduped.append(s)
                entry["shapes"] = deduped[: max(0, int(max_shapes))]
            entries.append(entry)
        # Atomic + durable (tmp+fsync+rename, utils.atomic): the bare
        # tmp+replace this used to do was atomic against readers but a
        # power cut could still leave an empty rename target.
        from ..utils.atomic import atomic_write_json

        atomic_write_json(_manifest_path(cache_dir), {"programs": entries})
        from ..obs.metrics import record_compile_cache

        record_compile_cache("manifest_write")
    except OSError as exc:
        log.warning("warmup manifest write failed (%s)", exc)


def manifest_occupancies(
    cache_dir: Optional[str], pipeline: str
) -> List[int]:
    """Occupancies a previous ``pipeline`` process recorded (any
    kernel) — the set a warm restart should re-trace."""
    occs = set()
    for e in load_manifest(cache_dir):
        if e.get("pipeline") == pipeline:
            occs.update(int(o) for o in e.get("occupancies", []))
    return sorted(occs)


def manifest_shapes(
    cache_dir: Optional[str], pipeline: str
) -> List[tuple]:
    """Production pad-bucket shapes a previous ``pipeline`` process
    recorded: ``(kernel, occupancy, leaves)`` tuples with ``leaves`` a
    tuple of leaf-shape tuples — the full jit-cache key modulo config.
    Shape-faithful warmup replays these at startup."""
    out = []
    for e in load_manifest(cache_dir):
        if e.get("pipeline") != pipeline or not e.get("kernel"):
            continue
        for s in e.get("shapes", []):
            occ, leaves = _shape_sig(s)
            out.append((str(e["kernel"]), occ, leaves))
    return out


def manifest_kernels(
    cache_dir: Optional[str], pipeline: str
) -> List[str]:
    """Kernels a previous ``pipeline`` process warmed — the compile
    witness (analysis.shapes.predict_key_space) and the ``witness``
    CLI read this to narrow the predicted key space to what the
    warmup manifest actually declares."""
    kernels = set()
    for e in load_manifest(cache_dir):
        if e.get("pipeline") == pipeline and e.get("kernel"):
            kernels.add(str(e["kernel"]))
    return sorted(kernels)
