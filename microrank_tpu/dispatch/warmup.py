"""Warmup program replay: trace the programs a process will need
before traffic arrives.

The jit cache keys on (padded shapes, occupancy, config); the
persistent compile cache (dispatch.cache) turns each compile into a
disk reload — but only once the program is *requested*. This module is
the requester: build one small synthetic abnormal window through the
normal ``prepare_window_graph`` seam and dispatch it through the router
at each target occupancy. Serve runs it at startup (its configured
occupancies plus whatever the warmup manifest recorded last run);
stream replays the manifest's occupancies on restart so an abnormal
burst right after a redeploy doesn't pay the ~1.7 s first-call compile.
"""

from __future__ import annotations

from typing import Iterable, Optional, Tuple

from ..utils.logging import get_logger

log = get_logger("microrank_tpu.dispatch.warmup")


def synthetic_prepared(config) -> Optional[Tuple[object, list, str]]:
    """(graph, op_names, kernel) of a small synthetic abnormal window
    prepared through the production seam, or None when the fixed-seed
    case fails to partition (never observed; guarded anyway)."""
    from ..detect import compute_slo, detect_partition
    from ..rank_backends.jax_tpu import prepare_window_graph
    from ..testing import SyntheticConfig, generate_case

    case = generate_case(
        SyntheticConfig(n_operations=12, n_traces=60, seed=0)
    )
    vocab, baseline = compute_slo(case.normal)
    flag, nrm, abn = detect_partition(config, vocab, baseline, case.abnormal)
    if not flag or not nrm or not abn:  # pragma: no cover - fixed seed
        log.warning("warmup case did not partition; skipping warmup")
        return None
    return prepare_window_graph(case.abnormal, nrm, abn, config)


def graph_like(config, kernel: str, leaves_shapes) -> Optional[object]:
    """A dispatchable window graph whose padded leaf shapes equal
    ``leaves_shapes`` (one shape tuple per pytree leaf — a recorded
    ``bucket_key(graph, kernel)[1:]``), so dispatching it traces the
    EXACT jit program a production window of that pad bucket hits.

    Built by preparing the synthetic warmup window with the target
    kernel forced, then resizing each leaf to the recorded shape
    (zero-fill, overlapping region copied from the synthetic values so
    the numerics stay tame). Returns None when the recorded signature
    no longer matches this build's pytree (kernel/config drift) — the
    caller skips that manifest entry rather than warming a program no
    request will ever hit.
    """
    import dataclasses

    import jax
    import numpy as np

    forced = dataclasses.replace(
        config, runtime=dataclasses.replace(config.runtime, kernel=kernel)
    )
    prepared = synthetic_prepared(forced)
    if prepared is None:
        return None
    graph, _, built_kernel = prepared
    if built_kernel != kernel:  # pragma: no cover - forced above
        return None
    leaves, treedef = jax.tree.flatten(graph)
    targets = [tuple(int(d) for d in s) for s in leaves_shapes]
    if len(leaves) != len(targets):
        return None
    out = []
    for leaf, target in zip(leaves, targets):
        src = np.asarray(leaf)
        if src.shape == target:
            out.append(leaf)
            continue
        if src.ndim != len(target):
            return None
        dst = np.zeros(target, dtype=src.dtype)
        overlap = tuple(
            slice(0, min(a, b)) for a, b in zip(src.shape, target)
        )
        dst[overlap] = src[overlap]
        out.append(dst)
    return jax.tree.unflatten(treedef, out)


def warm_manifest_shapes(
    router,
    config,
    cache_dir,
    pipeline: str,
    probe=None,
) -> int:
    """Shape-faithful warmup: replay every production pad-bucket shape
    the manifest recorded for ``pipeline`` (dispatch.cache
    ``manifest_shapes``) through the router, so a restarted process
    compiles — or reloads from the persistent cache — the same jit
    programs it served before going down, not just synthetic
    approximations. Returns the number of (kernel, occupancy, shapes)
    signatures warmed; each failure skips that signature only."""
    from ..dispatch.cache import manifest_shapes
    from ..obs.spans import get_tracer

    sigs = manifest_shapes(cache_dir, pipeline)
    if not sigs:
        return 0
    tracer = get_tracer()
    was_enabled = tracer.enabled
    tracer.enabled = False
    warmed = 0
    try:
        conv = bool(config.runtime.convergence_trace)
        for kernel, occ, leaves_shapes in sigs:
            try:
                graph = graph_like(config, kernel, leaves_shapes)
                if graph is None:
                    _record_warm_shape("skipped")
                    continue
                router.rank_batch(
                    [graph] * max(1, int(occ)), kernel,
                    conv_trace=conv, record=False,
                )
                if probe is not None:
                    probe.observe()
                warmed += 1
                _record_warm_shape("warmed")
            except Exception as exc:  # noqa: BLE001 - one stale
                # signature must not abort the rest of the warmup
                log.warning(
                    "shape warmup failed for kernel=%s occ=%d (%s)",
                    kernel, occ, exc,
                )
                _record_warm_shape("failed")
        return warmed
    finally:
        tracer.enabled = was_enabled


def _record_warm_shape(outcome: str) -> None:
    try:
        from ..obs.metrics import record_warm_shape

        record_warm_shape(outcome)
    except Exception:  # pragma: no cover - metrics best-effort
        pass


def warm_occupancies(
    router,
    config,
    occupancies: Iterable[int],
    probe=None,
) -> Optional[str]:
    """Dispatch the batched rank program at each occupancy through the
    router (metrics suppressed — warmup must not pollute route/
    occupancy telemetry, and the span tracer is paused so synthetic
    warmup traces never reach a flight dump). ``probe``
    (dispatch.cache.CompileCacheProbe) classifies each compile as a
    persistent-cache hit or miss. Returns the kernel warmed, or None
    when nothing ran."""
    from ..obs.spans import get_tracer

    tracer = get_tracer()
    was_enabled = tracer.enabled
    tracer.enabled = False
    try:
        prepared = synthetic_prepared(config)
        if prepared is None:
            return None
        graph, _, kernel = prepared
        conv = bool(config.runtime.convergence_trace)
        for occ in occupancies:
            occ = max(1, int(occ))
            router.rank_batch(
                [graph] * occ, kernel, conv_trace=conv, record=False
            )
            if probe is not None:
                probe.observe()
        return kernel
    finally:
        tracer.enabled = was_enabled
