"""Warmup program replay: trace the programs a process will need
before traffic arrives.

The jit cache keys on (padded shapes, occupancy, config); the
persistent compile cache (dispatch.cache) turns each compile into a
disk reload — but only once the program is *requested*. This module is
the requester: build one small synthetic abnormal window through the
normal ``prepare_window_graph`` seam and dispatch it through the router
at each target occupancy. Serve runs it at startup (its configured
occupancies plus whatever the warmup manifest recorded last run);
stream replays the manifest's occupancies on restart so an abnormal
burst right after a redeploy doesn't pay the ~1.7 s first-call compile.
"""

from __future__ import annotations

from typing import Iterable, Optional, Tuple

from ..utils.logging import get_logger

log = get_logger("microrank_tpu.dispatch.warmup")


def synthetic_prepared(config) -> Optional[Tuple[object, list, str]]:
    """(graph, op_names, kernel) of a small synthetic abnormal window
    prepared through the production seam, or None when the fixed-seed
    case fails to partition (never observed; guarded anyway)."""
    from ..detect import compute_slo, detect_partition
    from ..rank_backends.jax_tpu import prepare_window_graph
    from ..testing import SyntheticConfig, generate_case

    case = generate_case(
        SyntheticConfig(n_operations=12, n_traces=60, seed=0)
    )
    vocab, baseline = compute_slo(case.normal)
    flag, nrm, abn = detect_partition(config, vocab, baseline, case.abnormal)
    if not flag or not nrm or not abn:  # pragma: no cover - fixed seed
        log.warning("warmup case did not partition; skipping warmup")
        return None
    return prepare_window_graph(case.abnormal, nrm, abn, config)


def warm_occupancies(
    router,
    config,
    occupancies: Iterable[int],
    probe=None,
) -> Optional[str]:
    """Dispatch the batched rank program at each occupancy through the
    router (metrics suppressed — warmup must not pollute route/
    occupancy telemetry, and the span tracer is paused so synthetic
    warmup traces never reach a flight dump). ``probe``
    (dispatch.cache.CompileCacheProbe) classifies each compile as a
    persistent-cache hit or miss. Returns the kernel warmed, or None
    when nothing ran."""
    from ..obs.spans import get_tracer

    tracer = get_tracer()
    was_enabled = tracer.enabled
    tracer.enabled = False
    try:
        prepared = synthetic_prepared(config)
        if prepared is None:
            return None
        graph, _, kernel = prepared
        conv = bool(config.runtime.convergence_trace)
        for occ in occupancies:
            occ = max(1, int(occ))
            router.rank_batch(
                [graph] * occ, kernel, conv_trace=conv, record=False
            )
            if probe is not None:
                probe.observe()
        return kernel
    finally:
        tracer.enabled = was_enabled
