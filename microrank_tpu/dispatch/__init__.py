"""Adaptive dispatch routing (PR 5).

One shared seam between "a prepared window graph" and "a device
program": size-aware sharded-vs-vmapped routing, burst coalescing
buckets, double-buffered staging, and the persistent compile cache +
warmup manifest. Serve's scheduler and stream's engine both dispatch
through here; the batch pipelines keep their own lanes (they already
pipeline via the table runner) but share the underlying staging and
kernel-resolution helpers.
"""

from .cache import (
    CompileCacheProbe,
    WARMUP_MANIFEST_NAME,
    configure_compile_cache,
    load_manifest,
    manifest_kernels,
    manifest_occupancies,
    manifest_shapes,
    record_manifest_entry,
    resolve_cache_dir,
)
from .router import DispatchRouter, RouteInfo, bucket_key
from .warmup import (
    graph_like,
    synthetic_prepared,
    warm_manifest_shapes,
    warm_occupancies,
)

__all__ = [
    "CompileCacheProbe",
    "DispatchRouter",
    "RouteInfo",
    "WARMUP_MANIFEST_NAME",
    "bucket_key",
    "configure_compile_cache",
    "graph_like",
    "load_manifest",
    "manifest_kernels",
    "manifest_occupancies",
    "manifest_shapes",
    "record_manifest_entry",
    "resolve_cache_dir",
    "synthetic_prepared",
    "warm_manifest_shapes",
    "warm_occupancies",
]
