"""The adaptive dispatch router: one device seam for serve and stream.

Before PR 5 the serve batcher and the stream engine each owned a
private single-path dispatch: stack the bucket, run the vmapped
single-device program, fetch. The proven mesh path
(``parallel.rank_windows_sharded``) was only reachable from the batch
pipelines, staging serialized with ranking, and every process paid the
~1.7 s first-call compile. The router centralizes the device half:

* **size-aware routing** — a batch whose staged footprint (post
  ``device_subset``) crosses ``DispatchConfig.sharded_bytes_threshold``,
  or whose occupancy fills the mesh's windows axis, dispatches through
  ``rank_windows_sharded`` on the configured mesh; everything else
  keeps the vmapped single-device program. Kernel resolution on the
  sharded route is the table lane's own policy
  (``parallel.sharded_rank.resolve_shard_kernel``), so the two callers
  and the batch pipeline cannot disagree — including the round-6
  partition-centric fallback: past the per-shard packed budget the
  policy lands on ``pcsr`` (per-shard partition tables; stage_sharded
  tiles the trace axis to PCSR_PART_TRACES * shards), and giant
  windows that no bitmap fits route through the same seam. Parity between the two
  routes is tie-aware by construction (both end in the same two-key
  sort) and pinned by tests/test_dispatch.py.

* **double-buffered staging** — ``rank_batch(next_batch=...)`` stages
  the NEXT batch (host blob pack + H2D transfer, both asynchronous
  with respect to device execution) after dispatching the current
  program and before fetching its results, so staging overlaps the
  rank and leaves the critical path; the staged handle is cached one
  slot deep and consumed by the next call. ``jax.block_until_ready``
  semantics live only at the consumer edge (the one batched
  ``jax.device_get`` of the tiny top-k outputs). Staged blob buffers
  are donated to the program on backends that support donation, so
  double-buffering holds at most one idle blob in HBM.

* **burst coalescing** — same-pad-bucket windows queued behind an
  in-flight dispatch coalesce into ONE vmapped program (the serve
  batcher's trick, now shared): ``bucket_key`` lives here and the
  stream engine groups its pending builds with it before calling
  ``rank_batch``.

Threading: the router has no thread of its own — every method runs on
the caller's device thread (scheduler thread in serve, engine thread in
stream), preserving the one-thread-owns-the-device program-order rule.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from ..config import MicroRankConfig
from ..utils.logging import get_logger


def bucket_key(graph, kernel: str) -> Tuple:
    """Shape signature of a (kernel-stripped) window graph: the jit
    cache key modulo config. Two graphs with equal keys stack into one
    batch whose compiled program is shared across every batch of the
    same occupancy. Shared by the serve batcher's shape buckets and the
    stream engine's burst coalescing."""
    import jax

    return (kernel,) + tuple(
        tuple(np.asarray(leaf).shape) for leaf in jax.tree.leaves(graph)
    )


@dataclass
class RouteInfo:
    """What one router dispatch did (journals + bench artifact)."""

    route: str                  # "vmapped" | "sharded"
    kernel: str                 # kernel actually dispatched
    windows: int                # batch occupancy
    footprint_bytes: int        # staged bytes that drove the decision
    dispatch_ms: float = 0.0    # issue -> results on host
    overlap_ms: float = 0.0     # next-batch staging hidden behind this rank
    prestaged: bool = False     # this batch's staging was itself hidden


class _Staged:
    __slots__ = ("key", "route", "kernel", "handle", "n_pad", "footprint")

    def __init__(self, key, route, kernel, handle, n_pad=0, footprint=0):
        self.key = key
        self.route = route
        self.kernel = kernel
        self.handle = handle
        self.n_pad = n_pad
        self.footprint = footprint


class DispatchRouter:
    """Route prepared window graphs to the right device program.

    ``graphs`` passed to :meth:`rank_batch` must share one pad bucket
    (equal :func:`bucket_key`) — callers coalesce before routing.
    """

    def __init__(self, config: MicroRankConfig, mesh=None):
        self.config = config
        self.cfg = config.dispatch
        self.log = get_logger("microrank_tpu.dispatch")
        self._mesh = mesh if mesh is not None else self._build_mesh()
        self._prestaged: Optional[_Staged] = None
        self.dispatches = 0
        # Sampled device profiling: every N-th dispatch runs inside a
        # jax.profiler.trace session (ObsConfig.profile_every_n; 0 off).
        self._profiler = None
        obs = getattr(config, "obs", None)
        if obs is not None and obs.profile_every_n > 0:
            from pathlib import Path

            from ..obs.profiler import DeviceProfiler

            profile_dir = obs.profile_dir or str(
                Path.home() / ".cache" / "microrank_tpu" / "profiles"
            )
            self._profiler = DeviceProfiler(obs.profile_every_n, profile_dir)

    # ------------------------------------------------------------- mesh
    def _build_mesh(self):
        shape = self.config.runtime.mesh_shape
        if shape is None:
            return None
        shape = tuple(shape)
        if len(shape) == 1:  # pure graph parallelism
            shape = (1, shape[0])
        try:
            from ..parallel.mesh import SHARD_AXIS, WINDOW_AXIS, make_mesh

            mesh = make_mesh(shape, (WINDOW_AXIS, SHARD_AXIS))
        except ValueError as exc:
            self.log.warning(
                "mesh %s unavailable (%s); routing everything to the "
                "single-device path", shape, exc,
            )
            return None
        self.log.info(
            "dispatch router: mesh %s available for sharded routing",
            mesh.devices.shape,
        )
        return mesh

    @property
    def mesh(self):
        return self._mesh

    # ------------------------------------------------------------- plan
    def plan(self, graphs, kernel: str) -> Tuple[str, str, int]:
        """(route, resolved_kernel, footprint_bytes) for one batch.

        Decision table (see DESIGN.md "Dispatch router"):

        * no mesh configured                          -> vmapped
        * footprint >= sharded_bytes_threshold        -> sharded
        * occupancy >= mesh windows axis (axis > 1)   -> sharded
        * otherwise                                   -> vmapped
        """
        from ..rank_backends.jax_tpu import graph_device_bytes

        footprint = sum(graph_device_bytes(g) for g in graphs)
        if self._mesh is None:
            return "vmapped", kernel, footprint
        by_size = footprint >= max(0, int(self.cfg.sharded_bytes_threshold))
        w_n = int(self._mesh.devices.shape[0])
        by_occupancy = (
            self.cfg.shard_on_full_occupancy
            and w_n > 1
            and len(graphs) >= w_n
        )
        if not (by_size or by_occupancy):
            return "vmapped", kernel, footprint
        from ..parallel.sharded_rank import resolve_shard_kernel

        shard_kernel = resolve_shard_kernel(
            graphs, self._mesh, self.config.runtime, self.log
        )
        return "sharded", shard_kernel, footprint

    # ------------------------------------------------------------ stage
    def _stage(self, graphs, kernel: str) -> _Staged:
        key = self._key(graphs, kernel)
        route, resolved, footprint = self.plan(graphs, kernel)
        if route == "sharded":
            from ..parallel.sharded_rank import stage_sharded

            w_n = int(self._mesh.devices.shape[0])
            # The batch must divide the windows axis: pad by repeating
            # the last window and drop the tail rows after the fetch.
            n_pad = (-len(graphs)) % w_n
            handle = stage_sharded(
                list(graphs) + [graphs[-1]] * n_pad, self._mesh, resolved
            )
            return _Staged(key, route, resolved, handle, n_pad, footprint)
        from ..parallel.sharded_rank import stack_window_graphs
        from ..rank_backends.blob import stage_windows_batched
        from ..rank_backends.jax_tpu import device_subset

        stacked = device_subset(stack_window_graphs(graphs), resolved)
        handle = stage_windows_batched(
            stacked, self.config.runtime.blob_staging
        )
        return _Staged(key, route, resolved, handle, 0, footprint)

    @staticmethod
    def _key(graphs, kernel: str) -> Tuple:
        return (kernel,) + tuple(id(g) for g in graphs)

    def _take_prestaged(self, graphs, kernel: str) -> Optional[_Staged]:
        staged = self._prestaged
        self._prestaged = None
        if staged is not None and staged.key == self._key(graphs, kernel):
            return staged
        return None  # mismatch: the cached staging is dropped unused

    # --------------------------------------------------------- dispatch
    def _dispatch_program(self, staged: _Staged, conv_trace: bool):
        cfg = self.config
        if staged.route == "sharded":
            from ..parallel.sharded_rank import resolve_sharded_rank_fn

            # The sharded route's staged global arrays donate exactly
            # like the blob path's buffer: each staged handle is
            # dispatched once, so the program may consume it (halves
            # peak staging HBM under double-buffering on donation-
            # capable backends).
            fn = resolve_sharded_rank_fn(
                conv_trace, cfg.runtime.device_checks,
                donate=self._donate(),
            )
            return fn(
                staged.handle, cfg.pagerank, cfg.spectrum, self._mesh,
                staged.kernel,
            )
        from ..rank_backends.blob import dispatch_windows_staged

        return dispatch_windows_staged(
            staged.handle,
            cfg.pagerank,
            cfg.spectrum,
            staged.kernel,
            conv_trace=conv_trace,
            donate=self._donate(),
        )

    def _donate(self) -> bool:
        if not self.cfg.donate_staging:
            return False
        import jax

        # CPU (and some plugin) backends warn per call on unusable
        # donations; donate only where it buys the HBM back.
        return jax.default_backend() not in ("cpu",)

    # -------------------------------------------------------------- API
    def rank_batch(
        self,
        graphs,
        kernel: str,
        conv_trace: bool = False,
        next_batch: Optional[Tuple[List, str]] = None,
        record: bool = True,
    ):
        """Rank one same-bucket batch; returns ``(outs, RouteInfo)``.

        ``outs`` are HOST arrays — ``(top_idx [B,k], top_scores [B,k],
        n_valid [B])`` plus ``(residuals [B,2,I], n_iters [B])`` when
        ``conv_trace``. ``next_batch=(graphs, kernel)`` double-buffers:
        the next batch's staging is issued after this batch's program
        and before its fetch, so the H2D transfer overlaps device
        execution; the staged handle is consumed by the next
        ``rank_batch`` call with the same graphs. ``record=False``
        (warmup) skips the route metrics.
        """
        import contextlib

        import jax

        from ..obs.spans import get_tracer
        from ..utils.guards import assert_device_owner

        assert_device_owner("dispatch.rank_batch")
        tracer = get_tracer()
        t0 = time.monotonic()
        staged = self._take_prestaged(graphs, kernel)
        prestaged = staged is not None
        if staged is None:
            with tracer.span(
                "staging", service="dispatch", kernel=kernel,
                windows=len(graphs),
            ):
                staged = self._stage(graphs, kernel)
        from ..analysis import mrsan

        if mrsan.witness_armed():
            # Compile witness (R13-R16's runtime twin): report this
            # batch's compile-key signature before dispatch so an
            # unpredicted key is journalled even if the compile hangs.
            mrsan.observe_compile_key(
                "dispatch." + staged.route,
                kernel=staged.kernel,
                graph=graphs[0] if graphs else None,
                occupancy=len(graphs),
            )
        profile_cm = (
            self._profiler.session()
            if self._profiler is not None
            else contextlib.nullcontext()
        )
        with profile_cm:
            with tracer.span(
                "device_dispatch", service="dispatch",
                kernel=staged.kernel, route=staged.route,
                windows=len(graphs),
            ):
                dev_outs = self._dispatch_program(staged, conv_trace)
            overlap_s = 0.0
            if next_batch is not None and self.cfg.double_buffer:
                t_stage = time.monotonic()
                try:
                    # The prestage span attributes to the CURRENT trace
                    # (whose rank hides it) — the overlap is this
                    # window's contribution to the pipeline.
                    with tracer.span("prestage", service="dispatch"):
                        self._prestaged = self._stage(*next_batch)
                    overlap_s = time.monotonic() - t_stage
                except Exception as exc:  # noqa: BLE001 - a broken NEXT
                    # batch must not fail THIS one; it will surface on
                    # its own dispatch turn.
                    self.log.warning(
                        "double-buffer prestage failed: %s", exc
                    )
            # Consumer edge: the one blocking fetch of the tiny top-k
            # outputs (block_until_ready is not a sound fence on
            # tunneled runtimes; a value transfer is).
            with tracer.span(
                "result_fetch", service="dispatch", route=staged.route
            ):
                outs = jax.device_get(dev_outs)
        from ..obs.profiler import record_device_memory

        record_device_memory()
        from ..utils.guards import sanitizers_enabled

        if sanitizers_enabled() and staged.route == "sharded":
            # mrsan: the per-shard collective multisets recorded by the
            # armed interposition must match — a shard that skipped a
            # psum (R9's bug class) diverges here, at the fetch edge.
            from ..analysis import mrsan

            mrsan.verify_and_reset(log=self.log)
        if staged.n_pad:
            outs = tuple(o[: len(graphs)] for o in outs)
        self.dispatches += 1
        info = RouteInfo(
            route=staged.route,
            kernel=staged.kernel,
            windows=len(graphs),
            footprint_bytes=staged.footprint,
            dispatch_ms=round((time.monotonic() - t0) * 1e3, 3),
            overlap_ms=round(overlap_s * 1e3, 3),
            prestaged=prestaged,
        )
        if record:
            from ..obs.metrics import record_dispatch_route, stage_seconds

            record_dispatch_route(info.route, info.windows, overlap_s)
            # The device path as a first-class stage observation:
            # per-host dispatch cost rides the fleet metrics delta
            # (the coordinator's host/stage gauge) and the SLO
            # watchdog can budget it like any pipeline stage
            # (stage_budgets=("dispatch", ...)).
            stage_seconds().observe(
                info.dispatch_ms / 1e3, stage="dispatch"
            )
        return outs, info

    def rank_fused(self, graph, kernel: str, init=None, record: bool = True):
        """Rank ONE window through the fused pair program — both
        PageRank solves plus the spectrum epilogue in a single jitted
        dispatch (blob.stage_rank_window_warm), threading ``init`` (the
        previous window's mapped converged state, or None for a cold
        seed that still exports state). Returns ``(outs, RouteInfo)``
        where ``outs`` is the HOST 9-tuple — ``(top_idx, top_scores,
        n_valid, residuals, n_iters, score_n, rv_n, score_a, rv_a)``;
        entries [5:9] are the state export for the next window.

        Always single-window and single-device (warm state is
        shape-bound to one window's pad bucket; coalescing/sharding
        stay on rank_batch). The compile witness observes the dispatch
        as program "dispatch.fused" — one key per (kernel, pad bucket,
        init structure), so a steady stream proves dispatches-per-window
        == 1 with at most two cached programs (cold seed + warm)."""
        import jax

        from ..obs.spans import get_tracer
        from ..rank_backends.blob import stage_rank_window_warm
        from ..rank_backends.jax_tpu import graph_device_bytes
        from ..utils.guards import assert_device_owner

        assert_device_owner("dispatch.rank_fused")
        tracer = get_tracer()
        t0 = time.monotonic()
        from ..analysis import mrsan

        if mrsan.witness_armed():
            mrsan.observe_compile_key(
                "dispatch.fused", kernel=kernel, graph=graph, occupancy=1
            )
        cfg = self.config
        with tracer.span(
            "device_dispatch", service="dispatch", kernel=kernel,
            route="fused", windows=1,
        ):
            dev_outs = stage_rank_window_warm(
                graph, init, cfg.pagerank, cfg.spectrum, kernel,
                cfg.runtime.blob_staging,
            )
        with tracer.span(
            "result_fetch", service="dispatch", route="fused"
        ):
            outs = jax.device_get(dev_outs)
        from ..obs.profiler import record_device_memory

        record_device_memory()
        self.dispatches += 1
        info = RouteInfo(
            route="fused",
            kernel=kernel,
            windows=1,
            footprint_bytes=graph_device_bytes(graph),
            dispatch_ms=round((time.monotonic() - t0) * 1e3, 3),
        )
        if record:
            from ..obs.metrics import record_dispatch_route, stage_seconds

            record_dispatch_route(info.route, info.windows, 0.0)
            stage_seconds().observe(
                info.dispatch_ms / 1e3, stage="dispatch"
            )
        return outs, info

    def drop_prestaged(self) -> None:
        """Discard the cached prestaged batch (caller aborted it)."""
        self._prestaged = None
