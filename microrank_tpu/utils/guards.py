"""Numeric hygiene guards (SURVEY.md §5 race-detection/sanitizers row).

XLA programs are data-race-free by construction; the failure mode that
remains is numeric — NaN/inf escaping a division in the preference vector
or a spectrum formula. Backends validate fetched scores by default
(``RuntimeConfig.validate_numerics``); for deep debugging, enable
``jax.config.update("jax_debug_nans", True)`` to trap the originating op.

This module also holds the process-wide switch for the shape/dtype
contracts on the rank/spectrum entry points
(``analysis.contracts.contract``, mrlint rule R5): backends enter
``contract_checks(cfg.runtime.validate_numerics)`` around dispatch, so
one RuntimeConfig knob gates both the host-side score validation and
the trace-time signature contracts.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

import numpy as np


class NumericsError(RuntimeError):
    pass


class ContractError(TypeError):
    """A value violated an ``analysis.contracts.contract`` spec."""


_state = threading.local()


def contracts_enabled() -> bool:
    """Whether @contract specs are enforced in this thread (default off —
    the decorator is then a flag check)."""
    return getattr(_state, "contracts", False)


@contextmanager
def contract_checks(enabled: bool):
    """Enable/disable contract enforcement for the dynamic extent of the
    block (thread-local — the async dispatch workers validate or skip
    independently of the main thread)."""
    prev = getattr(_state, "contracts", False)
    _state.contracts = bool(enabled)
    try:
        yield
    finally:
        _state.contracts = prev


def set_contract_checks(enabled: bool) -> None:
    """Imperative form of :func:`contract_checks` (process setup paths)."""
    _state.contracts = bool(enabled)


def assert_finite_scores(scores, context: str) -> None:
    """Raise NumericsError if any ranked score is NaN/inf — or if the
    scores cannot be interpreted as numbers at all (a corrupted fetch
    should fail as a numerics error at the validation boundary, not as
    a numpy cast error deep in the caller)."""
    try:
        arr = np.asarray(scores, dtype=np.float64)
    except (TypeError, ValueError) as e:
        raise NumericsError(
            f"non-numeric ranking scores in {context}: {e}"
        ) from None
    bad = ~np.isfinite(arr)
    if bad.any():
        idx = np.flatnonzero(bad.reshape(-1))[:5].tolist()
        flat = arr.reshape(-1)
        raise NumericsError(
            f"non-finite ranking scores in {context}: positions {idx} of "
            f"{arr.size} (values {[float(flat[i]) for i in idx]}); enable "
            "jax_debug_nans to locate the producing op"
        )
