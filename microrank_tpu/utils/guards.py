"""Numeric hygiene guards (SURVEY.md §5 race-detection/sanitizers row).

XLA programs are data-race-free by construction; the failure mode that
remains is numeric — NaN/inf escaping a division in the preference vector
or a spectrum formula. Backends validate fetched scores by default
(``RuntimeConfig.validate_numerics``); for deep debugging, enable
``jax.config.update("jax_debug_nans", True)`` to trap the originating op.

This module also holds the process-wide switch for the shape/dtype
contracts on the rank/spectrum entry points
(``analysis.contracts.contract``, mrlint rule R5): backends enter
``contract_checks(cfg.runtime.validate_numerics)`` around dispatch, so
one RuntimeConfig knob gates both the host-side score validation and
the trace-time signature contracts.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

import numpy as np


class NumericsError(RuntimeError):
    pass


class ContractError(TypeError):
    """A value violated an ``analysis.contracts.contract`` spec."""


class DeviceOwnershipError(RuntimeError):
    """A device-touching seam ran on a thread that is neither the
    claimed device owner nor an authorized delegate (mrsan, rule R8's
    runtime twin)."""


_state = threading.local()

# ---------------------------------------------------------------------------
# Device-thread ownership (mrsan — the runtime twin of mrlint R8).
#
# The static model (analysis.threads): one thread owns the device; pool
# workers, HTTP handlers and sink callbacks never dispatch. The runtime
# sanitizer validates it: run entries claim ownership
# (``claim_device_owner``), sanctioned delegates register
# (``authorize_device_thread`` — the table lane's async staging/fetch
# workers), and every staging/dispatch/fetch seam asserts
# (``assert_device_owner``). Checks are armed by
# ``RuntimeConfig.sanitizers`` (analysis.mrsan.configure_sanitizers);
# disarmed they cost one boolean read.

_own_lock = threading.Lock()
_owner_ident: int | None = None
_owner_role: str | None = None
_authorized: set = set()
_sanitizers_on = False


def set_sanitizers(enabled: bool) -> None:
    """Arm/disarm the mrsan runtime checks process-wide."""
    global _sanitizers_on
    _sanitizers_on = bool(enabled)


def sanitizers_enabled() -> bool:
    return _sanitizers_on


def claim_device_owner(role: str) -> None:
    """Declare the CURRENT thread the device owner (re-claimable: run
    entries claim at start, so ownership follows the active pipeline).
    The static analyzer treats thread roots that claim as owner threads
    — keep the call lexically inside the thread's root function."""
    global _owner_ident, _owner_role
    with _own_lock:
        _owner_ident = threading.get_ident()
        _owner_role = role


def release_device_owner() -> None:
    global _owner_ident, _owner_role
    with _own_lock:
        _owner_ident = None
        _owner_role = None


def authorize_device_thread() -> None:
    """Register the CURRENT thread as a sanctioned device delegate —
    used as the ``initializer=`` of the table lane's staging/fetch
    executors (RuntimeConfig.async_dispatch), whose device RPCs are
    single-width and ordered by construction."""
    with _own_lock:
        _authorized.add(threading.get_ident())


def reset_device_ownership() -> None:
    """Fresh ownership state (run entries, tests)."""
    global _owner_ident, _owner_role
    with _own_lock:
        _owner_ident = None
        _owner_role = None
        _authorized.clear()


def device_owner() -> tuple:
    """(role, ident) of the claimed owner, or (None, None)."""
    with _own_lock:
        return _owner_role, _owner_ident


def assert_device_owner(seam: str) -> None:
    """mrsan seam check: when sanitizers are armed and an owner is
    claimed, the calling thread must be the owner or an authorized
    delegate. Violations are counted (microrank_mrsan_violations_total)
    and raised — a cross-thread dispatch is a program-order bug, not a
    condition to limp through."""
    if not _sanitizers_on:
        return
    from ..obs.metrics import record_mrsan_check, record_mrsan_violation

    record_mrsan_check(seam)
    with _own_lock:
        owner = _owner_ident
        role = _owner_role
        ok = (
            owner is None
            or threading.get_ident() == owner
            or threading.get_ident() in _authorized
        )
    if not ok:
        record_mrsan_violation("cross-thread-device")
        raise DeviceOwnershipError(
            f"device seam `{seam}` entered on thread "
            f"{threading.current_thread().name!r} but the device owner "
            f"is {role!r} — jax staging/dispatch/fetch must stay on the "
            "owner thread (mrlint R8's runtime model); route the work "
            "through the owner loop or authorize_device_thread() if the "
            "delegation is by design"
        )


def contracts_enabled() -> bool:
    """Whether @contract specs are enforced in this thread (default off —
    the decorator is then a flag check)."""
    return getattr(_state, "contracts", False)


@contextmanager
def contract_checks(enabled: bool):
    """Enable/disable contract enforcement for the dynamic extent of the
    block (thread-local — the async dispatch workers validate or skip
    independently of the main thread)."""
    prev = getattr(_state, "contracts", False)
    _state.contracts = bool(enabled)
    try:
        yield
    finally:
        _state.contracts = prev


def set_contract_checks(enabled: bool) -> None:
    """Imperative form of :func:`contract_checks` (process setup paths)."""
    _state.contracts = bool(enabled)


def assert_finite_scores(scores, context: str) -> None:
    """Raise NumericsError if any ranked score is NaN/inf — or if the
    scores cannot be interpreted as numbers at all (a corrupted fetch
    should fail as a numerics error at the validation boundary, not as
    a numpy cast error deep in the caller)."""
    try:
        arr = np.asarray(scores, dtype=np.float64)
    except (TypeError, ValueError) as e:
        raise NumericsError(
            f"non-numeric ranking scores in {context}: {e}"
        ) from None
    bad = ~np.isfinite(arr)
    if bad.any():
        idx = np.flatnonzero(bad.reshape(-1))[:5].tolist()
        flat = arr.reshape(-1)
        raise NumericsError(
            f"non-finite ranking scores in {context}: positions {idx} of "
            f"{arr.size} (values {[float(flat[i]) for i in idx]}); enable "
            "jax_debug_nans to locate the producing op"
        )
