"""Numeric hygiene guards (SURVEY.md §5 race-detection/sanitizers row).

XLA programs are data-race-free by construction; the failure mode that
remains is numeric — NaN/inf escaping a division in the preference vector
or a spectrum formula. Backends validate fetched scores by default
(``RuntimeConfig.validate_numerics``); for deep debugging, enable
``jax.config.update("jax_debug_nans", True)`` to trap the originating op.

This module also holds the process-wide switch for the shape/dtype
contracts on the rank/spectrum entry points
(``analysis.contracts.contract``, mrlint rule R5): backends enter
``contract_checks(cfg.runtime.validate_numerics)`` around dispatch, so
one RuntimeConfig knob gates both the host-side score validation and
the trace-time signature contracts.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

import numpy as np


class NumericsError(RuntimeError):
    pass


class ContractError(TypeError):
    """A value violated an ``analysis.contracts.contract`` spec."""


class DeviceOwnershipError(RuntimeError):
    """A device-touching seam ran on a thread that is neither the
    claimed device owner nor an authorized delegate (mrsan, rule R8's
    runtime twin)."""


_state = threading.local()

# ---------------------------------------------------------------------------
# Device-thread ownership (mrsan — the runtime twin of mrlint R8).
#
# The static model (analysis.threads): one thread owns the device; pool
# workers, HTTP handlers and sink callbacks never dispatch. The runtime
# sanitizer validates it: run entries claim ownership
# (``claim_device_owner``), sanctioned delegates register
# (``authorize_device_thread`` — the table lane's async staging/fetch
# workers), and every staging/dispatch/fetch seam asserts
# (``assert_device_owner``). Checks are armed by
# ``RuntimeConfig.sanitizers`` (analysis.mrsan.configure_sanitizers);
# disarmed they cost one boolean read.

_own_lock = threading.Lock()
_owner_ident: int | None = None
_owner_role: str | None = None
_authorized: set = set()
_sanitizers_on = False


def set_sanitizers(enabled: bool) -> None:
    """Arm/disarm the mrsan runtime checks process-wide. The flag is
    read lock-free on every seam check by design (disarmed = one
    boolean read is the documented cost model); a stale read during
    the arm/disarm transition at worst skips or adds one check —
    mrlint R10's ``published`` seam."""
    global _sanitizers_on
    _sanitizers_on = published(bool(enabled))


def sanitizers_enabled() -> bool:
    return _sanitizers_on


def claim_device_owner(role: str) -> None:
    """Declare the CURRENT thread the device owner (re-claimable: run
    entries claim at start, so ownership follows the active pipeline).
    The static analyzer treats thread roots that claim as owner threads
    — keep the call lexically inside the thread's root function."""
    global _owner_ident, _owner_role
    with _own_lock:
        _owner_ident = threading.get_ident()
        _owner_role = role


def release_device_owner() -> None:
    global _owner_ident, _owner_role
    with _own_lock:
        _owner_ident = None
        _owner_role = None


def authorize_device_thread() -> None:
    """Register the CURRENT thread as a sanctioned device delegate —
    used as the ``initializer=`` of the table lane's staging/fetch
    executors (RuntimeConfig.async_dispatch), whose device RPCs are
    single-width and ordered by construction."""
    with _own_lock:
        _authorized.add(threading.get_ident())


def reset_device_ownership() -> None:
    """Fresh ownership state (run entries, tests)."""
    global _owner_ident, _owner_role
    with _own_lock:
        _owner_ident = None
        _owner_role = None
        _authorized.clear()


def device_owner() -> tuple:
    """(role, ident) of the claimed owner, or (None, None)."""
    with _own_lock:
        return _owner_role, _owner_ident


def assert_device_owner(seam: str) -> None:
    """mrsan seam check: when sanitizers are armed and an owner is
    claimed, the calling thread must be the owner or an authorized
    delegate. Violations are counted (microrank_mrsan_violations_total)
    and raised — a cross-thread dispatch is a program-order bug, not a
    condition to limp through."""
    if not _sanitizers_on:
        return
    from ..obs.metrics import record_mrsan_check, record_mrsan_violation

    record_mrsan_check(seam)
    with _own_lock:
        owner = _owner_ident
        role = _owner_role
        ok = (
            owner is None
            or threading.get_ident() == owner
            or threading.get_ident() in _authorized
        )
    if not ok:
        record_mrsan_violation("cross-thread-device")
        raise DeviceOwnershipError(
            f"device seam `{seam}` entered on thread "
            f"{threading.current_thread().name!r} but the device owner "
            f"is {role!r} — jax staging/dispatch/fetch must stay on the "
            "owner thread (mrlint R8's runtime model); route the work "
            "through the owner loop or authorize_device_thread() if the "
            "delegation is by design"
        )


# ---------------------------------------------------------------------------
# Lock tracking (mrsan — the runtime twin of mrlint R10/R11/R12).
#
# The static model (analysis.locks): every shared variable has a
# non-empty common lockset across its cross-thread accesses (R10), the
# lock-acquisition-order graph is acyclic (R11), and no blocking call
# happens under a lock (R12). The runtime half validates the first two
# Eraser-style: production locks wrap in :class:`TrackedLock` (a named
# threading lock recording per-thread held-locksets when sanitizers
# are armed), registered shared objects are lockset-checked on access
# (``register_shared``/``note_shared_access`` — candidate sets seeded
# from the static lock catalog), and a process-wide watchdog asserts
# the OBSERVED acquisition order stays a DAG on every armed acquire.
# Disarmed, every hook is one module-global boolean read.


class LockOrderError(RuntimeError):
    """An armed TrackedLock acquisition closed a cycle in the observed
    lock-order graph (mrsan, rule R11's runtime twin)."""


class LocksetError(RuntimeError):
    """A registered shared object was accessed with an empty candidate
    lockset (mrsan, rule R10's runtime twin — the Eraser discipline)."""


class _HeldLocks(threading.local):
    def __init__(self):
        self.stack: list = []


_held = _HeldLocks()
_order_lock = threading.Lock()
_order_edges: dict = {}       # lock name -> set of lock names acquired under it
_shared_lock = threading.Lock()
_shared_seed: dict = {}        # object name -> declared candidate lock names
_shared_candidates: dict = {}  # object name -> current (refined) candidates


def held_locks() -> tuple:
    """Names of the TrackedLocks the CURRENT thread holds, in
    acquisition order (armed mode only — disarmed holds record
    nothing)."""
    return tuple(_held.stack)


def _order_reaches(start: str, goal: str) -> bool:
    """DFS over the observed acquisition edges (caller holds
    _order_lock)."""
    stack = [start]
    seen = set()
    while stack:
        cur = stack.pop()
        if cur == goal:
            return True
        if cur in seen:
            continue
        seen.add(cur)
        stack.extend(_order_edges.get(cur, ()))
    return False


def _note_acquire(name: str) -> None:
    """Lock-order watchdog: record held->name edges; a new edge that
    closes a cycle is a potential deadlock — counted and raised (the
    second thread would already be blocked for real)."""
    holders = [h for h in _held.stack if h != name]
    if not holders:
        return
    with _order_lock:
        inversion = None
        for h in holders:
            if _order_reaches(name, h):
                inversion = h
                break
        if inversion is None:
            # Record only DAG-preserving edges: the inverting edge is
            # reported, not merged, so later well-ordered acquires of
            # the same locks do not trip on a poisoned graph.
            for h in holders:
                _order_edges.setdefault(h, set()).add(name)
    if inversion is not None:
        from ..obs.metrics import record_mrsan_violation

        record_mrsan_violation("lock-order")
        raise LockOrderError(
            f"lock-order inversion: acquiring {name!r} while holding "
            f"{inversion!r}, but the observed acquisition order already "
            f"has {name!r} -> ... -> {inversion!r} (mrlint R11's "
            "runtime model) — impose one global acquisition order"
        )


class TrackedLock:
    """A named threading lock that feeds the mrsan lockset/lock-order
    checkers when sanitizers are armed. Instances of one class share
    the name — the granularity of the static model. Disarmed cost: one
    boolean read per acquire/release on top of the raw lock."""

    __slots__ = ("name", "_inner")

    def __init__(self, name: str, reentrant: bool = False):
        self.name = str(name)
        self._inner = (
            threading.RLock() if reentrant else threading.Lock()
        )

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if _sanitizers_on:
            _note_acquire(self.name)
        ok = self._inner.acquire(blocking, timeout)
        if ok and _sanitizers_on:
            _held.stack.append(self.name)
        return ok

    def release(self) -> None:
        self._inner.release()
        if _sanitizers_on:
            stack = _held.stack
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] == self.name:
                    del stack[i]
                    break

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        return bool(locked()) if locked is not None else False


def register_shared(name: str, candidates) -> None:
    """Declare one shared object and the lock names the static
    analysis says guard it (the DESIGN.md lock catalog seeds these).
    The declaration survives ``reset_lock_tracking`` — a run entry
    resets the REFINED candidate sets back to the declared seed, not
    the registrations themselves (objects register at construction,
    which may precede the run entry)."""
    with _shared_lock:
        seed = frozenset(map(str, candidates))
        _shared_seed[str(name)] = seed
        _shared_candidates[str(name)] = set(seed)


def note_shared_access(name: str) -> None:
    """Eraser lockset check on one registered shared object: the
    candidate set intersects with the caller's held locks; an access
    that empties it means NO lock consistently guards the object —
    counted into microrank_mrsan_violations_total{kind=
    "shared-state-race"} and raised. Unregistered names are ignored
    (the checker validates the catalog, it does not invent one)."""
    if not _sanitizers_on:
        return
    from ..obs.metrics import (
        record_mrsan_lockset_check,
        record_mrsan_violation,
    )

    key = str(name)
    held = set(_held.stack)
    with _shared_lock:
        cand = _shared_candidates.get(key)
        if cand is None:
            return
        cand &= held
        emptied = not cand
    record_mrsan_lockset_check(key)
    if emptied:
        record_mrsan_violation("shared-state-race")
        raise LocksetError(
            f"shared object {key!r} accessed with candidate lockset "
            f"emptied (thread {threading.current_thread().name!r} "
            f"holds {sorted(held) or 'no tracked locks'}) — no lock "
            "consistently guards this object across its accessing "
            "threads (mrlint R10's runtime model)"
        )


def reset_lock_tracking() -> None:
    """Fresh lock-order graph; refined candidate locksets return to
    their declared seeds (run entries, tests). Held stacks are
    per-thread and clear as locks release."""
    with _order_lock:
        _order_edges.clear()
    with _shared_lock:
        _shared_candidates.clear()
        for name, seed in _shared_seed.items():
            _shared_candidates[name] = set(seed)


def published(value):
    """Mark an INTENTIONAL lock-free cross-thread publish (mrlint R10's
    escape seam): ``self.stop = published(True)`` documents that racy
    readers are by design (monotonic flags, best-effort stats).
    Identity at runtime; the static analysis exempts every variable
    whose writes route through it."""
    return value


def contracts_enabled() -> bool:
    """Whether @contract specs are enforced in this thread (default off —
    the decorator is then a flag check)."""
    return getattr(_state, "contracts", False)


@contextmanager
def contract_checks(enabled: bool):
    """Enable/disable contract enforcement for the dynamic extent of the
    block (thread-local — the async dispatch workers validate or skip
    independently of the main thread)."""
    prev = getattr(_state, "contracts", False)
    _state.contracts = bool(enabled)
    try:
        yield
    finally:
        _state.contracts = prev


def set_contract_checks(enabled: bool) -> None:
    """Imperative form of :func:`contract_checks` (process setup paths)."""
    _state.contracts = bool(enabled)


def assert_finite_scores(scores, context: str) -> None:
    """Raise NumericsError if any ranked score is NaN/inf — or if the
    scores cannot be interpreted as numbers at all (a corrupted fetch
    should fail as a numerics error at the validation boundary, not as
    a numpy cast error deep in the caller)."""
    try:
        arr = np.asarray(scores, dtype=np.float64)
    except (TypeError, ValueError) as e:
        raise NumericsError(
            f"non-numeric ranking scores in {context}: {e}"
        ) from None
    bad = ~np.isfinite(arr)
    if bad.any():
        idx = np.flatnonzero(bad.reshape(-1))[:5].tolist()
        flat = arr.reshape(-1)
        raise NumericsError(
            f"non-finite ranking scores in {context}: positions {idx} of "
            f"{arr.size} (values {[float(flat[i]) for i in idx]}); enable "
            "jax_debug_nans to locate the producing op"
        )
