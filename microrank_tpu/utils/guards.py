"""Numeric hygiene guards (SURVEY.md §5 race-detection/sanitizers row).

XLA programs are data-race-free by construction; the failure mode that
remains is numeric — NaN/inf escaping a division in the preference vector
or a spectrum formula. Backends validate fetched scores by default
(``RuntimeConfig.validate_numerics``); for deep debugging, enable
``jax.config.update("jax_debug_nans", True)`` to trap the originating op.
"""

from __future__ import annotations

import numpy as np


class NumericsError(RuntimeError):
    pass


def assert_finite_scores(scores, context: str) -> None:
    """Raise if any ranked score is NaN or infinite."""
    arr = np.asarray(scores, dtype=np.float64)
    bad = ~np.isfinite(arr)
    if bad.any():
        idx = np.flatnonzero(bad)[:5].tolist()
        raise NumericsError(
            f"non-finite ranking scores in {context}: positions {idx} of "
            f"{arr.size} (values {[float(arr[i]) for i in idx]}); enable "
            "jax_debug_nans to locate the producing op"
        )
