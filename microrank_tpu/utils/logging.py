"""Structured logging (SURVEY.md §5 observability row).

The reference prints to stdout throughout (online_rca.py:151,172-174;
anormaly_detector.py:49,74-76). Here everything goes through stdlib
``logging`` under the ``microrank_tpu`` namespace; no print-as-API.
"""

from __future__ import annotations

import logging
import sys

_FORMAT = "%(asctime)s %(levelname)-7s %(name)s: %(message)s"
_configured = False


def get_logger(name: str = "microrank_tpu") -> logging.Logger:
    global _configured
    if not _configured:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT))
        root = logging.getLogger("microrank_tpu")
        if not root.handlers:
            root.addHandler(handler)
        root.setLevel(logging.INFO)
        _configured = True
    return logging.getLogger(name)


_warned: set = set()


def warn_once(logger: logging.Logger, key: str, msg: str, *args) -> None:
    """Per-process once-only warning (telemetry paths that would
    otherwise warn every window — e.g. conv-trace x device_checks)."""
    if key in _warned:
        return
    _warned.add(key)
    logger.warning(msg, *args)
