"""Tie-aware ranked-list agreement — the ONE comparator the bench's
full-window oracle gate and the multichip dryrun's sharded-vs-single
gate share (review finding: three bespoke copies with subtly different
tie rules can silently drift; tie semantics live here).

Two rankings from different compute paths (f32 device kernels, f64
oracle, sharded summation trees) may legally permute EXACT ties and
wobble scores by reassociation — but any non-tied positional difference
is a real disagreement. The rules:

* lengths (clamped to k) must match;
* scores must agree rank by rank within ``rtol``;
* an id mismatch at a rank is forgiven only when BOTH ids appear in the
  other list's top-k with a score tied to this rank's (a genuinely
  permuted tie) — membership alone would accept swapped non-tied
  rankings;
* with ``exempt_last`` (full truncated lists), the final kept rank is
  exempt from the membership rule: a near-tie straddling the top-k cut
  can legally swap an id across it (the score check above still binds).
"""

from __future__ import annotations

from typing import Sequence, Tuple


def scores_tied(a: float, b: float, rtol: float = 1e-3) -> bool:
    """THE tie comparator: two scores are an exact-tie-within-rounding
    when they agree within ``rtol`` relative tolerance (1e-12 floor for
    near-zero scores). Shared by :func:`tie_aware_topk_agreement`, the
    evaluation metrics (``evaluation.tie_aware_ranks``) and the
    scenario harness, so every tie rule in the repo is this one."""
    a, b = float(a), float(b)
    return abs(a - b) <= rtol * max(abs(a), abs(b), 1e-12)


def tie_aware_topk_agreement(
    ids_a: Sequence,
    scores_a: Sequence[float],
    ids_b: Sequence,
    scores_b: Sequence[float],
    k: int,
    rtol: float = 1e-3,
    exempt_last: bool = False,
) -> Tuple[bool, str]:
    """Returns (agree, reason); ``reason`` names the first failure."""
    n = min(k, len(ids_a), len(ids_b))
    if n < min(k, max(len(ids_a), len(ids_b))):
        return False, (
            f"length mismatch: {len(ids_a)} vs {len(ids_b)} entries "
            f"within top-{k}"
        )
    ids_a, ids_b = list(ids_a[:k]), list(ids_b[:k])
    for r in range(n):
        sa, sb = float(scores_a[r]), float(scores_b[r])
        if not scores_tied(sa, sb, rtol):
            return False, f"score mismatch at rank {r}: {sa} vs {sb}"
        if ids_a[r] == ids_b[r]:
            continue
        if exempt_last and r == n - 1:
            continue  # legal swap across the truncation cut
        if ids_a[r] not in ids_b or ids_b[r] not in ids_a:
            return False, (
                f"id mismatch at rank {r}: {ids_a[r]!r} vs {ids_b[r]!r}"
            )
        # Each swapped id's score in the OTHER list must tie this rank's.
        sb_of_a = float(scores_b[ids_b.index(ids_a[r])])
        sa_of_b = float(scores_a[ids_a.index(ids_b[r])])
        for cross in (sb_of_a, sa_of_b):
            if not scores_tied(cross, sa, rtol):
                return False, f"non-tied id swap at rank {r}"
    return True, "ok"
