"""Atomic file writes: tmp + fsync + rename, the crash-only contract.

A SIGKILL between ``open()`` and ``close()`` of a plain ``write_text``
leaves a torn file — half a JSON object where ``warmup_manifest.json``
or ``metrics.json`` used to be — and the NEXT process's warm start then
chokes on it (or worse, silently ignores it and cold-starts). Every
state file a restart reads back goes through this module instead:

1. write the full payload to ``<name>.tmp.<pid>`` in the SAME directory
   (``os.replace`` is only atomic within a filesystem);
2. flush + fsync the tmp file (the bytes are durable, not just cached);
3. ``os.replace`` onto the final name (atomic on POSIX: readers see the
   old complete file or the new complete file, never a mix);
4. best-effort fsync of the parent directory (the rename itself is
   durable across power loss, not just process death).

A crash at any point leaves either the old file intact (steps 1-3) or
the new file complete (after 3) — plus at most one stale ``.tmp.*``
the next writer overwrites. The chaos harness injects a kill between
steps 2 and 3 (seam passed via ``fault_seam``) to pin exactly this
property in tests and the CI ``chaos-smoke`` job.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Optional


def atomic_write_bytes(
    path, data: bytes, fault_seam: Optional[str] = None
) -> Path:
    """Atomically replace ``path`` with ``data`` (tmp+fsync+rename).

    ``fault_seam``: chaos injection point fired BETWEEN the durable tmp
    write and the rename — an injected fault here simulates a crash at
    the worst possible instant; the previous file must survive it.
    """
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.parent / f"{path.name}.tmp.{os.getpid()}"
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
    try:
        os.write(fd, data)
        os.fsync(fd)
    finally:
        os.close(fd)
    if fault_seam is not None:
        from ..chaos.faults import maybe_inject

        maybe_inject(fault_seam)  # may raise: tmp stays, target intact
    os.replace(tmp, path)
    _fsync_dir(path.parent)
    return path


def atomic_write_text(
    path, text: str, fault_seam: Optional[str] = None
) -> Path:
    return atomic_write_bytes(
        path, text.encode("utf-8"), fault_seam=fault_seam
    )


def atomic_write_json(
    path, obj, indent: int = 2, fault_seam: Optional[str] = None
) -> Path:
    return atomic_write_text(
        path, json.dumps(obj, indent=indent), fault_seam=fault_seam
    )


def _fsync_dir(dirpath) -> None:
    """Durability of the rename itself; best-effort (some filesystems
    refuse O_RDONLY directory fds)."""
    try:
        fd = os.open(dirpath, os.O_RDONLY)
    except OSError:  # pragma: no cover - exotic filesystems
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover
        pass
    finally:
        os.close(fd)
