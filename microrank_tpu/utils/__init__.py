from .atomic import (
    atomic_write_bytes,
    atomic_write_json,
    atomic_write_text,
)
from .logging import get_logger
from .profiling import StageTimings, trace_context

__all__ = [
    "atomic_write_bytes",
    "atomic_write_json",
    "atomic_write_text",
    "get_logger",
    "StageTimings",
    "trace_context",
]
