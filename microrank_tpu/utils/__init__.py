from .logging import get_logger
from .profiling import StageTimings, trace_context

__all__ = ["get_logger", "StageTimings", "trace_context"]
