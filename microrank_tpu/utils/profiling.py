"""Per-stage wall-clock profiling (SURVEY.md §5 tracing row).

The reference has no self-timing at all (its paper reports module latencies
measured externally, Table 7). Here every pipeline stage records into a
``StageTimings`` struct so each window result carries
ingest/detect/build/rank timings; ``jax.profiler`` trace export can be
layered on via ``trace_context`` for deep dives.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Dict, Iterator, Optional


class StageTimings:
    """Accumulates named stage durations (seconds)."""

    def __init__(self) -> None:
        self._acc: Dict[str, float] = defaultdict(float)
        self._counts: Dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._acc[name] += time.perf_counter() - t0
            self._counts[name] += 1

    def as_dict(self) -> Dict[str, float]:
        return {k: round(v, 6) for k, v in self._acc.items()}

    def total(self) -> float:
        return sum(self._acc.values())

    def merge(self, other: "StageTimings") -> None:
        for k, v in other._acc.items():
            self._acc[k] += v
            self._counts[k] += other._counts[k]


@contextlib.contextmanager
def trace_context(log_dir: Optional[str]) -> Iterator[None]:
    """Optionally wrap a region in a jax.profiler trace (Perfetto dump)."""
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield
