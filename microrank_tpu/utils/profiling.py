"""Per-stage wall-clock profiling (SURVEY.md §5 tracing row).

The reference has no self-timing at all (its paper reports module latencies
measured externally, Table 7). Here every pipeline stage records into a
``StageTimings`` struct so each window result carries
ingest/detect/build/rank timings — and every stage duration ALSO feeds
the process metrics registry (``obs.metrics.stage_seconds`` histogram,
labeled by stage), so ``cli stats`` / the ``--metrics-port`` endpoint see
cumulative stage distributions without touching the per-window records.
``jax.profiler`` trace export can be layered on via ``trace_context``
for deep dives.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Dict, Iterator, Optional


class StageTimings:
    """Accumulates named stage durations (seconds).

    ``ctx`` (an ``obs.spans.SpanContext``) pins every stage recorded
    through this instance to ONE trace — the per-window/per-request
    seam sets it once, and stages that complete later on other threads
    (async fetch workers, bulk joins) still attribute to the right
    trace instead of whatever window the ambient context points at by
    then.
    """

    def __init__(self, ctx=None) -> None:
        self._acc: Dict[str, float] = defaultdict(float)
        self._counts: Dict[str, int] = defaultdict(int)
        self.ctx = ctx

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        from ..obs.spans import get_tracer

        t0 = time.perf_counter()
        # The span wraps the same region the timer measures — one
        # choke-point seam, two outputs (histogram + span ring).
        with get_tracer().span(name, ctx=self.ctx):
            try:
                yield
            finally:
                dt = time.perf_counter() - t0
                self._acc[name] += dt
                self._counts[name] += 1
                # Mirror into the registry histogram (a locked list
                # update; ~1 us — noise next to any stage worth timing).
                from ..obs.metrics import stage_seconds

                stage_seconds().observe(dt, stage=name)

    def as_dict(self) -> Dict[str, float]:
        return {k: round(v, 6) for k, v in self._acc.items()}

    def total(self) -> float:
        return sum(self._acc.values())

    def merge(self, other: "StageTimings") -> None:
        for k, v in other._acc.items():
            self._acc[k] += v
            self._counts[k] += other._counts[k]


@contextlib.contextmanager
def trace_context(log_dir: Optional[str]) -> Iterator[None]:
    """Optionally wrap a region in a jax.profiler trace (Perfetto dump)."""
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield
