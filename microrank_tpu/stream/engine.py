"""The continuous RCA engine (``cli stream``): the paper made literal.

MicroRank is described as an always-on monitor — the anomaly detector
watches live traces and only wakes the PageRank/spectrum machinery when
a window deviates from SLO. The batch pipelines replay finished dumps
and the serve path answers explicit requests; this engine closes the
gap: an unbounded span source feeds an event-time windower
(stream.window), every CLOSED window runs the cheap detector against
ONLINE SLO baselines (stream.baseline), and only ABNORMAL windows pay
for graph build + device rank — the gated-dispatch counter staying
below the window counter is the design working.

Overlap: abnormal windows' host graph builds run on the build worker
pool (stream.pool) while THIS thread — the only one touching jax, the
program-order rule — dispatches the previous window's rank; during an
incident burst (consecutive abnormal windows, exactly when latency
matters) window N+1 builds while window N ranks. Healthy windows drain
the pipeline first so the incident lifecycle (stream.incidents)
observes windows strictly in order.

Dispatch (PR 5) goes through the shared router (dispatch/): abnormal
windows that queued up behind an in-flight dispatch and share a pad
bucket COALESCE into one vmapped program (the serve batcher's trick —
``microrank_stream_dispatches_total`` dropping below the ranked-window
count under a burst is the coalescing working), oversized windows route
to the sharded mesh path when one is configured, the next window's
staging double-buffers behind the current rank, and a warmup manifest
next to the persistent compile cache lets a restarted engine re-trace
its programs as cache reloads instead of ~1.7 s cold compiles.

Baseline poisoning guard: baselines update only on healthy windows and
freeze while any incident is open, so a fault's own latencies cannot
absorb into the SLO and mask the recovery.

Crash-only (chaos/): the engine's host state — baseline moments + P^2
markers, incident tracker, windower watermark + buffered open windows,
source cursor — checkpoints atomically to ``out_dir/state.ckpt`` at
every pipeline-drained window boundary and on the SIGTERM drain;
``cli stream --resume`` restores it, so a restart opens ZERO duplicate
incidents, re-enters no cold start, and re-ranks no finalized window.
Dispatch and build go through the unified retry policy (chaos.retry:
backoff + jitter + per-seam breaker), and every seam consults the
seeded FaultPlan (``--chaos PLAN.json``) — the chaos the paper injects
into the systems MicroRank watches, injected into MicroRank itself.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from pathlib import Path
from typing import Deque, List, Optional

import numpy as np

from ..config import MicroRankConfig
from ..pipeline.results import ResultSink, WindowResult
from ..utils.logging import get_logger
from ..utils.profiling import StageTimings
from .baseline import OnlineBaseline
from .incidents import (
    IncidentTracker,
    JsonlIncidentSink,
    WebhookIncidentSink,
)
from .pool import BuildWorkerPool
from .window import ClosedWindow, StreamWindower

INCIDENT_LOG_NAME = "incidents.jsonl"


@dataclass
class _PendingRank:
    """One abnormal window: build submitted, device rank pending."""

    closed: ClosedWindow
    result: WindowResult
    future: object              # -> (graph, op_names, kernel)
    trace: object = None        # _WindowTrace (span context + start)
    frame: object = None        # admitted span frame (warehouse tier)


@dataclass
class _WindowTrace:
    """The self-tracing handle of one window: the root span context its
    stages parent-link against, plus the processing start times the
    root ``window`` span is recorded from at finalize."""

    ctx: object
    start_us: int
    perf0: float


@dataclass
class StreamSummary:
    windows: int = 0
    ranked: int = 0
    clean: int = 0
    empty: int = 0
    skipped: int = 0
    warmup: int = 0
    spans: int = 0
    dispatches: int = 0
    late_spans: int = 0
    incidents_opened: int = 0
    incidents_resolved: int = 0
    results: List[WindowResult] = field(default_factory=list)


class _JournalIncidentSink:
    """Mirror incident transitions into the run journal."""

    def __init__(self, journal):
        self.journal = journal

    def emit(self, event: dict) -> None:
        self.journal.emit(
            event["event"],
            **{k: v for k, v in event.items() if k != "event"},
        )


class StreamEngine:
    """Drive one span source through windowing, gated RCA, incidents."""

    def __init__(
        self,
        config: MicroRankConfig,
        source,
        out_dir=None,
        normal_df=None,
        incident_sinks: Optional[List] = None,
        resume: bool = False,
        tracker=None,
        sched=None,
    ):
        self.config = config
        # Co-deploy: ``sched`` is the unified DeviceScheduler sharing
        # the device with serve/backfill. Every device touch (warmup,
        # dispatch, fetch) then runs as a thunk on ITS thread — the
        # engine thread keeps windowing, builds, and incident
        # lifecycle. Solo (sched=None) the engine owns the device
        # exactly as before.
        self.sched = sched
        sc = config.stream
        self.source = source
        self.log = get_logger("microrank_tpu.stream")
        self.out_dir = Path(out_dir) if out_dir is not None else None
        self._stop_requested = False
        self.windower = self._make_windower()
        if normal_df is None:
            normal_df = getattr(source, "normal", None)
        self._normal_df = normal_df     # kept for cold-reset re-seed
        # Tuned-policy resolution (scenarios.policy — the ONE seam all
        # lanes share): a persisted policy.json may supply the spectrum
        # method / kernel / pad policy for this workload profile;
        # explicit config overrides always win. Resolved BEFORE any
        # config consumer (router, backend programs) is built.
        from ..scenarios.policy import apply_tuned_policy

        self.config, self.policy_resolution = apply_tuned_policy(
            config, lane="stream", profile_frame=normal_df
        )
        config = self.config
        self.baseline = self._make_baseline()
        self.pool = BuildWorkerPool(
            sc.build_workers, name="mr-stream-build"
        )
        self.journal = None
        self.sink = None
        if self.out_dir is not None:
            self.sink = ResultSink(
                self.out_dir,
                overwrite_csv=config.compat.overwrite_results,
            )
            if config.runtime.telemetry:
                from ..obs import JOURNAL_NAME, RunJournal, set_current_journal

                self.journal = RunJournal(
                    self.out_dir / JOURNAL_NAME,
                    max_bytes=config.obs.journal_max_bytes,
                )
                set_current_journal(self.journal)
        if tracker is not None:
            # Injected lifecycle (the fleet worker's coordinator proxy):
            # incidents are a GLOBAL concern there, so no local sinks —
            # the coordinator owns incidents.jsonl/webhook/journal.
            self.tracker = tracker
        else:
            sinks = list(incident_sinks or [])
            if self.out_dir is not None:
                sinks.append(
                    JsonlIncidentSink(self.out_dir / INCIDENT_LOG_NAME)
                )
                if self.journal is not None:
                    sinks.append(_JournalIncidentSink(self.journal))
            if sc.webhook_url:
                sinks.append(
                    WebhookIncidentSink(
                        sc.webhook_url,
                        timeout=sc.webhook_timeout_seconds,
                        max_attempts=sc.webhook_retry_max,
                        max_queue=sc.webhook_queue,
                    )
                )
            self.tracker = IncidentTracker(
                top_k=sc.fingerprint_top_k,
                resolve_after=sc.resolve_after_windows,
                cooldown_windows=sc.cooldown_windows,
                jaccard=sc.fingerprint_jaccard,
                score_drift=sc.fingerprint_score_drift,
                sinks=sinks,
            )
        from ..dispatch import DispatchRouter

        self.router = DispatchRouter(config)
        self._pending: Deque[_PendingRank] = deque()
        self._warmed: dict = {}     # kernel -> occupancies dispatched
        # Production pad-bucket shapes dispatched this run, recorded
        # into the warmup manifest at shutdown (shape-faithful warmup):
        # (kernel, occupancy, leaf-shape tuple) sigs, deduped.
        self._shape_sigs: set = set()
        # Warm-start seam (RuntimeConfig.warm_start): the previous
        # ranked window's converged iteration state
        # (rank_backends.warm.WarmState), threaded into the next
        # overlapping window's rank while an incident is open. Dropped
        # on incident resolution (an all-healthy stream has nothing to
        # warm) and never checkpointed — a restart simply cold-starts
        # its first window, which is exactly crash-only semantics.
        self._warm_state = None
        # Delta-build seam (RuntimeConfig.delta_build): the previous
        # built window's per-trace build caches
        # (graph.build.DeltaBuildState), threaded through the build
        # pool so each overlapping window's graph assembles in O(changed
        # traces). Builds chain on the previous build's future (the
        # state handoff is strictly window-ordered even with a deep
        # pool); the state itself is INDEPENDENT of the incident
        # lifecycle — the guard chain inside build_window_graph_delta
        # (bounds/params/churn/integrity) is what invalidates it, and a
        # restart cold-builds its first window. Never checkpointed.
        self._delta_state = None
        self._build_chain = None
        # Trace-relative clock-skew registry (ingest.TraceClock),
        # lazily built on the first pre-admitted batch. Never
        # checkpointed: a restart re-learns first-seen times from the
        # resumed stream (worst case, one window of unclamped skew).
        self._trace_clock = None
        self._cache_dir = None
        self._cache_probe = None
        self.summary = StreamSummary()
        # Rank provenance (explain/): the most recent incident bundle,
        # held until the flight dump it cross-links into is written.
        self._last_bundle = None
        if config.explain.enabled:
            from ..explain import get_explain_store

            get_explain_store().configure(config.explain.store_windows)
        # Flight recorder: dumps the span ring + correlated journal
        # events + metrics snapshot on incident open (rate-limited).
        self.flight = None
        if self.out_dir is not None:
            from ..obs import FlightRecorder

            self.flight = FlightRecorder(
                self.out_dir, config.obs, journal=self.journal
            )
        # Trace warehouse (warehouse/): every sealed window lands in the
        # hot buffer at finalize and is flushed to warm segments at the
        # same pipeline-drained boundary that writes the checkpoint —
        # segment data BEFORE the checkpoint commit, so a crash between
        # them replays (and idempotently re-seals) rather than loses.
        self.warehouse = None
        if config.warehouse.enabled and self.out_dir is not None:
            from ..warehouse import TraceWarehouse

            self.warehouse = TraceWarehouse(
                self.out_dir,
                config.warehouse,
                truth=getattr(source, "fault_pod_ops", None),
            )
        # Crash-only durability (chaos.checkpoint): state.ckpt under the
        # run dir, written at every pipeline-drained window boundary.
        from ..chaos import CHECKPOINT_NAME

        self._ckpt_path = (
            self.out_dir / CHECKPOINT_NAME
            if self.out_dir is not None and sc.checkpoint
            else None
        )
        self.resumed = False
        if resume:
            self._restore_checkpoint()

    # ------------------------------------------------------ components
    def _make_windower(self) -> StreamWindower:
        sc = self.config.stream
        slide_us = (
            None
            if sc.slide_minutes is None
            else int(sc.slide_minutes * 60e6)
        )
        return StreamWindower(
            width_us=int(sc.window_minutes * 60e6),
            slide_us=slide_us,
            lateness_us=int(sc.allowed_lateness_seconds * 1e6),
        )

    def _make_baseline(self) -> OnlineBaseline:
        sc = self.config.stream
        baseline = OnlineBaseline(
            decay=sc.baseline_decay,
            slo_stat=self.config.detector.slo_stat,
            min_windows=sc.min_healthy_windows,
        )
        if self._normal_df is not None:
            baseline.seed(self._normal_df)
        return baseline

    # ------------------------------------------------------ durability
    def request_stop(self) -> None:
        """Ask the engine to drain and exit (the SIGTERM path): the run
        loop stops consuming the source at the next batch boundary,
        pending ranks drain, and a final checkpoint is written — a
        subsequent ``--resume`` continues the run."""
        self._stop_requested = True

    def _restore_checkpoint(self) -> None:
        """``--resume``: load + verify state.ckpt and overwrite the
        fresh components with the crashed run's state. Any defect —
        corrupt file, version/checksum mismatch, incompatible config,
        a source cursor whose partition assignment no longer matches —
        rejects the WHOLE checkpoint and the engine cold-starts, which
        is always safe. Rejection is genuinely whole: components
        restored before the defect was hit are rebuilt cold
        (``_cold_reset``) — the pre-ISSUE-11 code restored in place and
        a late source-cursor mismatch left a half-restored engine
        (restored baselines over a cold cursor)."""
        from ..chaos import CheckpointError, load_checkpoint
        from ..obs.metrics import record_checkpoint

        if self._ckpt_path is None or not self._ckpt_path.exists():
            if self._ckpt_path is not None:
                self.log.info(
                    "--resume: no checkpoint at %s; starting fresh",
                    self._ckpt_path,
                )
            return
        try:
            payload = load_checkpoint(self._ckpt_path)
            self.baseline.restore(payload["baseline"])
            self.tracker.restore(payload["tracker"])
            self.windower.restore(payload["windower"])
            src_state = payload.get("source")
            if src_state is not None and hasattr(
                self.source, "restore_state"
            ):
                self.source.restore_state(src_state)
            for k, v in payload.get("summary", {}).items():
                if hasattr(self.summary, k) and k != "results":
                    setattr(self.summary, k, v)
            if self.warehouse is not None:
                self.warehouse.restore_cursor(payload.get("warehouse"))
        except (CheckpointError, KeyError, TypeError, ValueError) as e:
            record_checkpoint("rejected")
            self._cold_reset()
            self.log.warning(
                "--resume: checkpoint rejected (%s); cold start", e
            )
            return
        self.resumed = True
        record_checkpoint("restore")
        self.log.info(
            "resumed from %s: %d windows done, %d open incident(s), "
            "watermark at window %d",
            self._ckpt_path, self.summary.windows,
            len(self.tracker.open_incidents()), self.windower._next,
        )

    def _cold_reset(self) -> None:
        """Discard every partially-restored component: fresh windower,
        fresh (re-seeded) baseline, lifecycle counters and source
        cursor back to zero — the rejected checkpoint leaves NO trace,
        so cold start actually means cold."""
        self.windower = self._make_windower()
        self.baseline = self._make_baseline()
        reset = getattr(self.tracker, "reset", None)
        if callable(reset):
            reset()
        reset_cursor = getattr(self.source, "reset_cursor", None)
        if callable(reset_cursor):
            reset_cursor()
        if self.warehouse is not None:
            self.warehouse.reset_hot()
        self.summary = StreamSummary()

    def _checkpoint(self) -> None:
        """Write state.ckpt — only at a drained boundary (no pending
        ranks: every window the watermark sealed has been finalized, so
        the captured windower/source cursors mark nothing as done that
        a crash could lose). The warehouse flushes FIRST (segment data
        before the checkpoint commit): if the seal crashes, the
        checkpoint write is skipped too, so a resume replays the same
        windows and the deterministic segment names make the re-seal
        idempotent — exactly-once, never lost."""
        if self._pending:
            return
        from ..chaos import InjectedFault, save_checkpoint
        from ..obs.metrics import record_checkpoint

        if self.warehouse is not None:
            try:
                self.warehouse.flush()
            except InjectedFault:
                record_checkpoint("crash_injected")
                self.log.warning(
                    "chaos: warehouse seal crashed between segment "
                    "flush and manifest; checkpoint skipped — the "
                    "previous checkpoint stands and resume re-seals"
                )
                return
            except OSError as e:
                self.log.warning(
                    "warehouse flush failed (%s); checkpoint skipped "
                    "so the hot windows stay replayable", e
                )
                return
        if self._ckpt_path is None:
            return

        src_state = None
        ckpt_fn = getattr(self.source, "checkpoint_state", None)
        if callable(ckpt_fn):
            src_state = ckpt_fn()
        payload = {
            "baseline": self.baseline.to_state(),
            "tracker": self.tracker.to_state(),
            "windower": self.windower.to_state(),
            "source": src_state,
            "summary": {
                k: getattr(self.summary, k)
                for k in (
                    "windows", "ranked", "clean", "empty", "skipped",
                    "warmup", "spans", "dispatches", "late_spans",
                    "incidents_opened", "incidents_resolved",
                )
            },
        }
        if self.warehouse is not None:
            payload["warehouse"] = self.warehouse.cursor_state()
        try:
            save_checkpoint(self._ckpt_path, payload)
            record_checkpoint("write")
        except InjectedFault:
            # The chaos seam killed the write between tmp and rename:
            # exactly the crash the atomic protocol survives — the
            # previous checkpoint is intact and stays authoritative.
            record_checkpoint("crash_injected")
            self.log.warning(
                "chaos: checkpoint write crashed between tmp and "
                "rename; previous checkpoint stands"
            )
        except OSError as e:
            self.log.warning("checkpoint write failed: %s", e)

    # ------------------------------------------------------------------ run
    @property
    def queue_depth(self) -> int:
        """Pipelined windows in flight (build submitted, rank pending).
        Read by the fleet heartbeat thread for the per-host telemetry
        breakdown: a bare ``len`` on a deque only the engine thread
        mutates — a momentarily stale reading is fine for a gauge."""
        return len(self._pending)

    def run(self) -> StreamSummary:
        from ..analysis.mrsan import configure_sanitizers
        from ..chaos import configure_chaos, set_chaos_journal
        from ..ingest import configure_quarantine
        from ..obs import configure_tracer
        from ..obs.metrics import ensure_catalog
        from ..utils.guards import claim_device_owner

        ensure_catalog()
        configure_tracer(self.config.obs)  # fresh span ring per run
        configure_sanitizers(self.config)  # mrsan arm/disarm + reset
        configure_chaos(self.config)       # fault plan arm/disarm
        set_chaos_journal(self.journal)    # fault_injected -> journal
        # Dead-letter store next to the run outputs: every span row
        # admission refuses lands in quarantine.jsonl with a reason.
        configure_quarantine(
            self.config.ingest, default_dir=self.out_dir
        )
        # The engine thread is the sole jax toucher on the stream path
        # (program-order rule); builds go to the pool, sinks stay host.
        # Co-deployed, the unified DeviceScheduler owns the device and
        # every dispatch routes through _on_device instead.
        if self.sched is None:
            claim_device_owner("stream-engine")
        self._warm_start()
        sc = self.config.stream
        run_t0 = time.monotonic()
        if self.journal is not None:
            self.journal.run_start(
                pipeline="stream",
                kernel=self.config.runtime.kernel,
                pad_policy=self.config.runtime.pad_policy,
                window_minutes=sc.window_minutes,
                slide_minutes=sc.slide_minutes,
                lateness_seconds=sc.allowed_lateness_seconds,
                seeded=self.baseline.seeded,
                resumed=self.resumed,
            )
            # Journal evidence that the tuned policy was (or was not)
            # consulted — the scenario-smoke CI job greps this on the
            # warm-restart half.
            self.journal.emit(
                "policy", **self.policy_resolution.journal()
            )
        try:
            done = False
            for batch in self.source:
                if self._stop_requested:
                    done = True
                    break
                # Pre-windowing admission gate: rows whose event time
                # cannot exist (uncoercible timestamps, missing ids,
                # garbage durations, hopeless clock skew) quarantine
                # HERE — window assignment is undefined for them, so
                # they must never reach the windower.
                batch = self._pre_admit(batch)
                for w in self.windower.add(batch):
                    self._process(w)
                    if self._max_reached() or self._stop_requested:
                        done = True
                        break
                if done:
                    break
            if not done:
                for w in self.windower.flush():
                    self._process(w)
                    if self._max_reached():
                        break
            self._drain_all()
        finally:
            self.pool.shutdown()
            self._record_manifest()
            self.summary.late_spans = self.windower.dropped_late
            # Final durable state: on a SIGTERM drain (or a clean end)
            # the checkpoint is the run's resumable truth. An exception
            # mid-flight may leave pending ranks — _checkpoint refuses
            # that state and the last boundary checkpoint stands.
            self._checkpoint()
            if self._stop_requested and self.journal is not None:
                self.journal.emit("sigterm_drain", resumable=True)
            self._flush_webhooks()
            if self.journal is not None:
                elapsed = max(1e-9, time.monotonic() - run_t0)
                self.journal.run_end(
                    windows=self.summary.windows,
                    ranked=self.summary.ranked,
                    dispatches=self.summary.dispatches,
                    spans=self.summary.spans,
                    spans_per_second=round(
                        self.summary.spans / elapsed, 2
                    ),
                    late_spans=self.summary.late_spans,
                    incidents_opened=self.summary.incidents_opened,
                    incidents_resolved=self.summary.incidents_resolved,
                )
            set_chaos_journal(None)
            if (
                self.out_dir is not None
                and self.config.runtime.telemetry
            ):
                from ..obs import get_registry

                get_registry().write_snapshot(self.out_dir)
        return self.summary

    def _on_device(self, fn, lane=None):
        """Run a device-touching thunk where the device lives: inline
        when this engine owns it (solo), or on the unified scheduler's
        thread when co-deployed. The lane defaults by incident state —
        an open incident rides the hot lane ahead of interactive serve;
        a healthy stream shares the serve lane; both outrank backfill."""
        if self.sched is None:
            return fn()
        from ..sched import LANE_INCIDENT, LANE_SERVE

        if lane is None:
            lane = (
                LANE_INCIDENT
                if self.tracker.open_incidents()
                else LANE_SERVE
            )
        return self.sched.run_on(
            lane, self.config.sched.stream_tenant, fn
        )

    def _flush_webhooks(self) -> None:
        """Drain-time best effort for webhook sinks' retry queues: one
        flush pass per sink (entries still failing stay dropped-on-
        restart — the checkpoint does not carry undelivered alerts)."""
        for sink in self.tracker.sinks:
            flush = getattr(sink, "flush", None)
            if callable(flush):
                try:
                    flush()
                except Exception:  # noqa: BLE001 - drain must complete
                    pass

    def _max_reached(self) -> bool:
        mw = self.config.stream.max_windows
        return bool(mw) and self.summary.windows >= mw

    # --------------------------------------------------- compile cache
    def _warm_start(self) -> None:
        """Wire the persistent compile cache and, on a warm restart
        (a previous stream process left its warmup manifest), re-trace
        the recorded program occupancies — every compile hits the
        on-disk cache, so the first abnormal burst after a redeploy
        pays milliseconds instead of the ~1.7 s cold compile."""
        from ..dispatch import (
            CompileCacheProbe,
            configure_compile_cache,
            manifest_occupancies,
            warm_manifest_shapes,
            warm_occupancies,
        )

        self._cache_dir = configure_compile_cache(self.config.runtime)
        self._cache_probe = CompileCacheProbe(self._cache_dir)
        if (
            not self.config.dispatch.warmup_manifest
            or self.config.runtime.device_checks
        ):
            return
        occs = manifest_occupancies(self._cache_dir, "stream")
        if not occs:
            return
        from ..obs.metrics import record_compile_cache

        record_compile_cache("warm_start")
        t0 = time.monotonic()
        self._on_device(lambda: warm_occupancies(
            self.router, self.config, occs, probe=self._cache_probe
        ))
        shaped = 0
        if self.config.sched.shape_warmup:
            # Shape-faithful warmup: re-trace the exact production pad
            # buckets (kernel, occupancy, leaf shapes) the previous
            # process dispatched, so the first real window after a
            # restart hits an already-traced program — not just the
            # synthetic default occupancies.
            shaped = self._on_device(lambda: warm_manifest_shapes(
                self.router, self.config, self._cache_dir, "stream",
                probe=self._cache_probe,
            ))
        self.log.info(
            "warm restart: re-traced %d manifest occupancies + %d "
            "production shapes in %.2fs (compile cache %d hit / %d miss)",
            len(occs), shaped, time.monotonic() - t0,
            self._cache_probe.hits, self._cache_probe.misses,
        )

    def _record_manifest(self) -> None:
        from ..dispatch import record_manifest_entry

        if not self.config.dispatch.warmup_manifest:
            return
        shapes_by_kernel: dict = {}
        if self.config.sched.shape_warmup:
            for kernel, occ, leaves in sorted(self._shape_sigs):
                shapes_by_kernel.setdefault(kernel, []).append(
                    {"occupancy": occ,
                     "leaves": [list(s) for s in leaves]}
                )
        for kernel, occs in self._warmed.items():
            record_manifest_entry(
                self._cache_dir, "stream", kernel, sorted(occs),
                shapes=shapes_by_kernel.get(kernel),
                max_shapes=self.config.sched.max_shapes,
            )

    # -------------------------------------------------------- per window
    def _pre_admit(self, batch):
        """Source-boundary admission (ingest.pre_admit_frame): reject
        rows the windower cannot even place, coerce the survivors'
        dtypes, and repair trace-relative clock skew against the
        bounded first-seen registry. The window-relative ladder runs
        in :meth:`_process` on the closed window."""
        if batch is None or len(batch) == 0:
            return batch
        if not self.config.ingest.enabled:
            return batch
        from ..ingest import TraceClock, pre_admit_frame

        if self._trace_clock is None:
            self._trace_clock = TraceClock()
        clean, rejected = pre_admit_frame(
            batch, self.config.ingest, source="stream",
            trace_clock=self._trace_clock,
        )
        if rejected and self.journal is not None:
            self.journal.emit(
                "ingest", stage="source", rejected=rejected
            )
        return clean

    def _process(self, closed: ClosedWindow) -> None:
        from ..obs.spans import get_tracer

        tracer = get_tracer()
        trace = _WindowTrace(
            ctx=tracer.new_trace(f"win-{closed.start}"),
            start_us=int(time.time() * 1e6),
            perf0=time.monotonic(),
        )
        self.summary.windows += 1
        self.summary.spans += closed.n_spans
        result = WindowResult(
            start=closed.start, end=closed.end, anomaly=False
        )
        if closed.n_spans == 0:
            self._drain_all()
            result.skipped_reason = "empty_window"
            self._finalize(result, "empty", trace=trace)
            return
        # Window-relative admission ladder: duplicates, orphans,
        # clock-skew normalization and the resource budgets, on the
        # CLOSED window (the pre-windowing gate already rejected rows
        # without a placeable event time). A window mostly made of
        # garbage is refused WHOLE (low_admission): it must neither
        # retrain the baseline nor advance the incident lifecycle.
        frame = closed.frame
        if self.config.ingest.enabled:
            from ..ingest import admit_frame

            timings0 = StageTimings(ctx=trace.ctx)
            with timings0.stage("admit"):
                adm = admit_frame(
                    frame,
                    self.config.ingest,
                    source="stream",
                    window_bounds=(closed.start, closed.end),
                    # Vocab-growth guard reference: what the online
                    # baseline already knows (armed once detection is).
                    known_ops=(
                        self.baseline.known_ops()
                        if self.baseline.ready
                        else None
                    ),
                )
            frame = adm.frame
            result.ingest_rejected = adm.n_rejected
            result.degraded_input = adm.degraded
            result.timings.update(timings0.as_dict())
            if adm.degraded and self.journal is not None:
                self.journal.emit(
                    "ingest",
                    stage="window",
                    window_start=result.start,
                    **adm.journal_fields(),
                )
            if (
                adm.admission_ratio
                < self.config.ingest.min_admission_ratio
            ):
                self._drain_all()
                self.log.warning(
                    "window %s: admission ratio %.2f below %.2f — "
                    "refusing the window whole (baseline and "
                    "incident lifecycle untouched)",
                    result.start, adm.admission_ratio,
                    self.config.ingest.min_admission_ratio,
                )
                result.skipped_reason = "low_admission"
                self._finalize(result, "skipped", trace=trace)
                return
            if len(frame) == 0:
                self._drain_all()
                result.skipped_reason = "empty_window"
                self._finalize(result, "empty", trace=trace)
                return
        if not self.baseline.ready:
            # Cold start: feed the baseline, don't detect yet. The
            # CLEAN subset feeds it — quarantined rows never train.
            self._drain_all()
            self.baseline.update(frame)
            result.n_traces = int(frame["traceID"].nunique())
            result.skipped_reason = "baseline_warmup"
            self._finalize(result, "warmup", frame=frame, trace=trace)
            return
        from ..detect import detect_partition

        timings = StageTimings(ctx=trace.ctx)
        with timings.stage("detect"):
            vocab, slo = self.baseline.snapshot()
            flag, nrm, abn = detect_partition(
                self.config, vocab, slo, frame
            )
        result.timings.update(timings.as_dict())
        result.anomaly = bool(flag)
        result.n_normal, result.n_abnormal = len(nrm), len(abn)
        result.n_traces = len(nrm) + len(abn)
        if not flag:
            self._drain_all()
            self._finalize(
                result, "clean", frame=frame, trace=trace
            )
            return
        if not nrm or not abn:
            self._drain_all()
            result.skipped_reason = "degenerate_partition"
            self._finalize(result, "skipped", trace=trace)
            return
        # Gate open: host build on the pool; rank on THIS thread when it
        # lands — consecutive abnormal windows overlap build(N+1) with
        # rank(N). Healthy windows drained the pipe above, so lifecycle
        # observation order == window order. The CLEAN subset builds —
        # quarantined rows never stage (degraded-but-correct ranking).
        # attach: the pool captures the submitter's ambient context, so
        # the off-thread build parent-links to THIS window's trace.
        with tracer.attach(trace.ctx):
            fut = self.pool.submit(
                self._prepare, frame, nrm, abn,
                closed.start_us, closed.end_us, self._build_chain,
            )
        if self.config.runtime.delta_build:
            # Chain: the NEXT delta build waits on this one's future (a
            # pure barrier — the state handoff rides self._delta_state,
            # written on the worker before the future resolves).
            self._build_chain = fut
        self._pending.append(
            _PendingRank(closed, result, fut, trace, frame=frame)
        )
        while len(self._pending) >= max(
            1, self.config.stream.pipeline_windows
        ):
            self._rank_head()

    # ---------------------------------------------------------- ranking
    def _prepare(
        self, frame, nrm, abn, start_us=None, end_us=None, prev_build=None
    ):
        """The build-pool unit, under the unified retry policy: a
        build-pool exception (incl. the ``build`` chaos seam) retries
        with backoff ON the worker before it can surface as a skipped
        window — a transient build fault costs latency, not a window."""
        from ..chaos import BUILD_POLICY, retry_call

        if prev_build is not None:
            # Delta chain barrier: the previous window's build must have
            # published its DeltaBuildState before this one reads it.
            # Its FAILURE is not ours — the stale state is still a valid
            # delta base (the bounds/integrity guards absorb a larger
            # slide), and the failed window surfaces on its own turn.
            try:
                prev_build.result()
            except Exception:  # noqa: BLE001 - see above
                pass

        return retry_call(
            "build",
            lambda: self._prepare_impl(frame, nrm, abn, start_us, end_us),
            policy=BUILD_POLICY,
        )

    def _prepare_impl(self, frame, nrm, abn, start_us=None, end_us=None):
        """Prepared graph plus (when the explain subsystem is armed)
        the coverage-column retention context the incident bundle joins
        device attributions against. Uniform 4-tuple so the rank path
        never branches on the config."""
        from ..chaos import maybe_inject
        from ..rank_backends.jax_tpu import (
            prepare_window_graph,
            prepare_window_graph_delta,
            prepare_window_graph_explained,
        )

        maybe_inject("build")
        rt = self.config.runtime
        if rt.delta_build:
            # Incremental lane: thread the previous window's build
            # caches; the returned state is published BEFORE the future
            # resolves (the submit site chains the next build on it).
            graph, op_names, kernel, ectx, state, route, _reason = (
                prepare_window_graph_delta(
                    frame, nrm, abn, self.config,
                    state=self._delta_state,
                    start_us=start_us, end_us=end_us,
                )
            )
            self._delta_state = state
            if not (
                self.config.explain.enabled
                or rt.warm_start
                or rt.fused_pair
            ):
                ectx = None
            return graph, op_names, kernel, ectx
        if self.config.explain.enabled or rt.warm_start or rt.fused_pair:
            # The retention context doubles as the warm-start seam's
            # column identity map (rank_backends.warm maps rv across
            # the window delta by representative trace id).
            return prepare_window_graph_explained(
                frame, nrm, abn, self.config
            )
        graph, op_names, kernel = prepare_window_graph(
            frame, nrm, abn, self.config
        )
        return graph, op_names, kernel, None

    def _drain_all(self) -> None:
        while self._pending:
            self._rank_head()

    def _rank_head(self) -> None:
        head = self._pending.popleft()
        try:
            graph, op_names, kernel, ectx = head.future.result()
        except Exception as e:  # noqa: BLE001 - a bad window must not
            # kill the engine; the window records the failure and the
            # stream moves on.
            self.log.error(
                "window %s: graph build failed: %s", head.result.start, e
            )
            head.result.skipped_reason = f"build_failed: {e}"
            self._finalize(head.result, "skipped", trace=head.trace)
            return
        warm = bool(
            (
                self.config.runtime.warm_start
                or self.config.runtime.fused_pair
            )
            and not self.config.runtime.device_checks
            and ectx is not None
        )
        group = [(head, graph, op_names, ectx)]
        if not self.config.runtime.device_checks and not warm:
            group.extend(self._coalesce_burst(graph, kernel))
        for p, _, _, _ in group:
            p.result.queue_depth = len(self._pending)
        try:
            if warm:
                # Warm-start single-window dispatch: seeds from the
                # previous ranked window's converged state while an
                # incident is open and captures this window's state.
                self._dispatch_rank_warm(
                    head, graph, op_names, kernel, ectx
                )
            elif self.config.runtime.device_checks and len(group) == 1:
                # checkify programs have no batched twin: the checked
                # path keeps the single-window dispatch.
                self._dispatch_rank(
                    head.result, graph, op_names, kernel,
                    trace=head.trace,
                )
            else:
                self._dispatch_group(group, kernel)
        except Exception as e:  # noqa: BLE001 - same containment rule
            for p, _, _, _ in group:
                self.log.error(
                    "window %s: device rank failed: %s", p.result.start, e
                )
                p.result.skipped_reason = f"rank_failed: {e}"
                p.result.ranking = []
                self._finalize(p.result, "skipped", trace=p.trace)
            return
        for p, g, names, ec in group:
            self._finalize(
                p.result, "ranked", frame=p.frame, trace=p.trace,
                explain_src=(g, names, p.result.kernel or kernel, ec),
            )

    def _coalesce_burst(self, head_graph, kernel: str):
        """Abnormal-burst micro-batching: pending windows whose builds
        land in the SAME pad bucket as the head coalesce into its
        dispatch (a contiguous prefix of the FIFO, so the incident
        lifecycle still observes windows strictly in order). Waiting on
        the next build costs nothing the stream would not pay anyway —
        it was about to rank that window next — and buys one dispatch
        for the whole burst."""
        from ..dispatch import bucket_key

        extra = []
        cap = max(1, int(self.config.dispatch.coalesce_windows))
        key = bucket_key(head_graph, kernel)
        while self._pending and len(extra) + 1 < cap:
            nxt = self._pending[0]
            try:
                g2, n2, k2, e2 = nxt.future.result()
            except Exception:  # noqa: BLE001 - its failure surfaces on
                # its own _rank_head turn (futures cache exceptions).
                break
            if bucket_key(g2, k2) != key:
                break
            self._pending.popleft()
            extra.append((nxt, g2, n2, e2))
        return extra

    def _dispatch_group(self, group, kernel: str) -> None:
        """One router dispatch for a coalesced same-bucket group; the
        next pending window's staging double-buffers behind it. The
        router's staging/dispatch/fetch spans attribute to the HEAD
        window's trace (one dispatch serves the whole burst — the
        coalesced members' traces show build-but-no-dispatch, which is
        exactly what happened to them)."""
        from ..obs.metrics import record_stream_dispatch
        from ..obs.spans import get_tracer
        from ..utils.guards import contract_checks

        rt = self.config.runtime
        conv = bool(rt.convergence_trace)
        graphs = [g for _, g, _, _ in group]
        next_batch = None
        if self.config.dispatch.double_buffer and self._pending:
            nxt = self._pending[0]
            if nxt.future.done():
                try:
                    g2, _, k2, _ = nxt.future.result()
                    next_batch = ([g2], k2)
                except Exception:  # noqa: BLE001 - handled on its turn
                    pass
        head_trace = group[0][0].trace
        t0 = time.monotonic()

        def _attempt():
            """One dispatch attempt under the unified retry policy:
            the ``dispatch`` seam fires before the router (injected
            failure/latency), the ``fetch`` seam after it (a fired
            ``nan`` action poisons THIS attempt — the retry refetches
            clean, so validation never sees the poison)."""
            from ..chaos import InjectedFault, maybe_inject

            maybe_inject("dispatch")
            with contract_checks(rt.validate_numerics):
                o, i = self.router.rank_batch(
                    graphs, kernel, conv_trace=conv, next_batch=next_batch
                )
            if maybe_inject("fetch") is not None:
                raise InjectedFault("fetch", "nan")
            return o, i

        from ..chaos.retry import STREAM_DISPATCH_POLICY, retry_call

        def _ranked():
            # The tracer attach rides inside the thunk so the dispatch
            # spans land on the head window's trace even when the thunk
            # runs on the unified scheduler's thread (co-deploy).
            with get_tracer().attach(
                head_trace.ctx if head_trace is not None else None
            ):
                return retry_call(
                    "stream_dispatch", _attempt,
                    policy=STREAM_DISPATCH_POLICY,
                )

        outs, info = self._on_device(_ranked)
        record_stream_dispatch()
        self.summary.dispatches += 1
        if (
            self.config.sched.shape_warmup
            and self.config.dispatch.warmup_manifest
        ):
            from ..dispatch import bucket_key

            self._shape_sigs.add((
                info.kernel,
                len(group),
                bucket_key(graphs[0], info.kernel)[1:],
            ))
        occs = self._warmed.setdefault(info.kernel, set())
        if len(group) not in occs and self._cache_probe is not None:
            # First dispatch at this (kernel, occupancy) — the only kind
            # that can have compiled: classify it as a persistent-cache
            # hit (warm restart, program reloaded) or miss (cold).
            self._cache_probe.observe()
        occs.add(len(group))
        batch_ms = (time.monotonic() - t0) * 1e3
        ti, ts, nv = outs[:3]
        for b, (p, g_b, op_names, _) in enumerate(group):
            n = int(nv[b])
            names = [op_names[int(i)] for i in ti[b][:n]]
            scores = [float(s) for s in ts[b][:n]]
            if rt.validate_numerics:
                from ..utils.guards import assert_finite_scores

                assert_finite_scores(scores, "stream window")
            p.result.ranking = list(zip(names, scores))
            p.result.kernel = info.kernel
            p.result.route = info.route
            p.result.batch_windows = len(group)
            from ..graph.build import kind_dedup_ratio

            p.result.kind_dedup = kind_dedup_ratio(g_b)
            p.result.timings["rank_ms"] = round(batch_ms / len(group), 3)
            if conv:
                from ..obs.metrics import record_convergence

                res = np.asarray(
                    outs[3][b],
                    dtype=np.float64,  # mrlint: disable=R2(host-side summary of an already-fetched trace; never re-enters a jnp expression)
                )
                n_it = int(outs[4][b])
                final = (
                    float(res[:, n_it - 1].max()) if n_it else float("nan")
                )
                record_convergence(info.kernel, n_it, final)
                p.result.apply_convergence(
                    {"iterations": n_it, "final_residual": final}
                )

    def _dispatch_rank(
        self, result, graph, op_names, kernel, trace=None
    ) -> None:
        """Single-window checked dispatch (RuntimeConfig.device_checks
        — the checkify program has no batched/router twin)."""
        import jax

        from ..obs.metrics import record_stream_dispatch
        from ..obs.spans import get_tracer
        from ..rank_backends.blob import stage_rank_window
        from ..utils.guards import contract_checks

        tracer = get_tracer()
        rt = self.config.runtime
        # device_checks composes with the convergence trace since the
        # checkify program gained its residual-traced twin.
        conv = bool(rt.convergence_trace)
        t0 = time.monotonic()

        def _attempt():
            from ..chaos import maybe_inject

            maybe_inject("dispatch")
            with tracer.span(
                "device_dispatch", service="stream", kernel=kernel,
                checked=True,
            ):
                with contract_checks(rt.validate_numerics):
                    staged = stage_rank_window(
                        graph,
                        self.config.pagerank,
                        self.config.spectrum,
                        kernel,
                        rt.blob_staging,
                        checked=rt.device_checks,
                        conv_trace=conv,
                    )
            with tracer.span("result_fetch", service="stream"):
                return jax.device_get(staged)

        from ..chaos.retry import STREAM_DISPATCH_POLICY, retry_call

        def _ranked():
            with tracer.attach(trace.ctx if trace is not None else None):
                return retry_call(
                    "stream_dispatch", _attempt,
                    policy=STREAM_DISPATCH_POLICY,
                )

        out = self._on_device(_ranked)
        record_stream_dispatch()
        self.summary.dispatches += 1
        top_idx, top_scores, n_valid = out[:3]
        n = int(n_valid)
        names = [op_names[int(i)] for i in top_idx[:n]]
        scores = [float(s) for s in top_scores[:n]]
        if rt.validate_numerics:
            from ..utils.guards import assert_finite_scores

            assert_finite_scores(scores, "stream window")
        result.ranking = list(zip(names, scores))
        result.kernel = kernel
        result.timings["rank_ms"] = round(
            (time.monotonic() - t0) * 1e3, 3
        )
        if conv:
            from ..obs.metrics import record_convergence

            res = np.asarray(
                out[3],
                dtype=np.float64,  # mrlint: disable=R2(host-side summary of an already-fetched trace; never re-enters a jnp expression)
            )
            n_it = int(out[4])
            final = (
                float(res[:, n_it - 1].max()) if n_it else float("nan")
            )
            record_convergence(kernel, n_it, final)
            result.apply_convergence(
                {"iterations": n_it, "final_residual": final}
            )

    def _dispatch_rank_warm(
        self, head, graph, op_names, kernel, ectx
    ) -> None:
        """Warm-start single-window dispatch (RuntimeConfig.warm_start):
        rank through the warm program (rank_window_warm_device), seeding
        the iteration from the previous ranked window's converged state
        while an incident is open, and capture this window's state for
        the next — the converged vectors ride the same result fetch, so
        the seam adds no extra sync. With pagerank.tol configured the
        journal's rank_iterations visibly drops window over window."""
        import jax

        from ..obs.metrics import record_stream_dispatch
        from ..obs.spans import get_tracer
        from ..rank_backends.jax_tpu import rank_window_warm_device
        from ..rank_backends.warm import capture_warm_state, map_warm_state
        from ..utils.guards import contract_checks

        tracer = get_tracer()
        rt = self.config.runtime
        result = head.result
        init = None
        if self._warm_state is not None and self.tracker.open_incidents():
            init = map_warm_state(self._warm_state, op_names, ectx, graph)
        t0 = time.monotonic()
        fused = bool(rt.fused_pair)

        def _attempt():
            from ..chaos import InjectedFault, maybe_inject

            maybe_inject("dispatch")
            if fused:
                # Fused pair program through the router: blob staging +
                # both solves + epilogue in ONE dispatch; the router
                # owns the witness/route telemetry ("dispatch.fused").
                with contract_checks(rt.validate_numerics):
                    out, _info = self.router.rank_fused(
                        graph, kernel, init
                    )
                if maybe_inject("fetch") is not None:
                    raise InjectedFault("fetch", "nan")
                return out
            with tracer.span(
                "device_dispatch", service="stream", kernel=kernel,
                warm=init is not None,
            ):
                with contract_checks(rt.validate_numerics):
                    staged = rank_window_warm_device(
                        jax.device_put(graph),
                        init,
                        self.config.pagerank,
                        self.config.spectrum,
                        kernel,
                    )
            with tracer.span("result_fetch", service="stream"):
                out = jax.device_get(staged)
            if maybe_inject("fetch") is not None:
                raise InjectedFault("fetch", "nan")
            return out

        from ..chaos.retry import STREAM_DISPATCH_POLICY, retry_call
        from ..sched import LANE_INCIDENT

        def _ranked():
            with tracer.attach(
                head.trace.ctx if head.trace is not None else None
            ):
                return retry_call(
                    "stream_dispatch", _attempt,
                    policy=STREAM_DISPATCH_POLICY,
                )

        # Warm-start only seeds while an incident is open — this IS the
        # hot path, so pin the incident lane rather than re-deriving it.
        out = self._on_device(
            _ranked,
            lane=LANE_INCIDENT if init is not None else None,
        )
        record_stream_dispatch()
        self.summary.dispatches += 1
        top_idx, top_scores, n_valid = out[:3]
        n = int(n_valid)
        names = [op_names[int(i)] for i in top_idx[:n]]
        scores = [float(s) for s in top_scores[:n]]
        if rt.validate_numerics:
            from ..utils.guards import assert_finite_scores

            assert_finite_scores(scores, "stream window (warm)")
        result.ranking = list(zip(names, scores))
        result.kernel = kernel
        if fused:
            result.route = "fused" if init is not None else "fused_cold"
        else:
            result.route = "warm" if init is not None else "warm_cold"
        result.batch_windows = 1
        from ..graph.build import kind_dedup_ratio

        result.kind_dedup = kind_dedup_ratio(graph)
        result.timings["rank_ms"] = round(
            (time.monotonic() - t0) * 1e3, 3
        )
        from ..obs.metrics import record_convergence

        res = np.asarray(
            out[3],
            dtype=np.float64,  # mrlint: disable=R2(host-side summary of an already-fetched trace; never re-enters a jnp expression)
        )
        n_it = int(out[4])
        final = float(res[:, n_it - 1].max()) if n_it else float("nan")
        record_convergence(kernel, n_it, final)
        result.apply_convergence(
            {"iterations": n_it, "final_residual": final}
        )
        self._warm_state = capture_warm_state(op_names, ectx, out[5:9])

    def _explain_incident(self, result, explain_src) -> dict:
        """Materialize the incident-opening window's explain bundle
        (ON the engine thread — the device-owner rule): one explained
        dispatch over the retained graph, bundle written under
        out_dir/explain/, published to the /explainz store, mirrored
        into the journal. Returns the open-event enrichment fields."""
        import jax

        from ..explain import build_bundle, get_explain_store
        from ..obs.metrics import record_explain
        from ..obs.spans import get_tracer
        from ..rank_backends.blob import stage_rank_window

        graph, op_names, kernel, ectx = explain_src
        ex = self.config.explain

        def _explained():
            with get_tracer().span(
                "explain", service="stream", kernel=kernel
            ):
                return jax.device_get(
                    stage_rank_window(
                        graph,
                        self.config.pagerank,
                        self.config.spectrum,
                        kernel,
                        self.config.runtime.blob_staging,
                        explain=ex,
                    )
                )

        from ..sched import LANE_INCIDENT

        # An explain dispatch only happens on incident open — hot lane.
        outs = self._on_device(_explained, lane=LANE_INCIDENT)
        bundle = build_bundle(
            outs,
            op_names,
            ectx,
            method=self.config.spectrum.method,
            kernel=kernel,
            window={"start": result.start, "end": result.end},
            trigger="incident",
        )
        record_explain("incident")
        get_explain_store().publish(str(result.start), bundle.data)
        path = None
        if self.out_dir is not None:
            stem = str(result.start).replace(" ", "T").replace(":", "")
            path = bundle.write(self.out_dir / "explain" / stem)
        if self.journal is not None and ex.journal:
            self.journal.emit(
                "explain",
                bundle=str(path) if path else None,
                **bundle.journal_record(),
            )
        # Held until the flight dump this incident triggers, so the
        # bundle lands next to the dump and its manifest links it.
        self._last_bundle = bundle
        return {"explain_bundle": str(path)} if path else {}

    # ------------------------------------------------------ finalization
    def _finalize(
        self, result, outcome: str, frame=None, trace=None,
        explain_src=None,
    ) -> None:
        from ..obs.metrics import record_stream_window
        from ..obs.spans import get_tracer

        tracer = get_tracer()
        ctx = trace.ctx if trace is not None else None
        record_stream_window(outcome)
        setattr(
            self.summary, outcome, getattr(self.summary, outcome) + 1
        )
        opened_before = self.tracker.opened
        if outcome == "ranked":
            on_open = None
            ex = self.config.explain
            if (
                explain_src is not None
                and ex.enabled
                and ex.on_incident
            ):
                on_open = lambda inc: self._explain_incident(  # noqa: E731
                    result, explain_src
                )
            with tracer.span("incident", service="stream", ctx=ctx):
                inc = self.tracker.observe_ranked(
                    result.start, result.ranking, on_open=on_open
                )
            if inc is not None:
                self.summary.incidents_opened = self.tracker.opened
                self.log.info(
                    "window %s: anomaly -> %s (%d windows), top-1 %s",
                    result.start, inc.incident_id, inc.windows,
                    result.ranking[0][0] if result.ranking else "-",
                )
            if self.tracker.opened > opened_before and self.flight:
                # A NEW incident just opened: dump the causal record of
                # how the pipeline got here while the ring still holds
                # it (rate-limited inside the recorder).
                dump_dir = self.flight.dump("incident")
                if dump_dir is not None and self._last_bundle is not None:
                    # Rank provenance next to the flight dump, cross-
                    # linked in its manifest: the operator opens ONE
                    # directory and sees both the causal trace and the
                    # verdict's decomposition.
                    self._last_bundle.write(dump_dir)
                    self._link_bundle(dump_dir)
            self._last_bundle = None
        elif outcome != "warmup" and result.skipped_reason == "low_admission":
            # A window refused whole by admission is EVIDENCE-FREE: it
            # neither opens incidents (its garbage never ranked) nor
            # counts as a healthy observation (it cannot resolve one) —
            # a corruption burst is invisible to the lifecycle.
            pass
        elif outcome != "warmup":
            with tracer.span("incident", service="stream", ctx=ctx):
                resolved = self.tracker.observe_healthy(result.start)
            self.summary.incidents_resolved = self.tracker.resolved
            for inc in resolved:
                self.log.info(
                    "window %s: %s resolved after %d windows",
                    result.start, inc.incident_id, inc.windows,
                )
        # Freeze tracks the lifecycle: baselines absorb healthy traffic
        # only while no incident is open (anti-poisoning rule).
        if self.tracker.has_open:
            self.baseline.freeze()
        else:
            self.baseline.thaw()
            # Nothing left to warm-start against: the next incident's
            # first window cold-starts (and re-seeds the state).
            self._warm_state = None
        # Warehouse observation BEFORE the baseline absorbs this window:
        # the stored vocab/SLO snapshot must be the exact context the
        # verdict above was computed under (detect-time fidelity).
        if self.warehouse is not None:
            self._warehouse_observe(result, outcome, frame, explain_src)
        if outcome == "clean" and frame is not None:
            self.baseline.update(frame)   # no-op while frozen
        self.summary.results.append(result)
        if self.sink is not None:
            self.sink.emit(result)
        if self.journal is not None:
            self.journal.window(result)
        if trace is not None:
            # The per-window ROOT span: children (detect/build/dispatch/
            # fetch/incident) already parent-linked against its context;
            # its lifetime spans processing start -> emission.
            tracer.record_span(
                "window",
                ctx=trace.ctx,
                start_us=trace.start_us,
                dur_us=int((time.monotonic() - trace.perf0) * 1e6),
                service="stream",
                outcome=outcome,
            )
        # Durable boundary: this window's effects (sink lines, incident
        # transitions, baseline absorption) are on disk — capture the
        # state that makes them exactly-once across a restart. No-op
        # while pending ranks exist (the burst's drain boundary writes).
        self._checkpoint()

    def _warehouse_observe(self, result, outcome, frame, explain_src):
        """Hand one sealed window to the warehouse hot tier (flushed to
        warm segments at the next drained checkpoint boundary). A
        storage defect must never kill the stream — log and move on."""
        try:
            graph = op_names = kernel = None
            if explain_src is not None:
                graph, op_names, kernel, _ec = explain_src
            snapshot = (
                self.baseline.snapshot() if self.baseline.ready else None
            )
            self.warehouse.observe(
                result, outcome, frame=frame, graph=graph,
                op_names=op_names, kernel=kernel, snapshot=snapshot,
            )
        except Exception as e:  # noqa: BLE001 - containment rule
            self.log.warning("warehouse observe failed: %s", e)

    def _link_bundle(self, dump_dir) -> None:
        """Cross-link the explain bundle in the flight manifest."""
        import json as _json

        from ..explain.bundle import BUNDLE_JSON

        man = Path(dump_dir) / "manifest.json"
        try:
            data = _json.loads(man.read_text())
            data["explain_bundle"] = BUNDLE_JSON
            man.write_text(_json.dumps(data, indent=2))
        except (OSError, ValueError) as e:  # pragma: no cover
            self.log.warning(
                "could not cross-link explain bundle in %s: %s", man, e
            )


def run_stream(
    config: MicroRankConfig,
    source,
    out_dir=None,
    normal_df=None,
    on_result=None,
) -> StreamSummary:
    """Build and drive a StreamEngine to completion (the CLI entry)."""
    engine = StreamEngine(
        config, source, out_dir=out_dir, normal_df=normal_df
    )
    summary = engine.run()
    if on_result is not None:
        for r in summary.results:
            on_result(r)
    return summary
