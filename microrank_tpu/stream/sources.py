"""Pluggable span sources for the streaming engine.

A source is any iterable of canonical span DataFrames (io.schema
columns, parsed timestamps) — the engine does not care where spans come
from. Three deployment shapes ship:

* ``FileTailSource`` — tail a GROWING traces CSV, the file-drop shape
  ``pipeline/follow.py`` serves for the batch runner, sharing its
  ``TailTracker`` bookkeeping (and the ``follow_*`` metrics): torn
  final lines parse as a failure this poll and as data the next,
  rotation/truncation (size shrank) re-reads from scratch,
  ``idle_exit`` bounds consecutive no-progress polls. Unlike follow.py
  — which re-ranks via the window cursor — the tail yields only rows
  past the last yielded count; the engine's watermark handles
  everything downstream.
* ``ReplaySource`` — a staged CSV replayed with pacing: chunks emit in
  event-time order, optionally slept between (fixed ``pace_seconds`` or
  event-time faithful at ``rate`` x real time) — load generation and
  demos without a live collector.
* ``SyntheticSource`` — the in-process generator
  (``testing.synthetic.generate_timeline``) as a paced stream, with
  chosen windows carrying an injected fault. Exposes the ground truth
  (``fault_pod_op``) and the baseline-seeding normal window; the
  CI smoke and the acceptance tests run on it.
"""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Callable, Iterator, List, Optional, Sequence

import numpy as np
import pandas as pd

from ..utils.logging import get_logger

log = get_logger("microrank_tpu.stream.sources")


def _sort_by_event_time(df: pd.DataFrame) -> pd.DataFrame:
    """Stable event-time sort that survives hostile data: a corrupted
    ``startTime`` column (object dtype with garbage strings mixed in)
    sorts by the COERCED key — unparseable rows order first, flow to
    the engine's pre-admission gate, and get quarantined there instead
    of crashing the comparator here."""
    col = df["startTime"]
    if pd.api.types.is_datetime64_any_dtype(col):
        return df.sort_values(
            "startTime", kind="stable"
        ).reset_index(drop=True)
    key = pd.to_datetime(col, format="mixed", errors="coerce")
    order = np.argsort(
        key.values.astype("int64"), kind="stable"
    )
    return df.iloc[order].reset_index(drop=True)


def _sorted_chunks(
    df: pd.DataFrame, chunk_spans: int
) -> List[pd.DataFrame]:
    df = _sort_by_event_time(df)
    return [
        df.iloc[i : i + chunk_spans]
        for i in range(0, len(df), max(1, int(chunk_spans)))
    ]


def _maybe_corrupt_chunk(chunk: pd.DataFrame) -> pd.DataFrame:
    """The ``source_data`` chaos seam: when a fault spec fires with a
    data-corruption kind (ingest.hostile.CORRUPTION_KINDS), the chunk
    is deterministically corrupted — seeded by the plan seed and the
    seam's event number, so the same plan over the same stream replays
    the same dirty bytes. The admission ladder downstream is the
    defense under test."""
    from ..chaos.faults import get_fault_plan, maybe_inject
    from ..ingest.hostile import CORRUPTION_KINDS, corrupt_frame

    action = maybe_inject("source_data")
    if action is None or action["kind"] not in CORRUPTION_KINDS:
        return chunk
    plan = get_fault_plan()
    seed = (plan.seed if plan is not None else 0) * 7919 + int(
        action.get("event", 0)
    )
    value = action.get("value") or 0.0
    kwargs = {}
    if value:
        if action["kind"] == "cardinality_bomb":
            kwargs["bomb_ops"] = int(value)
        else:
            kwargs["fraction"] = float(value)
    return corrupt_frame(chunk, action["kind"], seed=seed, **kwargs)


def _is_warehouse_dir(path) -> bool:
    """A directory holding (or containing) sealed warehouse segments —
    ReplaySource accepts it anywhere a traces CSV is accepted."""
    try:
        p = Path(path)
    except TypeError:
        return False
    if not p.is_dir():
        return False
    from ..warehouse import MANIFEST_NAME, WAREHOUSE_DIR

    return (
        (p / MANIFEST_NAME).exists()
        or (p / WAREHOUSE_DIR / MANIFEST_NAME).exists()
        or any(p.glob("seg-*.npz"))
        or any(p.glob("cold-*.npz"))
    )


class ReplaySource:
    """Replay a staged traces CSV, a warehouse segment directory, or an
    in-memory frame with pacing.

    Resumable: the cursor is the count of rows already yielded (in the
    stable event-time sort order, which is a pure function of the data
    — a restarted replay re-sorts identically). The engine checkpoints
    it via :meth:`checkpoint_state`; :meth:`restore_state` makes the
    next iteration skip those rows.
    """

    def __init__(
        self,
        path_or_frame,
        chunk_spans: int = 5000,
        pace_seconds: float = 0.0,
        rate: Optional[float] = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if isinstance(path_or_frame, pd.DataFrame):
            self._df = path_or_frame
        elif _is_warehouse_dir(path_or_frame):
            # Warehouse-segment mode: reassemble the span stream from a
            # run's sealed segments — dictionary-compressed columnar
            # blobs decode straight to the canonical frame, no CSV
            # parse (the bench artifact's load_ms-vs-parse_ms row).
            from ..warehouse import load_warehouse_frame

            self._df = load_warehouse_frame(path_or_frame)
        else:
            from ..io import load_traces_csv

            self._df = load_traces_csv(path_or_frame)
        self.chunk_spans = int(chunk_spans)
        self.pace_seconds = float(pace_seconds)
        self.rate = rate
        self.sleep = sleep
        self.sleeps: List[float] = []   # what pacing actually did (tests)
        self.rows_emitted = 0           # checkpoint cursor
        self._skip_rows = 0

    # ------------------------------------------------------- durability
    def checkpoint_state(self) -> dict:
        return {"type": "replay", "row": int(self.rows_emitted)}

    def restore_state(self, state: dict) -> None:
        if state.get("type") != "replay":
            raise ValueError(f"not a replay cursor: {state}")
        self._skip_rows = max(0, int(state.get("row", 0)))

    def reset_cursor(self) -> None:
        """Drop a stashed resume cursor (whole-checkpoint rejection)."""
        self._skip_rows = 0

    def __iter__(self) -> Iterator[pd.DataFrame]:
        from ..chaos.faults import maybe_inject

        df = _sort_by_event_time(self._df)
        if self._skip_rows:
            # Resume: rows before the cursor were already windowed (and
            # live on in the checkpointed windower buffers/emits).
            log.info(
                "replay resume: skipping %d already-emitted rows",
                min(self._skip_rows, len(df)),
            )
            df = df.iloc[self._skip_rows :]
        self.rows_emitted = self._skip_rows
        chunks = _sorted_chunks(df, self.chunk_spans)
        for i, chunk in enumerate(chunks):
            # Cursor BEFORE the yield: while the engine processes (and
            # possibly checkpoints against) this chunk, the generator is
            # suspended here — the cursor must already cover the chunk
            # or a resume would re-feed spans the windower buffered.
            self.rows_emitted += len(chunk)
            yield _maybe_corrupt_chunk(chunk)
            if i == len(chunks) - 1:
                break
            maybe_inject("source_stall", sleep=self.sleep)
            if self.rate:
                # Event-time faithful pacing: sleep the event-time gap
                # to the next chunk, compressed by ``rate``. Hostile
                # data may leave garbage in the boundary cells; an
                # uncomputable gap paces at the fixed fallback.
                try:
                    gap_s = (
                        chunks[i + 1]["startTime"].iloc[0]
                        - chunk["startTime"].iloc[-1]
                    ).total_seconds()
                except (TypeError, ValueError, AttributeError):
                    gap_s = 0.0
                delay = max(0.0, gap_s / float(self.rate))
            else:
                delay = self.pace_seconds
            if delay > 0:
                self.sleeps.append(delay)
                self.sleep(delay)


class SyntheticSource:
    """Paced synthetic timeline with injected fault windows.

    Fault family/injection knobs ride the ``SyntheticConfig``
    (``fault_kind="error"`` for status-code faults, ``n_faults`` for
    multi-culprit windows, ``cascade_fraction``/``drift_per_window``
    for the cascade and drift families); the ground truth carries the
    FULL culprit set (``fault_pod_ops``) so multi-fault scoring is
    well-defined (``fault_pod_op`` stays the first culprit for back
    compat)."""

    def __init__(
        self,
        n_windows: int,
        faulted: Sequence[int],
        synth_config=None,
        chunk_spans: int = 4000,
        pace_seconds: float = 0.0,
        sleep: Callable[[float], None] = time.sleep,
    ):
        from ..testing import SyntheticConfig, generate_timeline

        cfg = synth_config or SyntheticConfig()
        tl = generate_timeline(cfg, int(n_windows), list(faulted))
        self.timeline = tl
        self.normal = tl.normal                 # baseline seed dump
        self.fault_pod_op = tl.fault_pod_op     # ground truth (first)
        self.fault_pod_ops = list(tl.fault_pod_ops)  # full culprit set
        self.window_faulted = tl.window_faulted
        self._replay = ReplaySource(
            tl.timeline,
            chunk_spans=chunk_spans,
            pace_seconds=pace_seconds,
            sleep=sleep,
        )

    def __iter__(self) -> Iterator[pd.DataFrame]:
        return iter(self._replay)

    # Resumable: the timeline is a pure function of the seed, so the
    # inner replay cursor restores a restarted synthetic run exactly.
    def checkpoint_state(self) -> dict:
        return self._replay.checkpoint_state()

    def restore_state(self, state: dict) -> None:
        self._replay.restore_state(state)

    def reset_cursor(self) -> None:
        self._replay.reset_cursor()


class FileTailSource:
    """Tail a growing traces CSV; yield only the newly appended rows.

    Resumable: the cursor is the tail's byte offset plus a ROTATION
    SIGNATURE (hash of the header line) — a restart restores the offset
    only when the signature still matches the file on disk; a rotated-
    in file re-reads from scratch (the checkpointed windower cursor
    still guards against double-emitting old windows). Chaos seams:
    ``source_stall`` (extra poll latency), ``source_torn`` (simulated
    torn tail line — parse fails this poll, the cursor holds, the data
    parses next poll) and ``source_rotation`` (forced cursor reset).
    """

    def __init__(
        self,
        path,
        poll_seconds: float = 2.0,
        idle_exit: int = 0,
        max_polls: int = 0,
        sleep: Callable[[float], None] = time.sleep,
        parse_retry_max: int = 3,
    ):
        self.path = Path(path)
        self.poll_seconds = float(poll_seconds)
        self.idle_exit = int(idle_exit)
        self.max_polls = int(max_polls)
        self.sleep = sleep
        # Dead-letter escalation: after this many consecutive failed
        # parses of the SAME byte range, the slice re-parses line by
        # line and the offending line(s) quarantine with their byte
        # offsets instead of retrying forever (0 disables).
        self.parse_retry_max = int(parse_retry_max)
        self._parse_fails = 0
        self._tracker = None
        self._restore: Optional[dict] = None

    # ------------------------------------------------------- durability
    def _signature(self) -> Optional[str]:
        """Rotation signature: hash of the header line. A rotated-in
        file with a different header invalidates the byte cursor."""
        import hashlib

        try:
            with open(self.path, "rb") as f:
                header = f.readline()
        except OSError:
            return None
        return hashlib.sha256(header).hexdigest() if header else None

    def checkpoint_state(self) -> Optional[dict]:
        t = self._tracker
        if t is None or t.parsed_offset <= 0:
            return {"type": "tail", "offset": 0}
        return {
            "type": "tail",
            "offset": int(t.parsed_offset),
            "size": int(t.last_size),
            "signature": self._signature(),
        }

    def restore_state(self, state: dict) -> None:
        if state.get("type") != "tail":
            raise ValueError(f"not a tail cursor: {state}")
        self._restore = dict(state)

    def reset_cursor(self) -> None:
        """Drop a stashed resume cursor (whole-checkpoint rejection)."""
        self._restore = None

    def _salvage(self, tracker, size: int) -> Optional[pd.DataFrame]:
        """Per-line re-parse of a slice that exhausted its whole-slice
        retries: each complete appended line parses alone (header
        prepended); lines that still fail quarantine with the reason
        ``unparseable_line`` and their ABSOLUTE byte offset, so an
        operator can find them in the file. The cursor then advances
        past the whole slice — the stream never retries a poison line
        again. Returns the good rows (possibly empty), or None when
        there was nothing to salvage (torn partial line: the normal
        hold-and-retry semantics keep applying)."""
        import io as _io

        from ..ingest.quarantine import get_quarantine
        from ..io import load_traces_csv
        from ..obs.metrics import record_ingest_rejected

        appended = tracker.read_appended(self.path, size)
        if appended is None:
            return None
        payload, offset = appended
        head_end = payload.find(b"\n")
        if head_end < 0:
            return None
        header = payload[: head_end + 1]
        body = payload[head_end + 1 :]
        if not body:
            return None
        base = offset - len(body)
        good: List[pd.DataFrame] = []
        bad = []
        pos = 0
        for line in body.splitlines(keepends=True):
            abs_off = base + pos
            pos += len(line)
            try:
                df = load_traces_csv(_io.BytesIO(header + line))
            except (ValueError, OSError):
                bad.append((line, abs_off))
                continue
            if len(df):
                good.append(df)
        store = get_quarantine()
        for line, abs_off in bad:
            store.put_raw(
                line,
                "unparseable_line",
                source=f"tail:{self.path}",
                offset=abs_off,
            )
            record_ingest_rejected("unparseable_line")
        if bad:
            log.warning(
                "tail %s: dead-lettered %d unparseable line(s) after "
                "%d whole-slice retries; cursor advanced to byte %d",
                self.path, len(bad), self._parse_fails, offset,
            )
        tracker.parsed(size, offset=offset)
        return (
            pd.concat(good, ignore_index=True)
            if good
            else pd.DataFrame()
        )

    def _tracker_for_run(self):
        from ..pipeline.follow import TailTracker

        tracker = TailTracker(idle_exit=self.idle_exit)
        st = self._restore
        if st and st.get("offset", 0) > 0:
            sig = self._signature()
            if sig is not None and sig == st.get("signature"):
                with open(self.path, "rb") as f:
                    header = f.readline()
                tracker.restore_cursor(
                    offset=int(st["offset"]),
                    size=int(st.get("size", st["offset"])),
                    header=header,
                )
                log.info(
                    "tail resume: cursor restored at byte %d of %s",
                    tracker.parsed_offset, self.path,
                )
            else:
                log.warning(
                    "tail resume: %s rotated since the checkpoint "
                    "(signature mismatch); re-reading from scratch",
                    self.path,
                )
        return tracker

    def __iter__(self) -> Iterator[pd.DataFrame]:
        import io as _io

        from ..chaos.faults import InjectedFault, maybe_inject
        from ..chaos.retry import record_attempt
        from ..io import load_traces_csv

        tracker = self._tracker = self._tracker_for_run()
        polls = 0
        while True:
            polls += 1
            maybe_inject("source_stall", sleep=self.sleep)
            if maybe_inject("source_rotation") is not None:
                # Simulated rotation: the cursor resets exactly as a
                # real size-shrink would reset it (full re-read next
                # poll; the windower guards double emission).
                tracker.force_rotation()
            size = (
                os.path.getsize(self.path) if self.path.exists() else -1
            )
            status = tracker.observe_size(size)
            if status != "grew":
                if status == "exit":
                    log.info(
                        "tail: no progress for %d polls; done",
                        tracker.idle,
                    )
                    return
                if self.max_polls and polls >= self.max_polls:
                    return
                self.sleep(self.poll_seconds)
                continue
            # Byte-offset incremental parse (TailTracker.read_appended):
            # only the header + complete lines appended since the last
            # successful parse reach pandas — O(appended) per poll, not
            # O(file); rotation resets the cursor to a full re-read.
            try:
                if maybe_inject("source_torn") is not None:
                    raise InjectedFault("source_torn", "torn_line")
                appended = tracker.read_appended(self.path, size)
                if appended is None:
                    # Only a torn partial line so far: no-progress poll;
                    # the cursor stays put and the bytes re-read later.
                    if self.max_polls and polls >= self.max_polls:
                        return
                    self.sleep(self.poll_seconds)
                    continue
                payload, offset = appended
                df = load_traces_csv(_io.BytesIO(payload))
            except (ValueError, OSError, InjectedFault) as exc:
                # Torn/corrupt tail: error this poll, valid data the
                # next (the tracker counts it toward idle_exit; the
                # cursor did not advance, so the slice re-feeds). The
                # re-read is a retry in the unified accounting.
                record_attempt("source_parse")
                self._parse_fails += 1
                if (
                    self.parse_retry_max
                    and self._parse_fails >= self.parse_retry_max
                ):
                    # The slice will never parse whole: re-parse it
                    # line by line, dead-letter the poison line(s)
                    # with their byte offsets, advance the cursor past
                    # them and keep streaming the good rows.
                    salvaged = self._salvage(tracker, size)
                    if salvaged is not None:
                        self._parse_fails = 0
                        if len(salvaged):
                            yield salvaged
                        if self.max_polls and polls >= self.max_polls:
                            return
                        self.sleep(self.poll_seconds)
                        continue
                if tracker.parse_failed(exc) == "exit":
                    return
                if self.max_polls and polls >= self.max_polls:
                    return
                self.sleep(self.poll_seconds)
                continue
            self._parse_fails = 0
            tracker.parsed(size, offset=offset)
            if len(df):
                yield df
            if self.max_polls and polls >= self.max_polls:
                return
            self.sleep(self.poll_seconds)
