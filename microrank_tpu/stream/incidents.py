"""Incident lifecycle: fingerprint, dedup, open/update/resolve, sinks.

A continuous engine that emits one ranked suspect list per abnormal
window buries the operator in duplicates — a 40-minute fault at
5-minute windows is ONE incident, not eight alerts. Here every ranked
window is fingerprinted by its tie-aware top-k suspect set (exact score
ties at the cut expand the set, so a legally permuted tie cannot split
an incident); consecutive windows whose fingerprints match — exactly or
by Jaccard overlap >= ``fingerprint_jaccard``, absorbing top-k tail
wobble across windows of the same fault — dedup into one OPEN incident
that UPDATEs per window and RESOLVEs after ``resolve_after_windows``
consecutive healthy windows. Dedup is DRIFT-AWARE (PR 5): fingerprints
carry the suspects' max-normalized score vector, and an update whose
vector moved by more than ``score_drift`` (L-inf) flags
``drifted: true`` — the suspect set looks the same but the fault is
evolving (dominant suspect changing, a second cause joining), which an
operator wants to see rather than have silently absorbed. A resolved fingerprint enters a cooldown:
re-flagging within ``cooldown_windows`` windows is suppressed (counted,
not alerted) — flap damping for faults straddling the detector's edge.

Transitions emit structured events to pluggable sinks: a JSONL incident
log (``incidents.jsonl``), stdout one-liners, and a best-effort webhook
POST (2 s timeout; failures counted, never raised into the engine).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

from ..utils.logging import get_logger

log = get_logger("microrank_tpu.stream.incidents")


def ranking_fingerprint(
    ranking: Sequence[Tuple[str, float]], k: int, rtol: float = 1e-6
) -> FrozenSet[str]:
    """Tie-aware top-k suspect set of one ranked window.

    Takes the top-k names plus every name whose score ties the k-th
    score within ``rtol`` — two windows whose rankings differ only by a
    permuted exact tie (different kernels/summation trees legally do
    this, see utils.ranking_compare) produce the SAME fingerprint.
    """
    if not ranking:
        return frozenset()
    k = min(max(1, int(k)), len(ranking))
    cut = float(ranking[k - 1][1])
    tol = rtol * max(abs(cut), 1e-12)
    return frozenset(
        name
        for i, (name, score) in enumerate(ranking)
        if i < k or float(score) >= cut - tol
    )


def _jaccard(a: FrozenSet[str], b: FrozenSet[str]) -> float:
    if not a or not b:
        return 0.0
    return len(a & b) / len(a | b)


def suspect_scores(
    ranking: Sequence[Tuple[str, float]], fingerprint: FrozenSet[str]
) -> Dict[str, float]:
    """The fingerprint members' scores, max-normalized so drift compares
    score SHAPE (which suspect dominates) rather than absolute scale —
    spectrum scores are only meaningful relative to the window."""
    scores = {
        str(n): float(s) for n, s in ranking if str(n) in fingerprint
    }
    peak = max((abs(s) for s in scores.values()), default=0.0)
    if peak <= 0:
        return {n: 0.0 for n in scores}
    return {n: s / peak for n, s in scores.items()}


def score_drift(a: Dict[str, float], b: Dict[str, float]) -> float:
    """L-inf distance between two normalized suspect-score vectors over
    the union of their supports (a missing suspect scores 0)."""
    if not a and not b:
        return 0.0
    return max(
        abs(a.get(n, 0.0) - b.get(n, 0.0)) for n in set(a) | set(b)
    )


@dataclass
class Incident:
    incident_id: str
    fingerprint: FrozenSet[str]
    opened_at: str                 # window start (event time)
    last_seen: str
    windows: int = 1
    healthy_streak: int = 0
    top: List[Tuple[str, float]] = field(default_factory=list)
    status: str = "open"           # open | resolved
    # Normalized suspect-score vector at the last observation: the
    # drift-aware dedup baseline (same top-k SET but a moved score
    # vector -> update carries drifted:true instead of silent dedup).
    scores: Dict[str, float] = field(default_factory=dict)
    drift_events: int = 0

    def to_event(self, transition: str, **extra) -> dict:
        return {
            "event": f"incident_{transition}",
            "incident_id": self.incident_id,
            "fingerprint": sorted(self.fingerprint),
            "opened_at": self.opened_at,
            "last_seen": self.last_seen,
            "windows": self.windows,
            "top": [[n, float(s)] for n, s in self.top[:10]],
            **extra,
        }

    # ------------------------------------------------------- durability
    def to_state(self) -> dict:
        return {
            "incident_id": self.incident_id,
            "fingerprint": sorted(self.fingerprint),
            "opened_at": self.opened_at,
            "last_seen": self.last_seen,
            "windows": self.windows,
            "healthy_streak": self.healthy_streak,
            "top": [[str(n), float(s)] for n, s in self.top],
            "status": self.status,
            "scores": {str(k): float(v) for k, v in self.scores.items()},
            "drift_events": self.drift_events,
        }

    @classmethod
    def from_state(cls, state: dict) -> "Incident":
        return cls(
            incident_id=str(state["incident_id"]),
            fingerprint=frozenset(state["fingerprint"]),
            opened_at=state["opened_at"],
            last_seen=state["last_seen"],
            windows=int(state.get("windows", 1)),
            healthy_streak=int(state.get("healthy_streak", 0)),
            top=[(str(n), float(s)) for n, s in state.get("top", [])],
            status=state.get("status", "open"),
            scores=dict(state.get("scores", {})),
            drift_events=int(state.get("drift_events", 0)),
        )


class JsonlIncidentSink:
    """Append one JSON line per lifecycle transition."""

    def __init__(self, path):
        from pathlib import Path

        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)

    def emit(self, event: dict) -> None:
        with open(self.path, "a") as f:
            f.write(json.dumps({"ts": time.time(), **event}) + "\n")


class StdoutIncidentSink:
    def emit(self, event: dict) -> None:
        top1 = event["top"][0][0] if event.get("top") else "-"
        print(
            f"[incident] {event['event']} {event['incident_id']} "
            f"windows={event['windows']} top1={top1} "
            f"at={event['last_seen']}"
        )


class WebhookIncidentSink:
    """JSON POST per transition with a bounded retry queue, never raises.

    The sink runs ON the engine thread, so every POST is bounded by an
    EXPLICIT timeout (``StreamConfig.webhook_timeout_seconds``) applied
    to connect AND read — a hung endpoint costs at most ``timeout``
    per transition, it cannot stall windowing/ranking indefinitely.

    Delivery is no longer fire-and-forget: a failed POST parks the
    event in a bounded FIFO with a per-event backoff schedule (the
    unified WEBHOOK_POLICY from chaos.retry — exponential, jittered)
    and re-sends due entries on later ``emit``/``flush`` calls, WITHOUT
    ever sleeping on the engine thread. An event is dropped — and
    counted in ``microrank_webhook_dropped_total`` — only after
    ``max_attempts`` failed sends, or when the full queue evicts its
    oldest entry. The payload enriches the raw lifecycle event with the
    top-k ``suspects`` and, when the explain subsystem produced one,
    the ``explain_bundle`` path. The ``webhook`` chaos seam fires
    inside each send (hang = bounded sleep, 5xx/fail = simulated
    failure) so the queue's behavior is drivable without a real wedged
    endpoint.
    """

    def __init__(
        self,
        url: str,
        timeout: float = 2.0,
        max_attempts: int = 4,
        max_queue: int = 64,
        clock=time.monotonic,
    ):
        from collections import deque

        from ..chaos.retry import WEBHOOK_POLICY

        self.url = url
        self.timeout = max(0.1, float(timeout))
        self.max_attempts = max(1, int(max_attempts))
        self.max_queue = max(1, int(max_queue))
        self.clock = clock
        self.policy = WEBHOOK_POLICY
        self.failures = 0   # failed POST attempts (cumulative)
        self.delivered = 0
        self.dropped = 0
        self._queue = deque()   # entries: [event, attempts, next_due]

    def emit(self, event: dict) -> None:
        self.flush()
        self._attempt(event, attempts=0)

    def flush(self) -> None:
        """Re-send every queued event whose backoff elapsed (called on
        each lifecycle transition and at engine drain; one pass, no
        sleeping — not-yet-due entries keep waiting)."""
        now = self.clock()
        for _ in range(len(self._queue)):
            entry = self._queue.popleft()
            event, attempts, due = entry
            if due > now:
                self._queue.append(entry)
                continue
            self._attempt(event, attempts)

    def pending(self) -> int:
        return len(self._queue)

    def _attempt(self, event: dict, attempts: int) -> None:
        import random as _random

        from ..chaos.retry import record_attempt

        if attempts > 0:
            record_attempt("webhook")
        if self._send(event):
            self.delivered += 1
            return
        self.failures += 1
        attempts += 1
        if attempts >= self.max_attempts:
            self._drop(event, f"{attempts} failed attempts")
            return
        due = self.clock() + self.policy.delay(attempts, _random)
        if len(self._queue) >= self.max_queue:
            oldest = self._queue.popleft()
            self._drop(oldest[0], "retry queue full")
        self._queue.append([event, attempts, due])

    def _drop(self, event: dict, why: str) -> None:
        from ..obs.metrics import record_webhook_dropped

        self.dropped += 1
        record_webhook_dropped()
        log.warning(
            "incident webhook event %s dropped (%s): %s",
            event.get("event"), why, self.url,
        )

    def _send(self, event: dict) -> bool:
        import urllib.request

        from ..chaos.faults import InjectedFault, maybe_inject

        req = urllib.request.Request(
            self.url,
            data=json.dumps(event).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            # Chaos seam: hang sleeps (bounded by the plan's value),
            # http_5xx/fail raise — both exercise the retry queue.
            maybe_inject("webhook")
            # The explicit timeout bounds the blocking socket ops
            # (connect + response read) — urlopen with no timeout would
            # inherit the global default of None and hang forever on a
            # wedged endpoint.
            urllib.request.urlopen(req, timeout=self.timeout).close()
            return True
        except InjectedFault as e:
            log.warning("incident webhook failed (%s): %s", self.url, e)
            return False
        except Exception as e:  # noqa: BLE001 - alerting must not kill RCA
            log.warning("incident webhook failed (%s): %s", self.url, e)
            return False


class IncidentTracker:
    """Window-ordered incident state machine over ranked/healthy windows."""

    def __init__(
        self,
        top_k: int = 5,
        resolve_after: int = 2,
        cooldown_windows: int = 2,
        jaccard: float = 0.5,
        score_drift: float = 0.25,
        sinks: Optional[List] = None,
    ):
        self.top_k = int(top_k)
        self.resolve_after = max(1, int(resolve_after))
        self.cooldown_windows = max(0, int(cooldown_windows))
        self.jaccard = float(jaccard)
        # Drift-aware dedup threshold (L-inf over normalized suspect
        # scores); <= 0 disables drift flagging.
        self.score_drift = float(score_drift)
        self.sinks = list(sinks or [])
        self._open: Dict[FrozenSet[str], Incident] = {}
        self._cooldown: Dict[FrozenSet[str], int] = {}  # fp -> window#
        self._window_no = 0
        self._ids = 0
        self.opened = 0
        self.resolved = 0
        self.suppressed = 0

    # ------------------------------------------------------------- state
    @property
    def has_open(self) -> bool:
        return bool(self._open)

    def open_incidents(self) -> List[Incident]:
        return list(self._open.values())

    # ------------------------------------------------------- durability
    def to_state(self) -> dict:
        """JSON-serializable tracker state (chaos.checkpoint): open
        incidents, cooldown table, and the id/window counters — a
        restored tracker dedups the restarted run's abnormal windows
        into the SAME incidents instead of re-opening them."""
        return {
            "open": [inc.to_state() for inc in self._open.values()],
            "cooldown": [
                [sorted(fp), int(n)] for fp, n in self._cooldown.items()
            ],
            "window_no": self._window_no,
            "ids": self._ids,
            "opened": self.opened,
            "resolved": self.resolved,
            "suppressed": self.suppressed,
        }

    def restore(self, state: dict) -> None:
        """Overwrite lifecycle state from a checkpoint. No events are
        emitted — the sinks already saw these transitions in the run
        that wrote the checkpoint. Parse-then-commit: every field is
        decoded (and may raise) BEFORE any tracker state mutates, so a
        malformed checkpoint can never leave a half-restored
        lifecycle."""
        if not isinstance(state, dict) or "open" not in state:
            raise ValueError(
                f"not an incident-tracker state (keys "
                f"{sorted(state) if isinstance(state, dict) else state})"
            )
        open_incidents = [
            Incident.from_state(s) for s in state.get("open", [])
        ]
        cooldown = {
            frozenset(fp): int(n)
            for fp, n in state.get("cooldown", [])
        }
        window_no = int(state.get("window_no", 0))
        ids = int(state.get("ids", 0))
        opened = int(state.get("opened", 0))
        resolved = int(state.get("resolved", 0))
        suppressed = int(state.get("suppressed", 0))
        self._open = {inc.fingerprint: inc for inc in open_incidents}
        self._cooldown = cooldown
        self._window_no = window_no
        self._ids = ids
        self.opened = opened
        self.resolved = resolved
        self.suppressed = suppressed

    def reset(self) -> None:
        """Back to a cold lifecycle (the engine's whole-checkpoint
        rejection path); sinks and thresholds stay."""
        self._open = {}
        self._cooldown = {}
        self._window_no = 0
        self._ids = 0
        self.opened = 0
        self.resolved = 0
        self.suppressed = 0

    # ------------------------------------------------------------ intake
    def observe_ranked(
        self,
        window_start: str,
        ranking: Sequence[Tuple[str, float]],
        on_open=None,
    ) -> Optional[Incident]:
        """One abnormal RANKED window; returns the incident it mapped to
        (None when suppressed by cooldown).

        ``on_open(incident) -> dict``: called once when a NEW incident
        is about to open, BEFORE its ``incident_open`` event is emitted;
        the returned fields merge into that event (the stream engine
        attaches the explain-bundle path this way, so webhooks see it in
        the open payload itself). A failing hook is contained."""
        self._window_no += 1
        fp = ranking_fingerprint(ranking, self.top_k)
        from ..obs.metrics import record_incident

        # Dedup against open incidents: exact match, else best overlap.
        match = self._open.get(fp)
        if match is None and self._open:
            best = max(
                self._open.values(),
                key=lambda inc: _jaccard(fp, inc.fingerprint),
            )
            if _jaccard(fp, best.fingerprint) >= self.jaccard:
                match = best
        if match is not None:
            # Drift-aware dedup: same (or overlapping) suspect SET, but
            # the normalized score vector moved past the threshold —
            # the fault is evolving (a second root cause joining, the
            # dominant suspect changing); the update says so instead of
            # silently absorbing the window.
            new_scores = suspect_scores(ranking, match.fingerprint | fp)
            drift = score_drift(match.scores, new_scores)
            drifted = bool(
                self.score_drift > 0 and drift >= self.score_drift
            )
            match.windows += 1
            match.healthy_streak = 0
            match.last_seen = window_start
            match.top = list(ranking)
            match.scores = new_scores
            if drifted:
                match.drift_events += 1
            record_incident("update")
            self._emit(
                match.to_event(
                    "update", drifted=drifted, score_drift=round(drift, 4)
                )
            )
            return match
        # Cooldown: the same (or overlapping) fingerprint resolved
        # within the last cooldown_windows windows — suppress, count.
        for cfp, resolved_no in list(self._cooldown.items()):
            if self._window_no - resolved_no > self.cooldown_windows:
                del self._cooldown[cfp]
            elif cfp == fp or _jaccard(fp, cfp) >= self.jaccard:
                self.suppressed += 1
                record_incident("suppressed")
                log.info(
                    "incident suppressed (cooldown): %s", sorted(fp)
                )
                return None
        self._ids += 1
        inc = Incident(
            incident_id=f"inc-{self._ids}",
            fingerprint=fp,
            opened_at=window_start,
            last_seen=window_start,
            top=list(ranking),
            scores=suspect_scores(ranking, fp),
        )
        self._open[fp] = inc
        self.opened += 1
        extra = {}
        if on_open is not None:
            try:
                extra = on_open(inc) or {}
            except Exception as e:  # noqa: BLE001 - provenance must not
                # block alerting; the open event just lacks the extras.
                log.warning("incident on_open hook failed: %s", e)
        record_incident("open", open_now=len(self._open))
        # Enrichment: the tie-aware top-k suspects WITH scores at the
        # fingerprint cut, explicit in every open payload (the full
        # ``top`` list stays for context).
        self._emit(
            inc.to_event(
                "open",
                suspects=[
                    [str(n), float(s)]
                    for n, s in inc.top[: self.top_k]
                ],
                **extra,
            )
        )
        return inc

    def observe_healthy(self, window_start: str) -> List[Incident]:
        """One healthy (clean/empty/skipped) window; returns incidents
        it resolved."""
        self._window_no += 1
        resolved: List[Incident] = []
        from ..obs.metrics import record_incident

        for fp, inc in list(self._open.items()):
            inc.healthy_streak += 1
            if inc.healthy_streak >= self.resolve_after:
                inc.status = "resolved"
                del self._open[fp]
                self._cooldown[fp] = self._window_no
                self.resolved += 1
                resolved.append(inc)
                record_incident("resolve", open_now=len(self._open))
                self._emit(
                    inc.to_event("resolve", resolved_at=window_start)
                )
        return resolved

    # ------------------------------------------------------------- sinks
    def _emit(self, event: dict) -> None:
        for sink in self.sinks:
            try:
                sink.emit(event)
            except Exception as e:  # noqa: BLE001 - sink faults stay local
                log.warning(
                    "incident sink %s failed: %s", type(sink).__name__, e
                )
