"""Event-time windower with watermarks (stream/ subsystem).

The batch pipelines slice a FINISHED dump into windows after the fact
(``window_spans`` over a static DataFrame); a continuous engine has to
decide *when a window is complete* while spans are still arriving, out
of order. The standard streaming answer — the one Flink/Beam-shaped
trace pipelines use — is the watermark: the engine tracks the maximum
span START time it has seen, subtracts an allowed-lateness bound, and
declares every window whose end precedes that watermark CLOSED. Spans
that arrive inside the bound still land in their (earlier) window;
spans older than every window they belong to are dropped and counted
(``microrank_stream_late_spans_total``) — bounded state, bounded
reordering, explicit loss accounting.

Windows are tumbling (slide == width, the batch runner's layout) or
sliding (slide < width: each span lands in ceil(width/slide) windows).
Closed windows emit IN ORDER of window start, including EMPTY windows
(a silent gap in traffic is itself a signal worth journaling — and the
engine must advance the incident lifecycle's healthy streak through it).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd


@dataclass
class ClosedWindow:
    """One window the watermark sealed: [start_us, end_us) event time."""

    start_us: int
    end_us: int
    frame: Optional[pd.DataFrame]   # None for an empty window

    @property
    def n_spans(self) -> int:
        return 0 if self.frame is None else len(self.frame)

    @property
    def start(self) -> str:
        return str(pd.Timestamp(self.start_us * 1000))

    @property
    def end(self) -> str:
        return str(pd.Timestamp(self.end_us * 1000))


def _event_us(frame: pd.DataFrame) -> np.ndarray:
    """Span event time (startTime) as int64 microseconds."""
    return (
        pd.to_datetime(frame["startTime"]).astype("int64").to_numpy()
        // 1000
    )


class StreamWindower:
    """Assign spans to event-time windows; close them at the watermark.

    ``add(frame)`` buffers the batch's spans into their window(s) and
    returns every window that CLOSED as a result (in start order);
    ``flush()`` closes everything still open (end of stream). Window
    boundaries align to the EPOCH (origin = first span's time floored to
    a slide multiple — the Flink/Beam convention): boundaries are a pure
    function of wall time, so restarts and replays produce identical
    windows and a collector cutting dumps on round timestamps never
    straddles them.
    """

    def __init__(
        self,
        width_us: int,
        slide_us: Optional[int] = None,
        lateness_us: int = 0,
    ):
        self.width_us = int(width_us)
        self.slide_us = int(slide_us) if slide_us else self.width_us
        if not 0 < self.slide_us <= self.width_us:
            raise ValueError(
                f"slide ({self.slide_us}) must be in (0, width="
                f"{self.width_us}]"
            )
        self.lateness_us = max(0, int(lateness_us))
        self.origin_us: Optional[int] = None
        self.max_event_us: Optional[int] = None
        self.dropped_late = 0
        self._next = 0                       # next window index to emit
        self._buffers: Dict[int, List[pd.DataFrame]] = {}

    # ------------------------------------------------------------ intake
    def add(self, frame: pd.DataFrame) -> List[ClosedWindow]:
        """Buffer one span batch; return the windows it closed."""
        if frame is None or len(frame) == 0:
            return []
        t = _event_us(frame)
        if self.origin_us is None:
            first = int(t.min())
            # Index 0 is the EARLIEST epoch-aligned window that can hold
            # the first span (overlap-1 slides back); tumbling reduces
            # to flooring the first span to a width boundary.
            n_overlap = -(-self.width_us // self.slide_us)
            self.origin_us = (
                first // self.slide_us - (n_overlap - 1)
            ) * self.slide_us
            self.max_event_us = first
        rel = t - self.origin_us
        base = np.floor_divide(rel, self.slide_us)
        # A span at rel belongs to windows i = base-j (j = 0..overlap-1)
        # with i*slide <= rel < i*slide + width. Window i has emitted iff
        # i < _next, so a span whose NEWEST window (i = base) already
        # emitted can land nowhere: it is late beyond the bound. (rel < 0
        # — before the origin — floors base negative and lands here too.)
        late = base < self._next
        self.dropped_late += int(late.sum())
        if late.any():
            from ..obs.metrics import stream_late_spans

            stream_late_spans().inc(float(late.sum()))
        n_overlap = -(-self.width_us // self.slide_us)
        for j in range(n_overlap):
            i = base - j
            ok = (
                (i >= self._next)
                & (rel - i * self.slide_us < self.width_us)
            )
            if not ok.any():
                continue
            sub = frame[ok]
            i_ok = i[ok]
            for idx in np.unique(i_ok):
                self._buffers.setdefault(int(idx), []).append(
                    sub[i_ok == idx]
                )
        self.max_event_us = max(self.max_event_us, int(t.max()))
        return self._emit_closed()

    # ---------------------------------------------------------- emission
    def _window_bounds(self, i: int) -> Tuple[int, int]:
        s = self.origin_us + i * self.slide_us
        return s, s + self.width_us

    def _pop_window(self, i: int) -> ClosedWindow:
        s, e = self._window_bounds(i)
        parts = self._buffers.pop(i, None)
        frame = pd.concat(parts, ignore_index=True) if parts else None
        return ClosedWindow(start_us=s, end_us=e, frame=frame)

    def _emit_closed(self) -> List[ClosedWindow]:
        if self.origin_us is None:
            return []
        watermark = self.max_event_us - self.lateness_us
        out: List[ClosedWindow] = []
        while self._window_bounds(self._next)[1] <= watermark:
            out.append(self._pop_window(self._next))
            self._next += 1
        return out

    def flush(self) -> List[ClosedWindow]:
        """Close every remaining open window (end of stream)."""
        out: List[ClosedWindow] = []
        if self.origin_us is None:
            return out
        while self._buffers:
            last = max(self._buffers)
            while self._next <= last:
                out.append(self._pop_window(self._next))
                self._next += 1
        return out

    # ------------------------------------------------------- durability
    def to_state(self) -> dict:
        """JSON-serializable windower state (chaos.checkpoint): the
        geometry (validated on restore — a resumed run must window
        identically), the emit cursor/watermark, and the OPEN buffers
        serialized as CSV text. Buffer size is bounded by the window
        overlap plus allowed lateness, and a checkpoint whose cursor
        and buffers were captured together is exactly consistent with
        the source cursor captured in the same checkpoint: the restored
        engine re-emits no window twice and loses none."""
        buffers = {}
        for idx, parts in self._buffers.items():
            frame = (
                parts[0]
                if len(parts) == 1
                else pd.concat(parts, ignore_index=True)
            )
            buffers[str(idx)] = frame.to_csv(index=False)
        return {
            "width_us": self.width_us,
            "slide_us": self.slide_us,
            "lateness_us": self.lateness_us,
            "origin_us": self.origin_us,
            "max_event_us": self.max_event_us,
            "next": self._next,
            "dropped_late": self.dropped_late,
            "buffers": buffers,
        }

    def restore(self, state: dict) -> None:
        """Overwrite windower state from a checkpoint; raises
        ValueError when the checkpointed geometry differs from the
        configured one (the run would re-window the stream
        differently, so the checkpoint is unusable)."""
        import io as _io

        geom = (state["width_us"], state["slide_us"], state["lateness_us"])
        if geom != (self.width_us, self.slide_us, self.lateness_us):
            raise ValueError(
                f"checkpoint window geometry {geom} != configured "
                f"{(self.width_us, self.slide_us, self.lateness_us)}"
            )
        self.origin_us = state["origin_us"]
        self.max_event_us = state["max_event_us"]
        self._next = int(state["next"])
        self.dropped_late = int(state.get("dropped_late", 0))
        self._buffers = {}
        for idx, csv_text in state.get("buffers", {}).items():
            frame = pd.read_csv(_io.StringIO(csv_text))
            for col in ("startTime", "endTime"):
                if col in frame.columns:
                    frame[col] = pd.to_datetime(frame[col])
            self._buffers[int(idx)] = [frame]
