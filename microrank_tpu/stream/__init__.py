"""Continuous RCA engine (``cli stream``): the always-on workload.

An unbounded span source (``sources``: file tail, paced CSV replay,
synthetic generator) feeds an event-time windower with watermarks and
bounded lateness (``window``); every closed window runs the detector
against online SLO baselines (``baseline``: exponential-decay mean/std
+ P^2 quantiles, frozen during incidents); only ABNORMAL windows pay
for graph build + device rank, with host builds overlapped on a worker
pool (``pool``, shared with serve/); ranked windows dedup into
incidents with open/update/resolve lifecycle and pluggable sinks
(``incidents``). ``engine`` wires it together.
"""

from .baseline import OnlineBaseline, P2Quantile
from .engine import (
    INCIDENT_LOG_NAME,
    StreamEngine,
    StreamSummary,
    run_stream,
)
from .incidents import (
    Incident,
    IncidentTracker,
    JsonlIncidentSink,
    StdoutIncidentSink,
    WebhookIncidentSink,
    ranking_fingerprint,
)
from .pool import BuildWorkerPool
from .sources import FileTailSource, ReplaySource, SyntheticSource
from .window import ClosedWindow, StreamWindower

__all__ = [
    "BuildWorkerPool",
    "ClosedWindow",
    "FileTailSource",
    "INCIDENT_LOG_NAME",
    "Incident",
    "IncidentTracker",
    "JsonlIncidentSink",
    "OnlineBaseline",
    "P2Quantile",
    "ReplaySource",
    "StdoutIncidentSink",
    "StreamEngine",
    "StreamSummary",
    "StreamWindower",
    "SyntheticSource",
    "WebhookIncidentSink",
    "ranking_fingerprint",
    "run_stream",
]
