"""Online SLO baselines: exponential-decay mean/std + P^2 quantiles.

The batch pipelines fit the SLO baseline ONCE from a normal-period dump
(``detect.slo.compute_slo``) and never revisit it; a continuous engine
cannot — operations appear, latencies drift, and a baseline frozen at
deploy time slowly turns every window anomalous (or none). Here each
operation carries:

* exponential-decay first/second moments (``m1``/``m2``) updated from
  every HEALTHY window's per-op sample mean — mean and population std
  fall out as ``m1`` and ``sqrt(m2 - m1^2)``, matching the batch
  baseline's shape while forgetting old traffic at ``decay`` per window;
* a P^2 streaming quantile estimator (Jain & Chlamtac 1985: five
  markers, O(1) state and O(1) per sample, no sample buffer) so the
  percentile SLO statistics (``DetectorConfig.slo_stat="p99"`` etc.)
  work online too.

The estimators update ONLY on healthy windows and FREEZE while an
incident is open — otherwise the fault's own latencies would absorb
into the baseline and the detector would declare recovery by drift
rather than by the system actually recovering (the classic
self-poisoning failure of online anomaly detection).

``snapshot()`` renders the current state as the ``(Vocab, SloBaseline)``
pair every existing detector entry point consumes — streaming mode
swaps the baseline's PRODUCER, not the detector.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

from ..detect.slo import slo_quantile
from ..graph.structures import SloBaseline
from ..io.interning import Vocab
from ..io.naming import operation_names
from ..io.schema import US_PER_MS


class P2Quantile:
    """Jain & Chlamtac's P^2 algorithm: one quantile, five markers.

    Exact over the first five samples; afterwards the three interior
    markers track the q-, q/2- and (1+q)/2-quantile positions via
    piecewise-parabolic height adjustment. State is 15 floats per
    (operation, quantile) — the whole point next to a sample buffer.
    """

    __slots__ = ("q", "n", "heights", "pos", "desired", "incr")

    def __init__(self, q: float):
        self.q = float(q)
        self.n = 0
        self.heights: List[float] = []
        self.pos = np.arange(1.0, 6.0)
        self.desired = np.array(
            [1.0, 1 + 2 * q, 1 + 4 * q, 3 + 2 * q, 5.0]
        )
        self.incr = np.array([0.0, q / 2, q, (1 + q) / 2, 1.0])

    def _adjust_marker(self, i: int) -> bool:
        """One P^2 marker-adjustment step for interior marker ``i``
        (the parabolic/linear height move); returns whether it moved.
        Shared verbatim by the scalar and batch update paths."""
        h = self.heights
        d = self.desired[i] - self.pos[i]
        step_up = self.pos[i + 1] - self.pos[i]
        step_dn = self.pos[i - 1] - self.pos[i]
        if not ((d >= 1 and step_up > 1) or (d <= -1 and step_dn < -1)):
            return False
        s = 1.0 if d >= 1 else -1.0
        cand = h[i] + s / (step_up - step_dn) * (
            (self.pos[i] - self.pos[i - 1] + s)
            * (h[i + 1] - h[i])
            / step_up
            + (self.pos[i + 1] - self.pos[i] - s)
            * (h[i] - h[i - 1])
            / step_dn
        )
        if not h[i - 1] < cand < h[i + 1]:
            # Parabolic estimate left the bracket: linear step.
            j = i + (1 if s > 0 else -1)
            cand = h[i] + s * (h[j] - h[i]) / (self.pos[j] - self.pos[i])
        h[i] = cand
        self.pos[i] += s
        return True

    def update(self, x: float) -> None:
        x = float(x)
        if self.n < 5:
            self.heights.append(x)
            self.heights.sort()
            self.n += 1
            return
        h = self.heights
        if x < h[0]:
            h[0] = x
            k = 0
        elif x >= h[4]:
            h[4] = x
            k = 3
        else:
            k = 0
            while x >= h[k + 1]:
                k += 1
        self.pos[k + 1 :] += 1.0
        self.desired += self.incr
        self.n += 1
        for i in (1, 2, 3):
            self._adjust_marker(i)

    def update_batch(self, xs) -> None:
        """Absorb a whole window's samples in vectorized chunks (the
        ROADMAP stream follow-up): each chunk bins its samples against
        the CURRENT marker heights with one ``searchsorted``,
        bulk-updates the marker positions from the cumulative cell
        counts, then runs the marker-adjustment steps until the markers
        reach their desired positions.

        Numerics: the scalar path re-bins after every height
        adjustment; freezing the heights for a whole chunk is the
        standard batched-P^2 trade, SAFE only while the chunk is small
        next to the state the estimator already holds — so the chunk
        size scales with ``n`` (the estimator's adaptation timescale is
        O(n)): early samples absorb in small chunks while the markers
        are immature, mature state takes whole windows at once. Parity
        vs the scalar implementation is pinned by
        test_stream.test_p2_batch_update_matches_scalar. Cost per
        window drops from O(samples) Python iterations to O(chunks)
        searchsorted passes + O(marker moves) scalar steps.
        """
        xs = np.asarray(xs, dtype=float).ravel()
        if xs.size == 0:
            return
        if self.n < 5:
            # Seed phase is exact: fill to the five markers scalar-wise.
            take = min(5 - self.n, xs.size)
            self.heights.extend(float(x) for x in xs[:take])
            self.heights.sort()
            self.n += take
            xs = xs[take:]
        start = 0
        while start < xs.size:
            chunk = max(16, self.n // 2)
            self._absorb_chunk(xs[start : start + chunk])
            start += chunk

    def _absorb_chunk(self, xs: np.ndarray) -> None:
        h = self.heights
        h[0] = min(h[0], float(xs.min()))
        h[4] = max(h[4], float(xs.max()))
        # Cell of each sample: k = #{j in 1..3 : h[j] <= x} — identical
        # to the scalar walk (x < h[0] lands in cell 0, x >= h[4] in 3).
        cells = np.searchsorted(np.asarray(h[1:4]), xs, side="right")
        counts = np.bincount(cells, minlength=4)[:4]
        below = np.cumsum(counts)          # samples with cell < j
        self.pos[1:4] += below[:3].astype(float)
        self.pos[4] += float(xs.size)
        self.desired += self.incr * xs.size
        self.n += int(xs.size)
        # Marker heights chase the bulk-advanced desired positions: each
        # adjustment moves a marker one position, so the loop is bounded
        # by the total displacement (<= q-weighted chunk size).
        for _ in range(int(xs.size) + 5):
            moved = False
            for i in (1, 2, 3):
                moved = self._adjust_marker(i) or moved
            if not moved:
                break

    def value(self) -> float:
        if self.n == 0:
            return float("nan")
        if self.n <= 5:
            h = sorted(self.heights)
            # Exact quantile over the few samples held so far.
            return float(np.quantile(h, self.q))
        return float(self.heights[2])

    # ------------------------------------------------------- durability
    def to_state(self) -> dict:
        """JSON-serializable estimator state (chaos.checkpoint): the
        five marker heights/positions ARE the whole estimator, so a
        restore is bit-faithful."""
        return {
            "q": self.q,
            "n": self.n,
            "heights": [float(h) for h in self.heights],
            "pos": [float(p) for p in self.pos],
            "desired": [float(d) for d in self.desired],
        }

    @classmethod
    def from_state(cls, state: dict) -> "P2Quantile":
        p2 = cls(float(state["q"]))
        p2.n = int(state["n"])
        p2.heights = [float(h) for h in state["heights"]]
        p2.pos = np.asarray(state["pos"], dtype=float)
        p2.desired = np.asarray(state["desired"], dtype=float)
        return p2


class _OpState:
    """One operation's online baseline state (durations in ms)."""

    __slots__ = ("m1", "m2", "windows", "p2")

    def __init__(self, quantile: Optional[float]):
        self.m1 = 0.0
        self.m2 = 0.0
        self.windows = 0
        self.p2 = P2Quantile(quantile) if quantile is not None else None


class OnlineBaseline:
    """Per-operation streaming SLO state behind the batch detector's
    ``(Vocab, SloBaseline)`` interface."""

    def __init__(
        self,
        decay: float = 0.1,
        slo_stat: str = "mean",
        min_windows: int = 1,
        p2_seed_cap: int = 2048,
    ):
        if not 0.0 < decay <= 1.0:
            raise ValueError(f"decay must be in (0, 1], got {decay}")
        self.decay = float(decay)
        self.slo_stat = slo_stat
        self.quantile = (
            None if slo_stat == "mean" else slo_quantile(slo_stat)
        )
        self.min_windows = int(min_windows)
        self.p2_seed_cap = int(p2_seed_cap)
        self._ops: Dict[str, _OpState] = {}
        self.frozen = False
        self.seeded = False
        self.n_updates = 0      # healthy windows absorbed
        self.n_frozen_skips = 0

    # ------------------------------------------------------------- state
    @property
    def ready(self) -> bool:
        """Detection arms once seeded or fed ``min_windows`` windows."""
        return bool(self._ops) and (
            self.seeded or self.n_updates >= self.min_windows
        )

    def freeze(self) -> None:
        self.frozen = True

    def thaw(self) -> None:
        self.frozen = False

    def known_ops(self) -> frozenset:
        """The operations this baseline has SLO state for — the
        reference set of the admission ladder's vocab-growth guard
        (ingest.admit_frame known_ops): ops outside it are never-seen,
        and a window introducing a burst of them is a cardinality
        attack, not a deployment."""
        return frozenset(self._ops)

    # ------------------------------------------------------------ intake
    def _grouped_ms(self, span_df: pd.DataFrame):
        names = operation_names(span_df, "service")
        dur_ms = span_df["duration"].astype(float) / US_PER_MS
        return dur_ms.groupby(names.to_numpy())

    def seed(self, normal_df: pd.DataFrame) -> None:
        """Initialize from a normal-period dump (the batch baseline's
        input) so detection arms immediately; the P^2 markers absorb at
        most ``p2_seed_cap`` strided samples per op (seeding is one-time
        but a multi-GB dump should not cost a per-span Python loop)."""
        for name, dur in self._grouped_ms(normal_df):
            st = self._ops.setdefault(str(name), _OpState(self.quantile))
            vals = dur.to_numpy()
            st.m1 = float(vals.mean())
            st.m2 = float((vals**2).mean())
            st.windows += 1
            if st.p2 is not None:
                stride = max(1, len(vals) // self.p2_seed_cap)
                st.p2.update_batch(vals[::stride])
        self.seeded = True

    def update(self, window_df: pd.DataFrame) -> bool:
        """Absorb one HEALTHY window; no-op (False) while frozen."""
        if self.frozen:
            self.n_frozen_skips += 1
            return False
        a = self.decay
        for name, dur in self._grouped_ms(window_df):
            st = self._ops.get(str(name))
            vals = dur.to_numpy()
            w_m1 = float(vals.mean())
            w_m2 = float((vals**2).mean())
            if st is None:
                st = self._ops[str(name)] = _OpState(self.quantile)
                st.m1, st.m2 = w_m1, w_m2
            else:
                st.m1 = (1 - a) * st.m1 + a * w_m1
                st.m2 = (1 - a) * st.m2 + a * w_m2
            st.windows += 1
            if st.p2 is not None:
                st.p2.update_batch(vals)
        self.n_updates += 1
        return True

    # ------------------------------------------------------- durability
    def to_state(self) -> dict:
        """The full baseline as JSON-serializable checkpoint state: the
        exp-decay moments and P^2 markers per op, plus the arming/freeze
        flags — a restore resumes detection exactly where the crashed
        process left it (no cold-start window gating)."""
        return {
            "decay": self.decay,
            "slo_stat": self.slo_stat,
            "min_windows": self.min_windows,
            "frozen": self.frozen,
            "seeded": self.seeded,
            "n_updates": self.n_updates,
            "n_frozen_skips": self.n_frozen_skips,
            "ops": {
                name: {
                    "m1": st.m1,
                    "m2": st.m2,
                    "windows": st.windows,
                    "p2": None if st.p2 is None else st.p2.to_state(),
                }
                for name, st in self._ops.items()
            },
        }

    def restore(self, state: dict) -> None:
        """Overwrite this baseline with checkpointed state. The SLO
        statistic must match — a p99 marker array is meaningless under a
        mean-configured detector (raises ValueError; the engine treats
        that as an unusable checkpoint)."""
        if state.get("slo_stat") != self.slo_stat:
            raise ValueError(
                f"checkpoint baseline slo_stat {state.get('slo_stat')!r} "
                f"!= configured {self.slo_stat!r}"
            )
        self.frozen = bool(state.get("frozen", False))
        self.seeded = bool(state.get("seeded", False))
        self.n_updates = int(state.get("n_updates", 0))
        self.n_frozen_skips = int(state.get("n_frozen_skips", 0))
        self._ops = {}
        for name, op in state.get("ops", {}).items():
            st = _OpState(self.quantile)
            st.m1 = float(op["m1"])
            st.m2 = float(op["m2"])
            st.windows = int(op.get("windows", 0))
            if op.get("p2") is not None and self.quantile is not None:
                st.p2 = P2Quantile.from_state(op["p2"])
            self._ops[str(name)] = st

    # ----------------------------------------------------------- egress
    def snapshot(self) -> Tuple[Vocab, SloBaseline]:
        """The detector-facing view: name-sorted vocab + dense arrays
        (center per ``slo_stat``, population-style std)."""
        names = sorted(self._ops)
        center = np.empty(len(names), np.float32)
        std = np.empty(len(names), np.float32)
        for i, n in enumerate(names):
            st = self._ops[n]
            var = max(0.0, st.m2 - st.m1 * st.m1)
            std[i] = np.float32(var**0.5)
            center[i] = np.float32(
                st.m1 if st.p2 is None else st.p2.value()
            )
        return Vocab(names), SloBaseline(mean_ms=center, std_ms=std)
