"""Build worker pool: host graph builds off the dispatch thread.

Both always-on paths have the same hot-loop shape: a single thread owns
the device (program-order guarantee for jax dispatch) and must not spend
its time in pandas/numpy graph construction while the device sits idle.
The pool is the seam that fixes it in both places:

* the stream engine submits window N+1's build here while its own
  thread dispatches window N's rank — the build/rank overlap the table
  lane gets from its stage/fetch workers, for the streaming loop;
* the serve scheduler (serve/scheduler.py) routes ``build_pending``
  through the same pool, so request-path host builds overlap device
  dispatch under load (the ROADMAP "build worker pool" follow-up).

Only HOST work runs here — callers keep every ``jax`` dispatch on their
own thread, preserving the one-thread-owns-the-device rule the offline
runners document (RuntimeConfig.async_dispatch's collective-order
constraint).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Optional, Set

from ..utils.guards import TrackedLock, note_shared_access, register_shared


class BuildWorkerPool:
    """A small thread pool with build accounting.

    ``build_threads`` records the idents that ran builds (tests assert
    builds left the dispatch thread); the inflight gauge and build
    counter land in the shared metrics registry.
    """

    def __init__(self, workers: int = 2, name: str = "mr-build"):
        self.workers = max(1, int(workers))
        self._ex = ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix=name
        )
        # Submitters (engine/scheduler thread) and workers both touch
        # the accounting: a registered mrsan shared object (R10's
        # runtime twin lockset-checks it when sanitizers are armed).
        self._lock = TrackedLock("build_pool")
        register_shared("build_pool", {"build_pool"})
        self._inflight = 0
        self.build_threads: Set[int] = set()
        self.builds = 0

    def submit(
        self,
        fn: Callable,
        *args,
        on_done: Optional[Callable] = None,
        **kwargs,
    ) -> Future:
        """Run ``fn(*args, **kwargs)`` on a worker; ``on_done(future)``
        (when given) fires on the worker thread after completion —
        exceptions from ``fn`` live in the future, not the worker.

        Trace propagation: the submitter's ambient span context is
        captured HERE (contextvars are per-thread, so the worker would
        otherwise start blank) and re-attached around the build — the
        window/request trace keeps its causal chain across the pool
        hop, which is exactly what the self-tracing layer exists to
        show."""
        from ..obs.metrics import record_build_pool
        from ..obs.spans import get_tracer

        tracer = get_tracer()
        ctx = tracer.current_context()
        with self._lock:
            note_shared_access("build_pool")
            self._inflight += 1
            record_build_pool(inflight=self._inflight)

        def _run():
            t0 = time.monotonic()
            try:
                with tracer.attach(ctx):
                    return fn(*args, **kwargs)
            finally:
                with self._lock:
                    note_shared_access("build_pool")
                    self._inflight -= 1
                    self.builds += 1
                    self.build_threads.add(threading.get_ident())
                    record_build_pool(
                        inflight=self._inflight,
                        build_seconds=time.monotonic() - t0,
                    )

        fut = self._ex.submit(_run)
        if on_done is not None:
            fut.add_done_callback(on_done)
        return fut

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def shutdown(self, wait: bool = True, cancel: bool = False) -> None:
        """Stop the pool. ``cancel`` drops builds still QUEUED (never a
        running one) — the fast path for an engine abort, where ranking
        the remaining windows is pointless; callers that coalesce
        pending builds (the dispatch router's burst grouping waits on
        ``Future.result()``) must NOT cancel, or the waiters would see
        CancelledError instead of a graph."""
        try:
            self._ex.shutdown(wait=wait, cancel_futures=cancel)
        except TypeError:  # pragma: no cover - py<3.9 signature
            self._ex.shutdown(wait=wait)
