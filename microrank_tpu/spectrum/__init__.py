from .formulas import FORMULAS, METHODS, spectrum_scores

__all__ = ["FORMULAS", "METHODS", "spectrum_scores"]
