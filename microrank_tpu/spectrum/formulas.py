"""The 13 weighted-spectrum formulas, vectorized (reference component C14).

The reference computes these per-op in a Python if/elif chain over dicts
(online_rca.py:75-142). Here each formula is a pure elementwise jnp
function over the four spectrum-counter arrays [V]; the method name is a
compile-time constant so XLA sees a single fused elementwise kernel.

Formula semantics (including the reference's exact algebraic forms — e.g.
dstar2 = ef^2 / (ep + nf), and the misspelled "simplematcing" key) are
preserved verbatim.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax.numpy as jnp

from ..analysis.contracts import contract


def _dstar2(ef, nf, ep, np_):
    return ef * ef / (ep + nf)


def _ochiai(ef, nf, ep, np_):
    return ef / jnp.sqrt((ep + ef) * (ef + nf))


def _jaccard(ef, nf, ep, np_):
    return ef / (ef + ep + nf)


def _sorensendice(ef, nf, ep, np_):
    return 2 * ef / (2 * ef + ep + nf)


def _m1(ef, nf, ep, np_):
    return (ef + np_) / (ep + nf)


def _m2(ef, nf, ep, np_):
    return ef / (2 * ep + 2 * nf + ef + np_)


def _goodman(ef, nf, ep, np_):
    return (2 * ef - nf - ep) / (2 * ef + nf + ep)


def _tarantula(ef, nf, ep, np_):
    return ef / (ef + nf) / (ef / (ef + nf) + ep / (ep + np_))


def _russellrao(ef, nf, ep, np_):
    return ef / (ef + nf + ep + np_)


def _hamann(ef, nf, ep, np_):
    return (ef + np_ - ep - nf) / (ef + nf + ep + np_)


def _dice(ef, nf, ep, np_):
    return 2 * ef / (ef + nf + ep)


def _simplematching(ef, nf, ep, np_):
    return (ef + np_) / (ef + np_ + nf + ep)


def _rogers(ef, nf, ep, np_):
    return (ef + np_) / (ef + np_ + 2 * nf + 2 * ep)


FORMULAS: Dict[str, Callable] = {
    "dstar2": _dstar2,
    "ochiai": _ochiai,
    "jaccard": _jaccard,
    "sorensendice": _sorensendice,
    "m1": _m1,
    "m2": _m2,
    "goodman": _goodman,
    "tarantula": _tarantula,
    "russellrao": _russellrao,
    "hamann": _hamann,
    "dice": _dice,
    "simplematcing": _simplematching,  # (sic) reference key, online_rca.py:133
    "simplematching": _simplematching,
    "rogers": _rogers,
}

METHODS = tuple(k for k in FORMULAS if k != "simplematching")


@contract(
    ef="float32[V]",
    nf="float32[V]",
    ep="float32[V]",
    np_="float32[V]",
    returns="float32[V]",
)
def spectrum_scores(ef, nf, ep, np_, method: str):
    """Vectorized spectrum score for one (static) method name."""
    try:
        fn = FORMULAS[method]
    except KeyError:
        raise ValueError(f"unknown spectrum method {method!r}") from None
    return fn(ef, nf, ep, np_)
