"""Unified multi-tenant device scheduler.

One parked-window store for every lane (serve, stream, warehouse
backfill) plus the single consumer thread that owns the device when
lanes are co-deployed. See ``DESIGN.md`` § "Unified scheduler".
"""

from .scheduler import DeviceScheduler
from .store import (
    LANE_BACKFILL,
    LANE_INCIDENT,
    LANE_NAMES,
    LANE_SERVE,
    ParkedEntry,
    ParkedWindowStore,
    TokenBucket,
    WeightedFairQueue,
)

__all__ = [
    "DeviceScheduler",
    "LANE_BACKFILL",
    "LANE_INCIDENT",
    "LANE_NAMES",
    "LANE_SERVE",
    "ParkedEntry",
    "ParkedWindowStore",
    "TokenBucket",
    "WeightedFairQueue",
]
