"""DeviceScheduler: the single thread that owns a co-deployed device.

Solo deployments keep their existing owner threads (serve's
BatchScheduler, the stream engine, the replay caller). When serve +
stream + backfill share one device, each lane parks work into the
shared :class:`~microrank_tpu.sched.store.ParkedWindowStore` and THIS
thread — the only one to call ``claim_device_owner`` — dequeues by the
store's lane/fair-share/quota policy and runs each batch's ``runner``
in dispatch order. Lanes that need a synchronous answer (stream's
gated dispatch, replay verification) park a thunk via :meth:`run_on`
and block on its future; the thunk executes here, on the owner thread,
so every ``assert_device_owner`` seam holds without per-thread
delegation.
"""

from __future__ import annotations

import itertools
import threading
import time
from concurrent.futures import Future
from typing import Optional

from ..utils.guards import claim_device_owner
from .store import LANE_NAMES, ParkedEntry, ParkedWindowStore

_IDLE_POLL_S = 0.2
_thunk_seq = itertools.count(1)


def _run_thunks(payloads) -> None:
    for fn, fut in payloads:
        if not fut.set_running_or_notify_cancel():
            continue
        try:
            fut.set_result(fn())
        except BaseException as exc:  # noqa: BLE001 - relayed to the
            # blocked caller via the future; the scheduler must survive.
            fut.set_exception(exc)


class DeviceScheduler(threading.Thread):
    """One consumer thread draining the shared parked-window store."""

    def __init__(self, store: ParkedWindowStore,
                 name: str = "mr-device-sched"):
        super().__init__(name=name, daemon=True)
        self.store = store
        self._stopping = False
        self._draining = True
        self._busy = False
        self.dispatched = 0     # batches run
        self.errors = 0         # runner exceptions contained

    # ------------------------------------------------------------- intake
    def submit_thunk(self, lane: int, tenant: str, fn,
                     cost: float = 1.0) -> Future:
        """Park ``fn`` for execution on the scheduler thread; returns
        its Future. Thunks carry a unique bucket key so each dequeues
        as its own singleton batch."""
        fut: Future = Future()
        self.store.park(ParkedEntry(
            lane, tenant, ("thunk", next(_thunk_seq)), (fn, fut),
            _run_thunks, cost=cost,
        ))
        return fut

    def run_on(self, lane: int, tenant: str, fn, cost: float = 1.0):
        """Run ``fn`` on the device-owner thread and return its result
        (raising what it raised). Called FROM the scheduler thread it
        runs inline — a runner may re-enter without deadlocking."""
        if threading.current_thread() is self:
            return fn()
        return self.submit_thunk(lane, tenant, fn, cost=cost).result()

    def kick(self, force: bool = False) -> None:
        """Wake the scheduler; ``force=True`` flushes partial serve
        buckets on the next pass (drain / test barriers)."""
        with self.store.cond:
            if force:
                self._force_once = True
            self.store.cond.notify_all()

    _force_once = False

    # -------------------------------------------------------------- drive
    def run(self) -> None:  # pragma: no branch - loop structure
        claim_device_owner("device-scheduler")
        store = self.store
        while True:
            now = time.monotonic()
            deadline = store.next_deadline()
            timeout = _IDLE_POLL_S if deadline is None else max(
                0.0, min(_IDLE_POLL_S, deadline - now)
            )
            with store.cond:
                if not store._buckets and not self._stopping:
                    store.cond.wait(timeout=timeout)
                stopping = self._stopping
                force = (stopping and self._draining) or self._force_once
                self._force_once = False
            for batch in store.take_ready(force=force):
                self._dispatch(batch)
            with store.cond:
                if stopping and not store._buckets:
                    break
        if not self._draining:
            for batch in store.take_ready(force=True):
                for e in batch:
                    if e.expire is not None:
                        try:
                            e.expire(e.payload)
                        except Exception:  # noqa: BLE001
                            pass

    def _dispatch(self, batch) -> None:
        with self.store.cond:
            self._busy = True
        try:
            batch[0].runner([e.payload for e in batch])
            self.dispatched += 1
            self._record(batch)
        except Exception:  # noqa: BLE001 - a lane's runner failing
            # (serve already degrades internally; a raw raise here
            # would silently kill every co-deployed lane's dispatch)
            self.errors += 1
        finally:
            with self.store.cond:
                self._busy = False
                self.store.cond.notify_all()

    # ---------------------------------------------------------- lifecycle
    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Block until the store is empty and no batch is running."""
        t_end = time.monotonic() + timeout
        with self.store.cond:
            while self.store._buckets or self._busy:
                left = t_end - time.monotonic()
                if left <= 0:
                    return False
                self.store.cond.wait(timeout=min(left, _IDLE_POLL_S))
        return True

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        with self.store.cond:
            self._stopping = True
            self._draining = drain
            self.store.cond.notify_all()
        if self.is_alive():
            self.join(timeout=timeout)

    # ------------------------------------------------------------ metrics
    def _record(self, batch) -> None:
        try:
            from ..obs.metrics import (
                record_sched_dispatch,
                record_sched_wait,
            )

            lane = LANE_NAMES.get(batch[0].lane, "serve")
            record_sched_dispatch(lane, batch[0].tenant, len(batch))
            record_sched_wait(
                lane, max(0.0, time.monotonic() - batch[0].parked)
            )
        except Exception:  # pragma: no cover - metrics best-effort
            pass
