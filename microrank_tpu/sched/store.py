"""The parked-window store: one queue for every device workload.

Serve's bucket batcher, stream's gated dispatch and warehouse/replay
backfill used to each own a private queue and collide when co-deployed
on one device. Here they all park prepared work into ONE store, keyed by
the dispatch router's ``bucket_key`` (kernel + padded leaf shapes — the
jit-cache key modulo config), and a single dequeue policy decides what
the device runs next:

* **priority lanes** — open-incident hot path (``LANE_INCIDENT``) >
  interactive serve (``LANE_SERVE``) > backfill (``LANE_BACKFILL``).
  ``take_ready`` returns every ready batch of a higher lane before any
  batch of a lower one, so an open incident's windows can never queue
  behind historical backfill (priority inversion is impossible by
  construction: ordering is by lane FIRST, and nothing a lower lane
  holds — no lock, no token state — is needed to dispatch a higher
  lane's batch).
* **weighted fair share** — stride scheduling over tenants: each
  dispatched window advances its tenant's virtual time by
  ``cost / weight``; the next batch goes to the backlogged tenant with
  the smallest virtual time, so long-run shares converge to the
  configured weights (SchedConfig.tenant_weights).
* **soft token-bucket quotas** — SchedConfig.tenant_rates refill
  per-tenant buckets in windows/second; an out-of-tokens tenant sorts
  behind every in-quota tenant but still dispatches when nothing else
  is ready. The scheduler is work-conserving: quotas shape ORDER under
  contention, they never idle the device or drop verdicts.
* **deadline expiry at dequeue** — entries carrying an absolute
  deadline (serve's per-request ``deadline_ms``) that lapsed while
  parked are expired here (their ``expire`` callback answers the 504)
  instead of burning device time on an abandoned answer.

Thread-safety: producers (HTTP threads via the serve scheduler, the
stream engine thread, backfill threads) park concurrently; one consumer
(the serve scheduler thread solo, or the DeviceScheduler thread when
co-deployed) drains. All state is guarded by one condition.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

LANE_INCIDENT = 0
LANE_SERVE = 1
LANE_BACKFILL = 2

LANE_NAMES = {
    LANE_INCIDENT: "incident",
    LANE_SERVE: "serve",
    LANE_BACKFILL: "backfill",
}

_seq = itertools.count(1)


class TokenBucket:
    """Windows/second refill up to ``burst``; time is passed in so the
    policy is deterministic under test. Not thread-safe — the store's
    condition guards every touch."""

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = max(0.0, float(rate))
        self.burst = max(0.0, float(burst))
        self.tokens = self.burst if self.rate > 0 else 0.0
        self._last = now

    def refill(self, now: float) -> None:
        if self.rate <= 0:
            return
        dt = max(0.0, now - self._last)
        self.tokens = min(self.burst, self.tokens + dt * self.rate)
        self._last = now

    def take(self, n: float) -> None:
        # May go negative: a batch dispatches whole even when the
        # tenant's remaining tokens cover only part of it — the debt
        # delays its NEXT batch, which is the soft-quota semantics.
        self.tokens -= n


class _Tenant:
    __slots__ = ("name", "weight", "bucket", "vt", "dispatched")

    def __init__(self, name, weight, bucket):
        self.name = name
        self.weight = max(1e-9, float(weight))
        self.bucket: Optional[TokenBucket] = bucket
        self.vt = 0.0           # stride-scheduling virtual time
        self.dispatched = 0     # windows dispatched (fair-share stats)


class ParkedEntry:
    """One parked unit of device work.

    Serve parks one PendingWindow per entry (``payload``), batched by
    bucket key at dequeue; stream and backfill park pre-formed dispatch
    thunks (``payload`` is the thunk, ``key`` unique) that dequeue as
    singleton batches. ``runner(payloads)`` executes the batch on the
    consuming (device-owner) thread; ``expire(payload)`` answers an
    entry whose deadline lapsed while parked.
    """

    __slots__ = (
        "lane", "tenant", "key", "payload", "runner", "expire",
        "parked", "deadline", "cost", "seq",
    )

    def __init__(
        self,
        lane: int,
        tenant: str,
        key: Tuple,
        payload,
        runner: Callable[[list], None],
        expire: Optional[Callable] = None,
        deadline: Optional[float] = None,
        cost: float = 1.0,
    ):
        self.lane = int(lane)
        self.tenant = str(tenant)
        self.key = key
        self.payload = payload
        self.runner = runner
        self.expire = expire
        self.parked = time.monotonic()
        self.deadline = deadline
        self.cost = float(cost)
        self.seq = next(_seq)


class ParkedWindowStore:
    """The one parked-window store; see the module docstring."""

    def __init__(self, config, serve_cfg=None):
        # ``config`` is the SchedConfig; ``serve_cfg`` (ServeConfig)
        # supplies the serve lane's batching knobs (max_batch_windows /
        # max_wait_ms) so the store flushes serve buckets exactly like
        # the old MicroBatcher did.
        self.cfg = config
        self.serve_cfg = serve_cfg
        self.cond = threading.Condition()
        # (lane, bucket key) -> FIFO of ParkedEntry (insertion = age).
        self._buckets: Dict[Tuple[int, Tuple], List[ParkedEntry]] = {}
        self._tenants: Dict[str, _Tenant] = {}
        self._weights = dict(config.tenant_weights)
        self._rates = dict(config.tenant_rates)
        self._global_vt = 0.0
        self.expired = 0

    # ------------------------------------------------------------ tenants
    def _tenant(self, name: str, now: float) -> _Tenant:
        t = self._tenants.get(name)
        if t is None:
            rate = self._rates.get(name)
            bucket = (
                None
                if rate is None
                else TokenBucket(rate, self.cfg.burst, now)
            )
            t = _Tenant(
                name, self._weights.get(name, self.cfg.default_weight),
                bucket,
            )
            # A newly active tenant joins at the current virtual time —
            # idling must not bank credit against busy tenants.
            t.vt = self._global_vt
            self._tenants[name] = t
        return t

    def tenant_shares(self) -> Dict[str, int]:
        """Windows dispatched per tenant (fair-share tests/metrics)."""
        with self.cond:
            return {
                name: t.dispatched for name, t in self._tenants.items()
            }

    # ------------------------------------------------------------- intake
    def park(self, entry: ParkedEntry) -> None:
        with self.cond:
            self._buckets.setdefault(
                (entry.lane, entry.key), []
            ).append(entry)
            self.cond.notify_all()
        self._record_depth()

    def pending(self, lane: Optional[int] = None) -> int:
        with self.cond:
            return sum(
                len(b)
                for (ln, _), b in self._buckets.items()
                if lane is None or ln == lane
            )

    def _lane_cap(self, lane: int) -> int:
        if lane == LANE_SERVE and self.serve_cfg is not None:
            return max(1, int(self.serve_cfg.max_batch_windows))
        return 1

    def _lane_wait_s(self, lane: int) -> float:
        if lane == LANE_SERVE and self.serve_cfg is not None:
            return max(0.0, float(self.serve_cfg.max_wait_ms)) / 1e3
        return 0.0  # thunk lanes are ready the moment they park

    def next_deadline(self) -> Optional[float]:
        """Monotonic time the oldest parked entry must flush by (the
        consumer's wait bound); None when the store is empty."""
        with self.cond:
            deadline = None
            for (lane, _), bucket in self._buckets.items():
                if not bucket:
                    continue
                d = bucket[0].parked + self._lane_wait_s(lane)
                deadline = d if deadline is None else min(deadline, d)
            return deadline

    def wait(self, timeout: float) -> None:
        with self.cond:
            if not self._buckets:
                self.cond.wait(timeout=max(0.0, timeout))

    # ------------------------------------------------------------ dequeue
    def take_ready(
        self,
        force: bool = False,
        lanes: Optional[Tuple[int, ...]] = None,
        now: Optional[float] = None,
    ) -> List[List[ParkedEntry]]:
        """Pop every ready batch, ordered for dispatch.

        Ready = a bucket holding a full batch (lane cap), an aged one
        (oldest entry past the lane's max wait), or anything at all
        under ``force`` (drain). Ordering: lane priority first; within
        a lane, in-quota tenants before out-of-quota ones, then
        smallest tenant virtual time, then oldest. Tokens are charged
        and virtual times advanced HERE — the returned order is the
        dispatch order.
        """
        now = time.monotonic() if now is None else now
        expired: List[ParkedEntry] = []
        out: List[List[ParkedEntry]] = []
        with self.cond:
            candidates: Dict[int, List[List[ParkedEntry]]] = {}
            for (lane, key) in list(self._buckets):
                bucket = self._buckets[(lane, key)]
                live = []
                for e in bucket:
                    if e.deadline is not None and now > e.deadline:
                        expired.append(e)
                    else:
                        live.append(e)
                bucket[:] = live
                if not bucket:
                    del self._buckets[(lane, key)]
                    continue
                if lanes is not None and lane not in lanes:
                    continue
                cap = self._lane_cap(lane)
                wait_s = self._lane_wait_s(lane)
                ready = candidates.setdefault(lane, [])
                while len(bucket) >= cap:
                    ready.append(bucket[:cap])
                    del bucket[:cap]
                if bucket and (
                    force or now - bucket[0].parked >= wait_s
                ):
                    ready.append(bucket[:])
                    bucket.clear()
                if not bucket:
                    del self._buckets[(lane, key)]
            for lane in sorted(candidates):
                out.extend(self._order_lane(candidates[lane], now))
            self.expired += len(expired)
        # Expiry callbacks resolve futures / emit journal events —
        # outside the lock so a callback touching the store (or a
        # waiter it wakes) cannot deadlock.
        for e in expired:
            if e.expire is not None:
                try:
                    e.expire(e.payload)
                except Exception:  # noqa: BLE001 - expiry is cleanup;
                    # one bad callback must not kill the dequeue.
                    pass
        if expired:
            self._record_expired(len(expired))
        self._record_depth()
        return out

    def _order_lane(
        self, batches: List[List[ParkedEntry]], now: float
    ) -> List[List[ParkedEntry]]:
        """Order one lane's ready batches by quota standing, then
        stride virtual time, then age — charging tokens and advancing
        virtual time as each batch is emitted (the emitted order IS
        the dispatch order, so later picks see earlier charges)."""
        for b in batches:
            t = self._tenant(b[0].tenant, now)
            if t.bucket is not None:
                t.bucket.refill(now)
        ordered: List[List[ParkedEntry]] = []
        remaining = list(batches)
        while remaining:
            def _rank(batch):
                t = self._tenants[batch[0].tenant]
                throttled = (
                    t.bucket is not None and t.bucket.tokens < 1.0
                )
                return (
                    1 if throttled else 0,
                    t.vt,
                    batch[0].parked,
                    batch[0].seq,
                )

            best = min(remaining, key=_rank)
            remaining.remove(best)
            throttled = _rank(best)[0] == 1
            for e in best:
                t = self._tenant(e.tenant, now)
                t.vt += e.cost / t.weight
                t.dispatched += 1
                if t.bucket is not None:
                    t.bucket.take(e.cost)
                self._global_vt = max(self._global_vt, t.vt)
            if throttled:
                self._record_throttled(best[0].tenant)
            ordered.append(best)
        return ordered

    # ------------------------------------------------------------ metrics
    def _record_depth(self) -> None:
        try:
            from ..obs.metrics import record_sched_parked

            with self.cond:
                depths = {name: 0 for name in LANE_NAMES.values()}
                for (lane, _), bucket in self._buckets.items():
                    depths[LANE_NAMES.get(lane, "serve")] += len(bucket)
            for name, depth in depths.items():
                record_sched_parked(name, depth)
        except Exception:  # pragma: no cover - metrics best-effort
            pass

    @staticmethod
    def _record_expired(n: int) -> None:
        try:
            from ..obs.metrics import record_sched_expired

            record_sched_expired(n)
        except Exception:  # pragma: no cover
            pass

    @staticmethod
    def _record_throttled(tenant: str) -> None:
        try:
            from ..obs.metrics import record_sched_throttled

            record_sched_throttled(tenant)
        except Exception:  # pragma: no cover
            pass


class WeightedFairQueue:
    """Tenant-keyed FIFOs popped by stride scheduling — the weighted
    upgrade of the serve scheduler's old round-robin ``_pop_fair``.
    With all-equal weights the pop order is exactly the old round-robin
    interleave (ties break by tenant arrival order); unequal weights
    give proportionally more turns to heavier tenants. NOT thread-safe:
    the owner holds its own condition around every call (the serve
    scheduler's ``_cond``), exactly like the OrderedDict it replaces.
    """

    def __init__(self, weights=None, default_weight: float = 1.0):
        self._weights = dict(weights or {})
        self._default = float(default_weight)
        self._queues: "Dict[str, List]" = {}
        self._vt: Dict[str, float] = {}
        self._arrival: Dict[str, int] = {}
        self._global_vt = 0.0
        self._n = 0

    def push(self, tenant: str, item) -> None:
        q = self._queues.get(tenant)
        if q is None:
            q = self._queues[tenant] = []
            self._arrival.setdefault(tenant, len(self._arrival))
            # Join at the current virtual time: returning tenants get
            # no banked credit for having been idle.
            self._vt[tenant] = max(
                self._vt.get(tenant, 0.0), self._global_vt
            )
        q.append(item)
        self._n += 1

    def pop(self):
        if not self._n:
            return None
        tenant = min(
            (t for t, q in self._queues.items() if q),
            key=lambda t: (self._vt[t], self._arrival[t]),
        )
        q = self._queues[tenant]
        item = q.pop(0)
        self._n -= 1
        w = max(1e-9, self._weights.get(tenant, self._default))
        self._vt[tenant] += 1.0 / w
        self._global_vt = max(self._global_vt, self._vt[tenant])
        if not q:
            del self._queues[tenant]
        return item

    def drain_items(self) -> List:
        """Remove and return every queued item (non-drain shutdown)."""
        items = [x for q in self._queues.values() for x in q]
        self._queues.clear()
        self._n = 0
        return items

    def __len__(self) -> int:
        return self._n

    def __bool__(self) -> bool:
        return self._n > 0
