"""Rank provenance: device-side attribution traces + explain bundles.

MicroRank's output is a ranked suspect list, but every score is opaque:
the weighted-spectrum formulas decompose into the four counters
ef/nf/ep/np and the two PPR weight vectors, yet none of that survives
the jitted program. This subsystem makes the *verdicts* observable:

* ``extract`` — explained twins of the rank programs: the attribution
  tensors (per-suspect counter decomposition, per-formula term values,
  normal-vs-abnormal PPR mass, top-k contributing coverage columns)
  ride the existing result fetch, folded into the kernels' epilogue the
  way FUSED-PAGERANK folds post-passes into the iteration — for every
  kernel family (coo/csr/packed/pcsr) and the sharded path;
* ``bundle`` — the host materialization: ``ExplainBundle`` (JSON +
  human-readable table), written on demand and automatically on
  incident open (next to the flight dump, cross-linked in its
  manifest);
* ``oracle`` — the float64 numpy twin the parity suite pins the device
  attributions against, tie-aware;
* ``store`` — bounded in-process ring of recent bundles, served by the
  obs server's ``GET /explainz?window=...`` endpoint.

Gated by ``ExplainConfig``: off (the default) dispatches the unchanged
rank programs, so the hot path pays nothing.
"""

from .bundle import ExplainBundle, ExplainContext, build_bundle
from .extract import (
    rank_window_explained_blob_core,
    rank_window_explained_core,
)
from .store import get_explain_store

__all__ = [
    "ExplainBundle",
    "ExplainContext",
    "build_bundle",
    "get_explain_store",
    "rank_window_explained_core",
    "rank_window_explained_blob_core",
]
