"""Float64 numpy oracle for rank provenance (the parity pin).

Recomputes, over the UNCOLLAPSED padded COO window graph and entirely
in float64 ``np.bincount`` arithmetic (the sparse oracle's summation
structure, independent of every device kernel's):

* the per-suspect spectrum counters ef/nf/ep/np and the per-formula
  term values across all 13 formulas;
* the normal/abnormal PPR weight split;
* the per-trace coverage contributions ``p_sr[v, t] * rv[t]`` at
  convergence — optionally aggregated per trace KIND (what a
  kind-collapsed device build's columns report, each column standing
  for its group with the multiplicity folded into p_sr).

tests/test_explain.py pins every device kernel family and the sharded
path against this, tie-aware.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..analysis.contracts import contract
from ..config import PageRankConfig, SpectrumConfig
from ..graph.structures import WindowGraph
from ..rank_backends.numpy_ref import spectrum_score
from ..rank_backends.sparse_oracle import (
    _iterate_sparse,
    _partition_arrays,
    _preference,
    recompute_kinds,
)
from ..spectrum.formulas import METHODS


def _kind_groups(inc_trace, inc_op, tracelen, n_traces: int):
    """(group_of[t], representative[g]) — independent kind grouping by
    byte signature (same equivalence as recompute_kinds), with each
    group's representative the LOWEST trace index (matching the
    collapse build's retention-map choice)."""
    order = np.lexsort((inc_op, inc_trace))
    tr = np.asarray(inc_trace)[order]
    op = np.asarray(inc_op)[order]
    starts = np.searchsorted(tr, np.arange(n_traces), side="left")
    ends = np.searchsorted(tr, np.arange(n_traces), side="right")
    sigs: Dict = {}
    group_of = np.zeros(n_traces, dtype=np.int64)
    reps: List[int] = []
    for t in range(n_traces):
        key = (op[starts[t]:ends[t]].tobytes(), float(tracelen[t]))
        g = sigs.setdefault(key, len(sigs))
        group_of[t] = g
        if g == len(reps):
            reps.append(t)
    return group_of, np.asarray(reps, dtype=np.int64)


def _partition_explain(g, anomaly: bool, cfg: PageRankConfig):
    """One partition's f64 (weight[v_pad], trace_num[v_pad], rv[T],
    arrays dict, kinds)."""
    p = _partition_arrays(g)
    v_pad = g.op_present.shape[0]
    kinds = recompute_kinds(
        p["inc_trace"], p["inc_op"], p["tracelen"], p["n_traces"]
    )
    pref = _preference(kinds, p["tracelen"], anomaly, cfg)
    v_s, v_r = _iterate_sparse(p, pref, v_pad, cfg)
    total = float(v_s[p["op_present"]].sum())
    weight = np.where(p["op_present"], v_s * total / p["n_ops"], 0.0)
    trace_num = np.bincount(p["inc_op"], minlength=v_pad).astype(np.int64)
    return weight, trace_num, np.asarray(v_r, dtype=np.float64), p


def _contributions(
    p: dict,
    rv: np.ndarray,
    vocab_idx: int,
    trace_ids: List,
    aggregate_kinds: bool,
    tracelen,
) -> List[Tuple[str, float]]:
    """[(trace_id, contribution)] for one suspect, descending, ties by
    ascending column order — per trace, or aggregated per kind with the
    group representative's id (the collapsed device build's view)."""
    sel = p["inc_op"] == vocab_idx
    tr = p["inc_trace"][sel]
    contrib = p["sr_val"][sel] * rv[tr]
    per_trace = np.zeros(p["n_traces"], dtype=np.float64)
    per_trace[tr] = contrib
    if aggregate_kinds:
        group_of, reps = _kind_groups(
            p["inc_trace"], p["inc_op"], tracelen, p["n_traces"]
        )
        agg = np.zeros(len(reps), dtype=np.float64)
        np.add.at(agg, group_of, per_trace)
        ids = [trace_ids[int(r)] for r in reps]
        vals = agg
    else:
        ids = list(trace_ids[: p["n_traces"]])
        vals = per_trace
    order = sorted(
        range(len(ids)), key=lambda i: (-vals[i], i)
    )
    return [
        (str(ids[i]), float(vals[i])) for i in order if vals[i] > 0.0
    ]


@contract(graph="windowgraph", returns="any")
def explain_window_oracle(
    graph: WindowGraph,
    op_names: List[str],
    normal_trace_ids: List,
    abnormal_trace_ids: List,
    pagerank_cfg: PageRankConfig = PageRankConfig(),
    spectrum_cfg: SpectrumConfig = SpectrumConfig(),
    top_traces: Optional[int] = None,
    aggregate_kinds: bool = False,
) -> dict:
    """Full f64 provenance of one UNCOLLAPSED window graph.

    Returns ``{"suspects": [...]}`` shaped like an ExplainBundle's
    suspect list: rank/op/score, counters, per-formula terms, mass, and
    ``top_traces`` per partition (ALL positive contributors when
    ``top_traces`` is None — tie-aware set comparison truncates at the
    caller's cut).
    """
    n_weight, n_num, rv_n, n_p = _partition_explain(
        graph.normal, False, pagerank_cfg
    )
    a_weight, a_num, rv_a, a_p = _partition_explain(
        graph.abnormal, True, pagerank_cfg
    )
    in_a = np.asarray(graph.abnormal.op_present)
    in_n = np.asarray(graph.normal.op_present)
    eps = spectrum_cfg.eps
    cells: Dict[int, Dict[str, float]] = {}
    for vi in np.flatnonzero(in_a | in_n):
        cell: Dict[str, float] = {}
        if in_a[vi]:
            a = a_weight[vi]
            cell["ef"] = a * a_num[vi]
            cell["nf"] = a * (a_p["n_traces"] - a_num[vi])
            if in_n[vi]:
                nw = n_weight[vi]
                cell["ep"] = nw * n_num[vi]
                cell["np"] = nw * (n_p["n_traces"] - n_num[vi])
            else:
                cell["ep"] = eps
                cell["np"] = eps
        else:
            nw = n_weight[vi]
            cell["ef"] = eps
            cell["nf"] = eps
            cell["ep"] = (1 + nw) * n_num[vi]
            cell["np"] = n_p["n_traces"] - n_num[vi]
        cells[int(vi)] = cell
    scored = {
        vi: spectrum_score(cell, spectrum_cfg.method)
        for vi, cell in cells.items()
    }
    ranked = sorted(scored.items(), key=lambda x: (-x[1], op_names[x[0]]))
    top = ranked[: spectrum_cfg.n_rows]
    tlen_n = np.asarray(graph.normal.tracelen)
    tlen_a = np.asarray(graph.abnormal.tracelen)
    suspects = []
    for rank, (vi, score) in enumerate(top, 1):
        cell = cells[vi]
        tr_n = _contributions(
            n_p, rv_n, vi, normal_trace_ids, aggregate_kinds, tlen_n
        )
        tr_a = _contributions(
            a_p, rv_a, vi, abnormal_trace_ids, aggregate_kinds, tlen_a
        )
        if top_traces is not None:
            tr_n = tr_n[:top_traces]
            tr_a = tr_a[:top_traces]
        suspects.append(
            {
                "rank": rank,
                "op": op_names[vi],
                "score": float(score),
                "counters": {k: float(cell[k]) for k in cell},
                "mass": {
                    "normal_weight": float(n_weight[vi]),
                    "abnormal_weight": float(a_weight[vi]),
                },
                "terms": {
                    m: float(spectrum_score(cell, m)) for m in METHODS
                },
                "top_traces": {"normal": tr_n, "abnormal": tr_a},
            }
        )
    return {"suspects": suspects}
