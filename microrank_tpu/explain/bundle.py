"""Host materialization of rank provenance: the ExplainBundle.

The device explained twins return raw attribution tensors over padded
vocab/column indices; this module joins them with the build's op names
and coverage-column retention map into a self-contained, serializable
record:

* JSON (``explain_bundle.json``) — machine-readable, schema-versioned;
* human-readable table (``explain_bundle.txt``) — what an operator
  reads next to an incident.

A bundle names, per suspect: rank + score, the ef/nf/ep/np counter
decomposition, the normal/abnormal PPR mass split, the score every
spectrum formula would have assigned (cross-formula agreement is
itself a confidence signal), and the top contributing traces per
partition (trace id, contribution ``p_sr[v,t] * rv[t]``, and the
column's multiplicity on kind-collapsed builds).
"""

from __future__ import annotations

import json
import math
import time
from dataclasses import dataclass
from pathlib import Path
from typing import List, Optional, Tuple

import numpy as np

from ..analysis.contracts import contract
from ..spectrum.formulas import METHODS

BUNDLE_SCHEMA = 1
BUNDLE_JSON = "explain_bundle.json"
BUNDLE_TXT = "explain_bundle.txt"

COUNTER_NAMES = ("ef", "nf", "ep", "np")


@dataclass
class ExplainContext:
    """Build-side retention the device outputs are joined against:
    per partition, coverage column -> (representative) trace id and the
    column's multiplicity (1 on uncollapsed builds)."""

    normal_trace_ids: List
    abnormal_trace_ids: List
    normal_mult: List[int]
    abnormal_mult: List[int]

    @classmethod
    def from_build(cls, graph, ids_n, ids_a, map_n, map_a):
        """Join build_window_graph's trace-id lists with its
        coverage-column retention map (None map = identity: every
        column IS one trace)."""

        def one(part, ids, cmap):
            n_cols = int(np.asarray(part.n_cols))
            if n_cols < 0 or cmap is None:
                return list(ids), [1] * len(ids)
            col_ids = [ids[int(i)] for i in np.asarray(cmap)[:n_cols]]
            mult = [
                int(m)
                for m in np.asarray(part.kind)[:n_cols]
            ]
            return col_ids, mult

        cn, mn = one(graph.normal, ids_n, map_n)
        ca, ma = one(graph.abnormal, ids_a, map_a)
        return cls(
            normal_trace_ids=cn,
            abnormal_trace_ids=ca,
            normal_mult=mn,
            abnormal_mult=ma,
        )

    def columns(self, partition: int) -> Tuple[List, List[int]]:
        if partition == 0:
            return self.normal_trace_ids, self.normal_mult
        return self.abnormal_trace_ids, self.abnormal_mult


@contract(returns="any")
def build_bundle(
    outs,
    op_names: List[str],
    ectx: Optional[ExplainContext],
    method: str,
    kernel: str = "",
    window: Optional[dict] = None,
    trigger: str = "on_demand",
) -> "ExplainBundle":
    """Join one fetched explained-program output tuple (host arrays —
    call ``jax.device_get`` first) with the build context into an
    ExplainBundle. ``ectx=None`` degrades gracefully: contributing
    columns are reported by column index instead of trace id."""
    (
        top_idx, top_scores, n_valid, _residuals, n_iters,
        counters, terms, mass, trace_idx, trace_val,
    ) = (np.asarray(o) for o in outs[:10])
    n = min(int(n_valid), counters.shape[1])
    suspects = []
    for i in range(n):
        vi = int(top_idx[i])
        traces = {}
        for p, pname in enumerate(("normal", "abnormal")):
            cols, mult = (
                ectx.columns(p) if ectx is not None else (None, None)
            )
            entries = []
            for j in range(trace_idx.shape[2]):
                val = float(trace_val[p, i, j])
                if not math.isfinite(val) or val <= 0.0:
                    continue
                ci = int(trace_idx[p, i, j])
                entry = {"column": ci, "contribution": val}
                if cols is not None and ci < len(cols):
                    entry["trace"] = str(cols[ci])
                    entry["multiplicity"] = int(mult[ci])
                entries.append(entry)
            traces[pname] = entries
        suspects.append(
            {
                "rank": i + 1,
                "op": op_names[vi] if vi < len(op_names) else str(vi),
                "score": float(top_scores[i]),
                "counters": {
                    cn: float(counters[c, i])
                    for c, cn in enumerate(COUNTER_NAMES)
                },
                "mass": {
                    "normal_weight": float(mass[0, i]),
                    "abnormal_weight": float(mass[1, i]),
                },
                "terms": {
                    m: float(terms[mi, i])
                    for mi, m in enumerate(METHODS)
                },
                "top_traces": traces,
            }
        )
    data = {
        "schema": BUNDLE_SCHEMA,
        "generated_ts": time.time(),
        "trigger": trigger,
        "method": method,
        "kernel": kernel,
        "iterations": int(n_iters),
        "window": dict(window or {}),
        "suspects": suspects,
    }
    return ExplainBundle(data)


@dataclass
class ExplainBundle:
    """One window's rank provenance, serializable both ways."""

    data: dict

    # ------------------------------------------------------------ access
    @property
    def suspects(self) -> List[dict]:
        return self.data.get("suspects", [])

    @property
    def window(self) -> dict:
        return self.data.get("window", {})

    def top1(self) -> Optional[str]:
        s = self.suspects
        return s[0]["op"] if s else None

    # ------------------------------------------------------- serialization
    def to_json(self, indent: int = 2) -> str:
        return json.dumps(self.data, indent=indent)

    def to_table(self) -> str:
        """Human-readable rendering (the .txt artifact / `cli explain`)."""
        d = self.data
        lines = [
            "Rank provenance — window "
            f"{d.get('window', {}).get('start', '?')} "
            f"(kernel={d.get('kernel') or '?'}, "
            f"method={d.get('method')}, "
            f"iterations={d.get('iterations')})",
        ]
        for s in self.suspects:
            c = s["counters"]
            m = s["mass"]
            lines.append(
                f"  #{s['rank']} {s['op']}  score={s['score']:.6g}"
            )
            lines.append(
                f"      counters ef={c['ef']:.6g} nf={c['nf']:.6g} "
                f"ep={c['ep']:.6g} np={c['np']:.6g}   "
                f"mass normal={m['normal_weight']:.6g} "
                f"abnormal={m['abnormal_weight']:.6g}"
            )
            ranked_terms = sorted(
                s["terms"].items(), key=lambda kv: -kv[1]
            )
            lines.append(
                "      formulas "
                + " ".join(f"{k}={v:.4g}" for k, v in ranked_terms[:5])
                + (" ..." if len(ranked_terms) > 5 else "")
            )
            for pname in ("abnormal", "normal"):
                entries = s["top_traces"].get(pname, [])
                if not entries:
                    continue
                lines.append(
                    f"      {pname} traces "
                    + " ".join(
                        (
                            f"{e.get('trace', e['column'])}"
                            + (
                                f"(x{e['multiplicity']})"
                                if e.get("multiplicity", 1) != 1
                                else ""
                            )
                            + f"={e['contribution']:.4g}"
                        )
                        for e in entries
                    )
                )
        return "\n".join(lines) + "\n"

    def journal_record(self) -> dict:
        """Compact record for the run journal's ``explain`` event (the
        CI smoke cross-checks bundle top-1/ef against it)."""
        s0 = self.suspects[0] if self.suspects else None
        return {
            "start": self.window.get("start"),
            "end": self.window.get("end"),
            "kernel": self.data.get("kernel"),
            "trigger": self.data.get("trigger"),
            "suspects": len(self.suspects),
            "top1": s0["op"] if s0 else None,
            "ef_top1": s0["counters"]["ef"] if s0 else None,
        }

    def write(self, dest) -> Path:
        """Write JSON + table under ``dest`` (a directory); returns the
        JSON path. Atomic per file (tmp+fsync+rename): incident bundles
        are read back by `cli explain` and warm restarts — a SIGKILL
        mid-write must leave either no bundle or a whole one."""
        from ..utils.atomic import atomic_write_text

        dest = Path(dest)
        dest.mkdir(parents=True, exist_ok=True)
        path = atomic_write_text(dest / BUNDLE_JSON, self.to_json())
        atomic_write_text(dest / BUNDLE_TXT, self.to_table())
        return path

    @classmethod
    def load(cls, path) -> "ExplainBundle":
        return cls(json.loads(Path(path).read_text()))
