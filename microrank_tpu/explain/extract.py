"""Device-side attribution extraction: the explained rank twins.

``rank_window_explained_core`` is ``rank_window_traced_core`` plus a
provenance epilogue fused into the same program (the FUSED-PAGERANK
shape — post-passes ride the iteration's program, arxiv 2203.09284):

* **counters** float32[4, Ke] — the method-independent spectrum
  counters (ef, nf, ep, np) gathered at the explained suspects;
* **terms** float32[M, Ke] — the score every one of the 13 formulas
  assigns those counters (METHODS order) — how the configured formula's
  verdict compares across the whole family;
* **mass** float32[2, Ke] — the normal/abnormal PPR weight split
  (row 0 normal, row 1 abnormal) the counters multiply;
* **trace_idx/trace_val** int32/float32[2, Ke, J] — per partition, the
  top-J contributing coverage columns of each suspect and their
  contributions ``p_sr[v, t] * rv[t]`` (the forward coverage term at
  convergence), recovered from whatever coverage representation the
  kernel actually staged: bitmap rows (packed family), COO entries
  (coo/dense/pallas), CSR row ranges (csr), or the ELL slab (pcsr) —
  ``device_subset`` stripping never blocks the epilogue. Entries are
  -inf-padded past each partition's live columns; hosts map indices
  back to trace ids via the build's coverage-column retention map.

Everything is carried in the program's output tuple — one fetch, no
host sync — and the epilogue only exists in the explained twins:
``ExplainConfig.enabled=False`` dispatches the unchanged programs.

Sharded: the same epilogue runs under ``shard_map`` (psum_axis set) —
entry-sharded kernels psum their scatter partials into the replicated
[Ke, T] contribution matrix; the trace-sharded packed kernels
all-gather their local column blocks — so the attribution outputs are
replicated exactly like the rank outputs
(``parallel.sharded_rank.rank_windows_explained_sharded``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..analysis.contracts import contract
from ..config import ExplainConfig, PageRankConfig, SpectrumConfig
from ..graph.structures import PartitionGraph, WindowGraph
from ..rank_backends.jax_tpu import (
    spectrum_counters,
    top_k_tiebroken,
    unpack_bits,
    window_weights_full,
)
from ..spectrum.formulas import METHODS, spectrum_scores

# Explain output tuple layout, after the 5 traced-rank outputs:
# (counters[4,Ke], terms[M,Ke], mass[2,Ke], trace_idx[2,Ke,J],
#  trace_val[2,Ke,J]).
N_EXPLAIN_OUTS = 5


def _slot_map(top_idx, v_pad: int):
    """int32[v_pad + 1] mapping op index -> suspect slot (K = scrap).

    top_idx rows are distinct by construction (top_k_tiebroken sorts a
    permutation), so the scatter never collides. The +1 row absorbs the
    csr path's past-the-end searchsorted result.
    """
    k = top_idx.shape[0]
    return (
        jnp.full((v_pad + 1,), k, jnp.int32)
        .at[top_idx]
        .set(jnp.arange(k, dtype=jnp.int32))
    )


def _contrib_rows(
    g: PartitionGraph,
    top_idx,
    rv,
    kernel: str,
    psum_axis: str | None,
):
    """float32[K, T] replicated contribution matrix of one partition:
    ``out[k, t] = p_sr[top_idx[k], t] * rv[t]`` over the padded trace
    (column) axis, from the kernel's own staged coverage view."""
    k = top_idx.shape[0]
    v_pad = g.cov_unique.shape[0]
    t_pad = g.kind.shape[0]  # LOCAL under the trace-sharded packed path

    if kernel in ("packed", "packed_bf16", "packed_blocked", "kind"):
        # Bitmap rows (or the kind view's int8 pattern rows — same 0/1
        # semantics, no unpack needed): K gathered rows over the
        # (local) column axis; inv_tracelen is the per-column p_sr
        # value (multiplicity folded in on collapsed builds).
        rows = (
            jnp.take(g.cov_i8, top_idx, axis=0).astype(jnp.float32)
            if kernel == "kind"
            else unpack_bits(jnp.take(g.cov_bits, top_idx, axis=0), t_pad)
        )
        local = rows * (rv * g.inv_tracelen)[None, :]
        if psum_axis is None:
            return local
        # Trace-sharded: concatenate the column blocks (tiled
        # all_gather keeps the result shape static).
        return lax.all_gather(local, psum_axis, axis=1, tiled=True)

    if kernel == "pcsr":
        # ELL slab [T_local, W]: a column covers suspect k iff any slab
        # cell names the op (pc_ell_rs > 0 masks slab padding). The
        # p_sr value is multiplicity/tracelen (kind holds the column
        # multiplicity on collapsed builds, 1-equivalent otherwise).
        t_local = g.pc_ell_op.shape[0]
        t_base = (
            0
            if psum_axis is None
            else lax.axis_index(psum_axis) * t_local
        )
        live_cell = g.pc_ell_rs > 0
        match = jnp.any(
            live_cell[None, :, :]
            & (g.pc_ell_op[None, :, :] == top_idx[:, None, None]),
            axis=-1,
        ).astype(jnp.float32)
        mult = jnp.where(
            g.n_cols < 0, 1.0, g.kind.astype(jnp.float32)
        )
        w_col = rv * mult / g.tracelen.astype(jnp.float32)
        local = match * lax.dynamic_slice(w_col, (t_base,), (t_local,))
        if psum_axis is None:
            return local
        full = lax.dynamic_update_slice(
            jnp.zeros((k, t_pad), jnp.float32), local, (0, t_base)
        )
        return lax.psum(full, psum_axis)

    if kernel == "csr":
        # Op-major entries: entry e belongs to the op whose indptr
        # range brackets its GLOBAL position (entry axes block-split
        # under sharding, indptrs replicated global).
        e_local = g.sr_val_opmajor.shape[0]
        base = (
            0
            if psum_axis is None
            else lax.axis_index(psum_axis) * e_local
        )
        e_idx = base + jnp.arange(e_local, dtype=jnp.int32)
        op_e = (
            jnp.searchsorted(g.inc_indptr_op, e_idx, side="right") - 1
        )
        op_e = jnp.clip(op_e, 0, v_pad)
        vals = g.sr_val_opmajor * jnp.take(rv, g.inc_trace_opmajor)
        partial = (
            jnp.zeros((k + 1, t_pad), jnp.float32)
            .at[_slot_map(top_idx, v_pad)[op_e], g.inc_trace_opmajor]
            .add(vals)
        )[:k]
        return (
            partial
            if psum_axis is None
            else lax.psum(partial, psum_axis)
        )

    # coo / dense / dense_bf16 / pallas: the trace-major COO entries.
    vals = g.sr_val * jnp.take(rv, g.inc_trace)
    partial = (
        jnp.zeros((k + 1, t_pad), jnp.float32)
        .at[_slot_map(top_idx, v_pad)[jnp.clip(g.inc_op, 0, v_pad)],
            g.inc_trace]
        .add(vals)
    )[:k]
    return partial if psum_axis is None else lax.psum(partial, psum_axis)


def _top_traces(
    g: PartitionGraph,
    top_idx,
    rv,
    explain_cfg: ExplainConfig,
    kernel: str,
    psum_axis: str | None,
):
    """(idx int32[K, J], val float32[K, J]): each suspect's top-J
    contributing coverage columns of one partition, -inf past the live
    columns (and past the partition's column count when J exceeds it).
    Ties break by ascending column index (vocab-order determinism, same
    two-key sort as the ranking itself)."""
    contrib = _contrib_rows(g, top_idx, rv, kernel, psum_axis)
    t_full = contrib.shape[1]
    n_live = jnp.where(g.n_cols < 0, g.n_traces, g.n_cols)
    live = jnp.arange(t_full) < n_live
    masked = jnp.where(live[None, :], contrib, -jnp.inf)
    j = min(int(explain_cfg.top_traces), t_full)
    vals, idx = jax.vmap(lambda row: top_k_tiebroken(row, j))(masked)
    j_want = int(explain_cfg.top_traces)
    if j < j_want:
        pad = j_want - j
        vals = jnp.concatenate(
            [vals, jnp.full((vals.shape[0], pad), -jnp.inf)], axis=1
        )
        idx = jnp.concatenate(
            [idx, jnp.zeros((idx.shape[0], pad), jnp.int32)], axis=1
        )
    return idx.astype(jnp.int32), vals


@contract(
    graph="windowgraph",
    returns=(
        "int32[K]", "float32[K]", "int32[]", "float32[2,I]", "int32[]",
        "float32[4,Ke]", "float32[M,Ke]", "float32[2,Ke]",
        "int32[2,Ke,J]", "float32[2,Ke,J]",
    ),
)
def rank_window_explained_core(
    graph: WindowGraph,
    pagerank_cfg: PageRankConfig,
    spectrum_cfg: SpectrumConfig,
    explain_cfg: ExplainConfig,
    psum_axis: str | None = None,
    kernel: str = "coo",
):
    """The explained traced ranking: rank_window_traced_core's 5 outputs
    plus the attribution tensors (module docstring), one program, one
    fetch. ``explain_cfg`` is a static (hashable frozen dataclass) jit
    argument like the other configs."""
    n_weight, a_weight, rv_n, rv_a, residuals, n_iters, _, _ = (
        window_weights_full(graph, pagerank_cfg, psum_axis, kernel)
    )
    ef, nf, ep, np_, valid = spectrum_counters(
        a_weight, graph.abnormal, n_weight, graph.normal, spectrum_cfg
    )
    scores = jnp.where(
        valid, spectrum_scores(ef, nf, ep, np_, spectrum_cfg.method),
        -jnp.inf,
    )
    k = min(spectrum_cfg.n_rows, scores.shape[0])
    top_scores, top_idx = top_k_tiebroken(scores, k)
    top_idx = top_idx.astype(jnp.int32)
    n_valid = jnp.minimum(valid.sum(), k).astype(jnp.int32)

    ke = (
        k
        if explain_cfg.top_suspects <= 0
        else min(int(explain_cfg.top_suspects), k)
    )
    sus = top_idx[:ke]
    c_sus = tuple(jnp.take(x, sus) for x in (ef, nf, ep, np_))
    counters = jnp.stack(c_sus)
    # Per-formula terms on the [Ke] gathered counters: elementwise
    # formulas, so gather-then-score equals score-then-gather exactly.
    terms = jnp.stack(
        [spectrum_scores(*c_sus, m) for m in METHODS]
    )
    mass = jnp.stack(
        [jnp.take(n_weight, sus), jnp.take(a_weight, sus)]
    )
    ti_n, tv_n = _top_traces(
        graph.normal, sus, rv_n, explain_cfg, kernel, psum_axis
    )
    ti_a, tv_a = _top_traces(
        graph.abnormal, sus, rv_a, explain_cfg, kernel, psum_axis
    )
    trace_idx = jnp.stack([ti_n, ti_a])
    trace_val = jnp.stack([tv_n, tv_a])
    return (
        top_idx, top_scores, n_valid, residuals, n_iters,
        counters, terms, mass, trace_idx, trace_val,
    )


@contract(
    blob="uint32[N]",
    returns=(
        "int32[K]", "float32[K]", "int32[]", "float32[2,I]", "int32[]",
        "float32[4,Ke]", "float32[M,Ke]", "float32[2,Ke]",
        "int32[2,Ke,J]", "float32[2,Ke,J]",
    ),
)
def rank_window_explained_blob_core(
    blob, layout, pagerank_cfg, spectrum_cfg, explain_cfg, kernel="coo"
):
    """Blob-staged twin of rank_window_explained_core (the default
    staging profile): unpack inside the program, same output tuple."""
    from ..rank_backends.blob import unpack_graph_blob

    graph = unpack_graph_blob(blob, layout)
    return rank_window_explained_core(
        graph, pagerank_cfg, spectrum_cfg, explain_cfg, None, kernel
    )


rank_window_explained_device = jax.jit(
    rank_window_explained_core, static_argnums=(1, 2, 3, 4, 5)
)
rank_window_explained_blob_device = jax.jit(
    rank_window_explained_blob_core, static_argnums=(1, 2, 3, 4, 5)
)
