"""Bounded in-process store of recent explain bundles.

The obs HTTP server's ``GET /explainz?window=...`` endpoint serves from
here: pipelines publish every materialized bundle (incident opens,
explain:true requests, on-demand CLI runs in the same process), keyed
by window start, and the ring keeps the most recent
``ExplainConfig.store_windows``. Thread-safe (engine thread publishes,
HTTP handler threads read).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import List, Optional

from ..utils.guards import TrackedLock, note_shared_access, register_shared


class ExplainStore:
    def __init__(self, capacity: int = 32):
        self.capacity = max(1, int(capacity))
        # Engine/scheduler threads publish, HTTP handler threads read:
        # the ring is a registered mrsan shared object — armed runs
        # lockset-check every access (mrlint R10's runtime twin).
        self._lock = TrackedLock("explain_store")
        register_shared("explain_store", {"explain_store"})
        self._bundles: "OrderedDict[str, dict]" = OrderedDict()

    def publish(self, window_id: str, bundle_data: dict) -> None:
        key = str(window_id)
        with self._lock:
            note_shared_access("explain_store")
            self._bundles.pop(key, None)
            self._bundles[key] = bundle_data
            while len(self._bundles) > self.capacity:
                self._bundles.popitem(last=False)

    def get(self, window_id: str) -> Optional[dict]:
        with self._lock:
            note_shared_access("explain_store")
            return self._bundles.get(str(window_id))

    def latest(self) -> Optional[dict]:
        with self._lock:
            note_shared_access("explain_store")
            if not self._bundles:
                return None
            return next(reversed(self._bundles.values()))

    def windows(self) -> List[str]:
        with self._lock:
            note_shared_access("explain_store")
            return list(self._bundles)

    def configure(self, capacity: int) -> None:
        with self._lock:
            note_shared_access("explain_store")
            self.capacity = max(1, int(capacity))
            while len(self._bundles) > self.capacity:
                self._bundles.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._bundles)


_store_lock = threading.Lock()
_store: Optional[ExplainStore] = None


def get_explain_store() -> ExplainStore:
    """The process-wide bundle store (created on first use)."""
    global _store
    with _store_lock:
        if _store is None:
            _store = ExplainStore()
        return _store
