"""Backend registry: ``numpy_ref`` (CPU parity oracle) and ``jax`` (TPU)."""

from __future__ import annotations

from typing import List, Tuple

from ..config import MicroRankConfig
from . import numpy_ref
from .base import RankBackend


class NumpyRefBackend:
    """Oracle backend: faithful reference semantics over graph dicts."""

    name = "numpy_ref"

    def __init__(self, config: MicroRankConfig = MicroRankConfig()):
        self.config = config
        # Residual traces of the most recent rank_window call (same
        # shape as JaxBackend.last_convergence) when
        # runtime.convergence_trace is on — the parity suite's oracle
        # side and the pandas runner's journal feed.
        self.last_convergence = None

    def rank_window(
        self, span_df, normal_ids, abnormal_ids
    ) -> Tuple[List[str], List[float]]:
        from ..graph.dicts import pagerank_graph_dicts
        from .base import validate_partitions

        normal_ids = list(normal_ids)
        abnormal_ids = list(abnormal_ids)
        validate_partitions(normal_ids, abnormal_ids)
        normal_graph = pagerank_graph_dicts(normal_ids, span_df)
        abnormal_graph = pagerank_graph_dicts(abnormal_ids, span_df)
        conv = {} if self.config.runtime.convergence_trace else None
        out = numpy_ref.rank_window_dicts(
            normal_graph,
            abnormal_graph,
            n_normal_traces=len(normal_ids),
            n_abnormal_traces=len(abnormal_ids),
            pagerank_cfg=self.config.pagerank,
            spectrum_cfg=self.config.spectrum,
            conv_out=conv,
        )
        self.last_convergence = None
        if conv is not None:
            joint = [
                max(n, a)
                for n, a in zip(conv["normal"], conv["abnormal"])
            ]
            self.last_convergence = {
                "iterations": conv["iterations"],
                "final_residual": joint[-1] if joint else None,
                "residuals": {
                    "normal": conv["normal"],
                    "abnormal": conv["abnormal"],
                },
            }
        return out


def get_backend(config: MicroRankConfig) -> RankBackend:
    name = config.runtime.backend
    if name in ("jax", "jax_tpu", "tpu"):
        from .jax_tpu import JaxBackend

        return JaxBackend(config)
    if name in ("numpy", "numpy_ref", "reference"):
        return NumpyRefBackend(config)
    raise ValueError(f"unknown rank backend {name!r}")


__all__ = ["RankBackend", "NumpyRefBackend", "get_backend", "numpy_ref"]
