"""Sparse full-scale numpy oracle (VERDICT r3 #5).

The dense oracle (numpy_ref.py) is value-faithful to the reference but
allocates the dense [V, T] transition matrices (pagerank.py:19-24), which
is infeasible at the 1M-span bench scale. This module re-derives the SAME
semantics — preference vector (pagerank.py:68-85), power iteration
(pagerank.py:116-130), rescale + coverage counts (pagerank.py:93-112) and
the weighted spectrum (online_rca.py:33-152) — over the padded COO window
graph, using float64 vectors and ``np.bincount`` segment sums instead of
dense matvecs. Memory is O(E + V + T); the 1M-span window ranks in
seconds.

Independence from the device path: everything downstream of the COO
entries is recomputed here in a different summation structure (bincount
vs the device's bitmap matvecs / CSR prefix sums), in float64, including
an independent trace-kind dedup (byte-signature grouping vs the device
build's splitmix hash) and an independent unique-coverage count. The COO
entries themselves are shared with the device path — their construction
is covered by the small-scale dense-oracle parity suite
(tests/test_backend_parity.py), which starts from raw spans.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from ..analysis.contracts import contract
from ..config import PageRankConfig, SpectrumConfig
from ..graph.structures import PartitionGraph, WindowGraph
from .numpy_ref import spectrum_score


def _partition_arrays(g: PartitionGraph):
    """Slice the live (unpadded) COO arrays of one partition."""
    if int(g.n_cols) >= 0:
        raise ValueError(
            "the sparse oracle ranks UNCOLLAPSED graphs only (its whole "
            "point is independence from the device path's "
            "transformations) — build the window with collapse='off' "
            "and compare the device's collapsed ranking against it"
        )
    e = int(g.n_inc)
    c = int(g.n_ss)
    t = int(g.n_traces)
    return {
        "inc_op": np.asarray(g.inc_op[:e]),
        "inc_trace": np.asarray(g.inc_trace[:e]),
        "sr_val": np.asarray(g.sr_val[:e], dtype=np.float64),
        "rs_val": np.asarray(g.rs_val[:e], dtype=np.float64),
        "ss_child": np.asarray(g.ss_child[:c]),
        "ss_parent": np.asarray(g.ss_parent[:c]),
        "ss_val": np.asarray(g.ss_val[:c], dtype=np.float64),
        # NB: g.kind is deliberately NOT read — the oracle recomputes
        # kinds independently (recompute_kinds).
        "tracelen": np.asarray(g.tracelen[:t], dtype=np.float64),
        "op_present": np.asarray(g.op_present),
        "n_ops": int(g.n_ops),
        "n_traces": t,
    }


def recompute_kinds(
    inc_trace, inc_op, tracelen, n_traces: int
) -> np.ndarray:
    """Independent trace-kind dedup (reference pagerank.py:54-66): two
    traces are one kind iff their p_sr columns match — same unique op set
    AND same with-duplicates length (the column's nonzero value is
    1/len_with_dups). Groups by a per-trace byte signature of (sorted op
    ids, tracelen). Returns counts[t] = size of t's kind.
    """
    order = np.lexsort((inc_op, inc_trace))
    tr = inc_trace[order]
    op = inc_op[order]
    starts = np.searchsorted(tr, np.arange(n_traces), side="left")
    ends = np.searchsorted(tr, np.arange(n_traces), side="right")
    tlen = np.asarray(tracelen)
    sigs = {}
    kind_of = np.zeros(n_traces, dtype=np.int64)
    for t in range(n_traces):
        key = (op[starts[t] : ends[t]].tobytes(), float(tlen[t]))
        kind_of[t] = sigs.setdefault(key, len(sigs))
    counts = np.bincount(kind_of, minlength=len(sigs))
    return counts[kind_of].astype(np.float64)


def _preference(kind, tracelen, anomaly: bool, cfg: PageRankConfig):
    """pagerank.py:68-85 in array form, float64."""
    inv_kind = 1.0 / kind
    inv_len = 1.0 / tracelen
    kind_sum = inv_kind.sum()
    if not anomaly:
        return inv_kind / kind_sum
    num_sum = inv_len.sum()
    if cfg.preference == "reference":
        return cfg.phi / num_sum / (kind / kind_sum * cfg.phi + inv_len)
    if cfg.preference == "paper":
        return (
            cfg.phi * inv_len / num_sum
            + (1.0 - cfg.phi) * inv_kind / kind_sum
        )
    raise ValueError(f"unknown preference form {cfg.preference!r}")


def _iterate_sparse(p, pref, v_pad: int, cfg: PageRankConfig):
    """pageRank (pagerank.py:116-130) over COO entries: each dense matvec
    becomes gather -> weighted bincount. float64 throughout (the dense
    oracle's vectors are float64 too — f32 matrix @ f64 vector promotes).
    """
    d = cfg.damping
    alpha = cfg.call_weight
    t = p["n_traces"]
    n_total = float(p["n_ops"] + t)
    v_s = np.where(p["op_present"], 1.0 / n_total, 0.0)
    v_r = np.full(t, 1.0 / n_total)
    for _ in range(cfg.iterations):
        sr = np.bincount(
            p["inc_op"],
            weights=p["sr_val"] * v_r[p["inc_trace"]],
            minlength=v_pad,
        )
        ss = np.bincount(
            p["ss_child"],
            weights=p["ss_val"] * v_s[p["ss_parent"]],
            minlength=v_pad,
        )
        new_s = d * (sr + alpha * ss)
        new_r = (
            d
            * np.bincount(
                p["inc_trace"],
                weights=p["rs_val"] * v_s[p["inc_op"]],
                minlength=t,
            )
            + (1.0 - d) * pref
        )
        if cfg.max_normalize_each_iter:
            new_s = new_s / np.amax(new_s)
            new_r = new_r / np.amax(new_r)
        if cfg.tol is not None:
            delta = max(
                float(np.max(np.abs(new_s - v_s))),
                float(np.max(np.abs(new_r - v_r))),
            )
            v_s, v_r = new_s, new_r
            if delta <= cfg.tol:
                break
        else:
            v_s, v_r = new_s, new_r
    # The final trace vector rides along for the explain oracle (the
    # coverage-column attribution decomposes p_sr[v, t] * rv[t]).
    return v_s / np.amax(v_s), v_r


def _partition_rank(g: PartitionGraph, anomaly: bool, cfg: PageRankConfig):
    """One partition's (weight[v_pad], trace_num[v_pad]) — the sparse twin
    of numpy_ref.trace_pagerank, with kinds and coverage counts recomputed
    independently of the build's aux arrays."""
    p = _partition_arrays(g)
    v_pad = g.op_present.shape[0]
    kinds = recompute_kinds(
        p["inc_trace"], p["inc_op"], p["tracelen"], p["n_traces"]
    )
    pref = _preference(kinds, p["tracelen"], anomaly, cfg)
    v_s, _ = _iterate_sparse(p, pref, v_pad, cfg)
    total = float(v_s[p["op_present"]].sum())
    weight = np.where(p["op_present"], v_s * total / p["n_ops"], 0.0)
    trace_num = np.bincount(p["inc_op"], minlength=v_pad).astype(np.int64)
    return weight, trace_num, p


@contract(graph="windowgraph")
def rank_window_sparse(
    graph: WindowGraph,
    op_names: List[str],
    pagerank_cfg: PageRankConfig = PageRankConfig(),
    spectrum_cfg: SpectrumConfig = SpectrumConfig(),
) -> Tuple[List[str], List[float]]:
    """Full-window oracle ranking from the padded COO graph: returns the
    top ``n_rows`` (name-tiebroken, matching the device path's
    vocab-index tie key over the name-sorted window vocab)."""
    n_weight, n_num, n_p = _partition_rank(graph.normal, False, pagerank_cfg)
    a_weight, a_num, a_p = _partition_rank(graph.abnormal, True, pagerank_cfg)
    in_a = np.asarray(graph.abnormal.op_present)
    in_n = np.asarray(graph.normal.op_present)
    eps = spectrum_cfg.eps
    scored = {}
    for vi in np.flatnonzero(in_a | in_n):
        cell = {}
        if in_a[vi]:
            a = a_weight[vi]
            cell["ef"] = a * a_num[vi]
            cell["nf"] = a * (a_p["n_traces"] - a_num[vi])
            if in_n[vi]:
                nw = n_weight[vi]
                cell["ep"] = nw * n_num[vi]
                cell["np"] = nw * (n_p["n_traces"] - n_num[vi])
            else:
                cell["ep"] = eps
                cell["np"] = eps
        else:  # only-in-normal branch (online_rca.py:60-69, asymmetric)
            nw = n_weight[vi]
            cell["ef"] = eps
            cell["nf"] = eps
            cell["ep"] = (1 + nw) * n_num[vi]
            cell["np"] = n_p["n_traces"] - n_num[vi]
        scored[int(vi)] = spectrum_score(cell, spectrum_cfg.method)
    ranked = sorted(scored.items(), key=lambda x: (-x[1], op_names[x[0]]))
    top = ranked[: spectrum_cfg.n_rows]
    return [op_names[vi] for vi, _ in top], [float(s) for _, s in top]
