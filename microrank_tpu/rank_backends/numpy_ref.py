"""Faithful numpy oracle backend (reference components C9-C14).

Re-derives the reference's ranking semantics — ``trace_pagerank``
(/root/reference/pagerank.py:15-112), ``pageRank`` (pagerank.py:116-130) and
``calculate_spectrum_without_delay_list`` (online_rca.py:33-152) — against
the SURVEY.md §2 citations, value-for-value, including the documented
quirks. This is the parity oracle for the jax backend: it is written for
clarity and exactness, not speed (the O(n) ``list.index`` lookups become
dict lookups and the O(T^2·O) kind dedup becomes ``np.unique`` — both
produce identical values).

Dtype fidelity: transition matrices are float32 (pagerank.py:19-24), the
ranking vectors start as numpy default float64 (``np.ones`` at
pagerank.py:118-119) and stay float64 through the iteration because
float32 @ float64 promotes.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..analysis.contracts import contract
from ..config import PageRankConfig, SpectrumConfig

EPS_DEFAULT = 1e-7


def page_rank_iterate(
    p_ss: np.ndarray,
    p_sr: np.ndarray,
    p_rs: np.ndarray,
    pref: np.ndarray,
    n_ops: int,
    n_traces: int,
    cfg: PageRankConfig,
    record: Optional[List[float]] = None,
) -> np.ndarray:
    """Power iteration (reference ``pageRank``, pagerank.py:116-130).

    Fixed iteration count, no convergence check (tol=None, the reference
    behavior); both vectors are max-normalized every iteration
    (pagerank.py:126-127 — not in the paper but load-bearing for score
    parity). ``cfg.tol`` adds the same early-exit rule as the device
    backend: stop once the L-inf change of both vectors is below tol.

    ``record``: list the per-iteration L-inf residual (max over both
    vectors, AFTER normalization) is appended to — the oracle twin of
    the device convergence trace (jax_tpu.window_weights_traced), same
    definition so the parity suite can pin them against each other.
    """
    d = cfg.damping
    alpha = cfg.call_weight
    v_s = np.ones((n_ops, 1)) / float(n_ops + n_traces)
    v_r = np.ones((n_traces, 1)) / float(n_ops + n_traces)
    for _ in range(cfg.iterations):
        new_s = d * (np.dot(p_sr, v_r) + alpha * np.dot(p_ss, v_s))
        new_r = d * np.dot(p_rs, v_s) + (1.0 - d) * pref
        if cfg.max_normalize_each_iter:
            new_s = new_s / np.amax(new_s)
            new_r = new_r / np.amax(new_r)
        need_delta = cfg.tol is not None or record is not None
        if need_delta:
            delta = max(
                float(np.max(np.abs(new_s - v_s))),
                float(np.max(np.abs(new_r - v_r))),
            )
            if record is not None:
                record.append(delta)
        v_s, v_r = new_s, new_r
        if cfg.tol is not None and delta <= cfg.tol:
            break
    return v_s / np.amax(v_s)


def _preference_vector(
    trace_index: Dict[str, int],
    pr_trace: Dict[str, List[str]],
    kind_list: np.ndarray,
    anomaly: bool,
    cfg: PageRankConfig,
) -> np.ndarray:
    """Personalized preference vector (pagerank.py:68-85).

    ``preference="reference"`` reproduces the code exactly — note the
    anomalous form deviates from paper Eq (7) (SURVEY.md §2.2 quirk #4).
    ``preference="paper"`` implements Eq (7): the phi-weighted sum of the
    normalized 1/n_t and 1/kind_t terms.
    """
    n = len(trace_index)
    pr = np.zeros((n, 1), dtype=np.float32)
    inv_kind = {t: 1.0 / kind_list[trace_index[t]] for t in pr_trace}
    inv_len = {t: 1.0 / len(pr_trace[t]) for t in pr_trace}

    if not anomaly:
        kind_sum = sum(inv_kind.values())
        for t in pr_trace:
            pr[trace_index[t]] = inv_kind[t] / kind_sum
        return pr

    if cfg.preference == "reference":
        kind_sum = sum(inv_kind.values())
        num_sum = sum(inv_len.values())
        for t in pr_trace:
            kind_t = kind_list[trace_index[t]]
            pr[trace_index[t]] = (
                1.0
                / (kind_t / kind_sum * cfg.phi + inv_len[t])
                / num_sum
                * cfg.phi
            )
    elif cfg.preference == "paper":
        kind_sum = sum(inv_kind.values())
        num_sum = sum(inv_len.values())
        for t in pr_trace:
            pr[trace_index[t]] = cfg.phi * inv_len[t] / num_sum + (
                1.0 - cfg.phi
            ) * inv_kind[t] / kind_sum
    else:
        raise ValueError(f"unknown preference form {cfg.preference!r}")
    return pr


def build_matrices(
    operation_operation: Dict[str, List[str]],
    operation_trace: Dict[str, List[str]],
    trace_operation: Dict[str, List[str]],
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, List[str], List[str]]:
    """Dense float32 transition matrices (pagerank.py:19-52).

    Returns (p_ss, p_sr, p_rs, node_list, trace_list). The call-graph
    matrix's duplicate children overwrite to the same value, so
    multiplicity only inflates the 1/child_num denominator
    (pagerank.py:35-39).
    """
    node_list = list(operation_operation.keys())
    trace_list = list(operation_trace.keys())
    node_index = {n: i for i, n in enumerate(node_list)}
    trace_index = {t: i for i, t in enumerate(trace_list)}
    n_ops = len(node_list)
    n_traces = len(trace_list)

    p_ss = np.zeros((n_ops, n_ops), dtype=np.float32)
    p_sr = np.zeros((n_ops, n_traces), dtype=np.float32)
    p_rs = np.zeros((n_traces, n_ops), dtype=np.float32)

    for operation, children in operation_operation.items():
        if not children:
            continue
        child_num = len(children)
        for child in children:
            p_ss[node_index[child]][node_index[operation]] = 1.0 / child_num

    for trace_id, ops in operation_trace.items():
        child_num = len(ops)
        for op in ops:
            p_sr[node_index[op]][trace_index[trace_id]] = 1.0 / child_num

    for operation, traces in trace_operation.items():
        child_num = len(traces)
        for trace_id in traces:
            p_rs[trace_index[trace_id]][node_index[operation]] = 1.0 / child_num

    return p_ss, p_sr, p_rs, node_list, trace_list


def compute_kind_list(p_sr: np.ndarray) -> np.ndarray:
    """Trace-kind dedup (pagerank.py:54-66): kind_list[t] = number of traces
    whose p_sr column is identical to t's. np.unique over columns gives the
    same float-equality grouping as the all-pairs loop, at O(T log T)."""
    n_traces = p_sr.shape[1]
    if not n_traces:
        return np.zeros(0)
    _, inverse, counts = np.unique(
        p_sr.T, axis=0, return_inverse=True, return_counts=True
    )
    return counts[inverse].astype(np.float64)


def trace_pagerank(
    operation_operation: Dict[str, List[str]],
    operation_trace: Dict[str, List[str]],
    trace_operation: Dict[str, List[str]],
    pr_trace: Dict[str, List[str]],
    anomaly: bool,
    cfg: PageRankConfig = PageRankConfig(),
    record: Optional[List[float]] = None,
) -> Tuple[Dict[str, float], Dict[str, int]]:
    """Reference ``trace_pagerank`` (pagerank.py:15-112), value-identical.

    Returns (weight, trace_num_list): the rescaled operation scores
    (``score * sum(scores) / n_ops``, rank-preserving — pagerank.py:106-107)
    and the per-op count of distinct covering traces (N_ef / N_ep for the
    spectrum step).
    """
    p_ss, p_sr, p_rs, node_list, trace_list = build_matrices(
        operation_operation, operation_trace, trace_operation
    )
    node_index = {n: i for i, n in enumerate(node_list)}
    trace_index = {t: i for i, t in enumerate(trace_list)}
    n_ops = len(node_list)
    n_traces = len(trace_list)

    kind_list = compute_kind_list(p_sr)

    pref = _preference_vector(trace_index, pr_trace, kind_list, anomaly, cfg)

    result = page_rank_iterate(
        p_ss, p_sr, p_rs, pref, n_ops, n_traces, cfg, record=record
    )

    total = float(sum(result[node_index[op]][0] for op in operation_operation))
    trace_num_list = {
        op: int(np.count_nonzero(p_sr[node_index[op]]))
        for op in operation_operation
    }
    weight = {
        op: result[node_index[op]][0] * total / n_ops
        for op in operation_operation
    }
    return weight, trace_num_list


def spectrum_components(
    anomaly_result: Dict[str, float],
    normal_result: Dict[str, float],
    anomaly_list_len: int,
    normal_list_len: int,
    normal_num_list: Dict[str, int],
    anomaly_num_list: Dict[str, int],
    eps: float = EPS_DEFAULT,
) -> Dict[str, Dict[str, float]]:
    """Per-op spectrum counters {ef, nf, ep, np} (online_rca.py:43-69).

    Note the asymmetric only-in-normal branch: ep = (1+P)*N_ep and
    np = N_p - N_ep (online_rca.py:65-66).
    """
    spectrum: Dict[str, Dict[str, float]] = {}
    for node, score in anomaly_result.items():
        cell = spectrum[node] = {}
        cell["ef"] = score * anomaly_num_list[node]
        cell["nf"] = score * (anomaly_list_len - anomaly_num_list[node])
        if node in normal_result:
            cell["ep"] = normal_result[node] * normal_num_list[node]
            cell["np"] = normal_result[node] * (
                normal_list_len - normal_num_list[node]
            )
        else:
            cell["ep"] = eps
            cell["np"] = eps
    for node, score in normal_result.items():
        if node not in spectrum:
            cell = spectrum[node] = {}
            cell["ep"] = (1 + score) * normal_num_list[node]
            cell["np"] = normal_list_len - normal_num_list[node]
            if node not in anomaly_result:
                cell["ef"] = eps
                cell["nf"] = eps
    return spectrum


def spectrum_score(cell: Dict[str, float], method: str) -> float:
    """The 13 spectrum formulas (online_rca.py:75-142), scalar form."""
    ef, nf = cell["ef"], cell["nf"]
    ep, np_ = cell["ep"], cell["np"]
    if method == "dstar2":
        return ef * ef / (ep + nf)
    if method == "ochiai":
        return ef / math.sqrt((ep + ef) * (ef + nf))
    if method == "jaccard":
        return ef / (ef + ep + nf)
    if method == "sorensendice":
        return 2 * ef / (2 * ef + ep + nf)
    if method == "m1":
        return (ef + np_) / (ep + nf)
    if method == "m2":
        return ef / (2 * ep + 2 * nf + ef + np_)
    if method == "goodman":
        return (2 * ef - nf - ep) / (2 * ef + nf + ep)
    if method == "tarantula":
        return ef / (ef + nf) / (ef / (ef + nf) + ep / (ep + np_))
    if method == "russellrao":
        return ef / (ef + nf + ep + np_)
    if method == "hamann":
        return (ef + np_ - ep - nf) / (ef + nf + ep + np_)
    if method == "dice":
        return 2 * ef / (ef + nf + ep)
    if method == "simplematcing":  # (sic) — reference spelling
        return (ef + np_) / (ef + np_ + nf + ep)
    if method == "rogers":
        return (ef + np_) / (ef + np_ + 2 * nf + 2 * ep)
    raise ValueError(f"unknown spectrum method {method!r}")


def calculate_spectrum(
    anomaly_result: Dict[str, float],
    normal_result: Dict[str, float],
    anomaly_list_len: int,
    normal_list_len: int,
    normal_num_list: Dict[str, int],
    anomaly_num_list: Dict[str, int],
    cfg: SpectrumConfig = SpectrumConfig(),
) -> Tuple[List[str], List[float]]:
    """Reference ``calculate_spectrum_without_delay_list``
    (online_rca.py:33-152): score every op, return the top
    ``top_max + extra_rows`` (score descending).

    Exactly tied scores order by ``cfg.tiebreak``: "name" (ascending op
    name — matches the device path, whose vocab-index tie key runs over
    the name-sorted window vocab) or "insertion" (the reference's
    accidental dict-insertion order under Python's stable sort)."""
    spectrum = spectrum_components(
        anomaly_result,
        normal_result,
        anomaly_list_len,
        normal_list_len,
        normal_num_list,
        anomaly_num_list,
        eps=cfg.eps,
    )
    result = {
        node: spectrum_score(cell, cfg.method) for node, cell in spectrum.items()
    }
    if cfg.tiebreak == "name":
        ranked = sorted(result.items(), key=lambda x: (-x[1], x[0]))
    elif cfg.tiebreak == "insertion":
        ranked = sorted(result.items(), key=lambda x: x[1], reverse=True)
    else:
        raise ValueError(f"unknown tiebreak {cfg.tiebreak!r}")
    top_list: List[str] = []
    score_list: List[float] = []
    for index, (node, score) in enumerate(ranked):
        if index < cfg.n_rows:
            top_list.append(node)
            score_list.append(float(score))
    return top_list, score_list


@contract(normal_graph="any", abnormal_graph="any")
def rank_window_dicts(
    normal_graph,
    abnormal_graph,
    n_normal_traces: int,
    n_abnormal_traces: int,
    pagerank_cfg: PageRankConfig = PageRankConfig(),
    spectrum_cfg: SpectrumConfig = SpectrumConfig(),
    conv_out: Optional[dict] = None,
) -> Tuple[List[str], List[float]]:
    """Full oracle ranking of one window from the two partitions' graph
    dicts — the composition the orchestrator performs at
    online_rca.py:180-201.

    ``conv_out``: dict the per-partition residual traces are written
    into ({"normal": [...], "abnormal": [...]}) — the oracle side of
    the convergence-trace parity suite."""
    rec_n = [] if conv_out is not None else None
    rec_a = [] if conv_out is not None else None
    normal_result, normal_num = trace_pagerank(
        *normal_graph, False, pagerank_cfg, record=rec_n
    )
    anomaly_result, anomaly_num = trace_pagerank(
        *abnormal_graph, True, pagerank_cfg, record=rec_a
    )
    if conv_out is not None:
        conv_out["normal"] = rec_n
        conv_out["abnormal"] = rec_a
        conv_out["iterations"] = max(len(rec_n), len(rec_a))
    return calculate_spectrum(
        anomaly_result=anomaly_result,
        normal_result=normal_result,
        anomaly_list_len=n_abnormal_traces,
        normal_list_len=n_normal_traces,
        normal_num_list=normal_num,
        anomaly_num_list=anomaly_num,
        cfg=spectrum_cfg,
    )
