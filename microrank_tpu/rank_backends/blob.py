"""Single-buffer host->device staging for window graphs.

A ``WindowGraph`` is ~50 leaf arrays; staging it with ``jax.device_put``
issues one transfer per leaf, and on tunneled-TPU runtimes every transfer
pays a full RPC round trip (~60-90 ms measured) regardless of size — round
3 measured 5 MB staged in 1,675 ms, pure per-transfer latency. Here the
whole graph is packed into ONE uint32 buffer on the host (a memcpy),
shipped in ONE transfer, and re-sliced into the graph's leaves *inside*
the jitted rank program: the layout (field offsets/shapes/dtypes) is a
static jit argument, so the unpack lowers to free slices + same-width
bitcasts that XLA fuses into the consumers.

No reference counterpart (the reference never crosses a device boundary —
SURVEY.md C18/C19); this is the TPU-native answer to its in-process numpy
arrays.

Word format: little-endian byte order within each uint32 word (the host
packs via a uint8 view of the word buffer; sub-word dtypes are decoded on
device with shift/mask arithmetic against that order, never bitcasts, so
device endianness is irrelevant). 4-byte dtypes round-trip as same-width
bitcasts, which are bit-pattern-exact.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..analysis.contracts import contract
from ..graph.structures import PartitionGraph, WindowGraph

# (field, dtype str, shape, word offset, word count) per leaf, one tuple
# per partition, normal first. Hashable -> usable as a static jit arg;
# offsets are a pure function of the (already static) padded shapes, so
# blob programs recompile exactly when the non-blob ones would.
BlobLayout = Tuple[Tuple[Tuple[str, str, Tuple[int, ...], int, int], ...], ...]

_WORD = 4


def _leaf_entries(part: PartitionGraph, off: int):
    entries = []
    for f in PartitionGraph._fields:
        arr = np.asarray(getattr(part, f))
        n_words = (arr.nbytes + _WORD - 1) // _WORD
        entries.append((f, str(arr.dtype), tuple(arr.shape), off, n_words))
        off += n_words
    return tuple(entries), off


@contract(graph="windowgraph", returns=("uint32[N]", "any"))
def pack_graph_blob(graph: WindowGraph) -> Tuple[np.ndarray, BlobLayout]:
    """Host side: one uint32 buffer + the static layout describing it."""
    n_entries, off = _leaf_entries(graph.normal, 0)
    a_entries, off = _leaf_entries(graph.abnormal, off)
    layout: BlobLayout = (n_entries, a_entries)
    blob = np.zeros(max(off, 1), np.uint32)
    u8 = blob.view(np.uint8)
    for part, entries in ((graph.normal, n_entries), (graph.abnormal, a_entries)):
        for f, _, _, o, _ in entries:
            b = np.ascontiguousarray(getattr(part, f)).view(np.uint8).reshape(-1)
            u8[o * _WORD : o * _WORD + b.size] = b
    return blob, layout


def _decode_leaf(blob, dtype_str: str, shape: Tuple[int, ...], off: int, n_words: int):
    w = lax.slice(blob, (off,), (off + n_words,))
    if dtype_str == "float32":
        return lax.bitcast_convert_type(w, jnp.float32).reshape(shape)
    if dtype_str == "int32":
        return lax.bitcast_convert_type(w, jnp.int32).reshape(shape)
    if dtype_str in ("uint8", "bool", "int8"):
        n = math.prod(shape)
        shifts = jnp.arange(4, dtype=jnp.uint32) * 8
        by = ((w[:, None] >> shifts) & jnp.uint32(0xFF)).astype(jnp.uint8)
        a = by.reshape(-1)[:n].reshape(shape)
        if dtype_str == "bool":
            return a != 0
        if dtype_str == "int8":
            # Same-width bitcast, not a value convert — int8 fields
            # (the kind view's 0/1 pattern) round-trip bit-exactly.
            return lax.bitcast_convert_type(a, jnp.int8)
        return a
    raise TypeError(f"blob staging: unsupported leaf dtype {dtype_str!r}")


@contract(blob="uint32[N]", returns="windowgraph")
def unpack_graph_blob(blob, layout: BlobLayout) -> WindowGraph:
    """Device side (traced): rebuild the WindowGraph from the blob —
    the @contract closes the pack/unpack round trip: the rebuilt graph
    must carry the canonical field dtypes (shape-only checks, so the
    wrapper is trace-compatible and costs nothing per cached call)."""
    parts = [
        PartitionGraph(*(_decode_leaf(blob, *e[1:]) for e in entries))
        for entries in layout
    ]
    return WindowGraph(normal=parts[0], abnormal=parts[1])


@contract(
    blob="uint32[N]",
    returns=("int32[K]", "float32[K]", "int32[]"),
)
def rank_window_blob_core(
    blob, layout, pagerank_cfg, spectrum_cfg, psum_axis=None, kernel="coo"
):
    from .jax_tpu import rank_window_core

    graph = unpack_graph_blob(blob, layout)
    return rank_window_core(graph, pagerank_cfg, spectrum_cfg, psum_axis, kernel)


rank_window_blob_device = jax.jit(
    rank_window_blob_core, static_argnums=(1, 2, 3, 4, 5)
)


@contract(
    blob="uint32[N]",
    returns=("int32[B,K]", "float32[B,K]", "int32[B]"),
)
def rank_windows_batched_blob_core(
    blob, layout, pagerank_cfg, spectrum_cfg, kernel="coo"
):
    from .jax_tpu import divide_block_budget, rank_window_core

    graph = unpack_graph_blob(blob, layout)
    b = graph.normal.kind.shape[0]
    pagerank_cfg = divide_block_budget(pagerank_cfg, kernel, b)
    return jax.vmap(
        lambda g: rank_window_core(g, pagerank_cfg, spectrum_cfg, None, kernel)
    )(graph)


rank_windows_batched_blob_device = jax.jit(
    rank_windows_batched_blob_core, static_argnums=(1, 2, 3, 4)
)


@contract(
    blob="uint32[N]",
    returns=(
        "int32[K]", "float32[K]", "int32[]", "float32[2,I]", "int32[]"
    ),
)
def rank_window_traced_blob_core(
    blob, layout, pagerank_cfg, spectrum_cfg, psum_axis=None, kernel="coo"
):
    """Blob twin of jax_tpu.rank_window_traced_core: the convergence
    trace (residuals + iteration count) is part of the program's output
    tuple — telemetry rides the existing result blob, no extra sync."""
    from .jax_tpu import rank_window_traced_core

    graph = unpack_graph_blob(blob, layout)
    return rank_window_traced_core(
        graph, pagerank_cfg, spectrum_cfg, psum_axis, kernel
    )


rank_window_traced_blob_device = jax.jit(
    rank_window_traced_blob_core, static_argnums=(1, 2, 3, 4, 5)
)


@contract(
    blob="uint32[N]",
    returns=(
        "int32[B,K]", "float32[B,K]", "int32[B]", "float32[B,2,I]",
        "int32[B]",
    ),
)
def rank_windows_traced_batched_blob_core(
    blob, layout, pagerank_cfg, spectrum_cfg, kernel="coo"
):
    from .jax_tpu import divide_block_budget, rank_window_traced_core

    graph = unpack_graph_blob(blob, layout)
    b = graph.normal.kind.shape[0]
    pagerank_cfg = divide_block_budget(pagerank_cfg, kernel, b)
    return jax.vmap(
        lambda g: rank_window_traced_core(
            g, pagerank_cfg, spectrum_cfg, None, kernel
        )
    )(graph)


rank_windows_traced_batched_blob_device = jax.jit(
    rank_windows_traced_batched_blob_core, static_argnums=(1, 2, 3, 4)
)


@contract(
    blob="uint32[N]",
    returns=(
        "int32[K]", "float32[K]", "int32[]", "float32[2,I]", "int32[]",
        "float32[V]", "float32[T]", "float32[V]", "float32[U]",
    ),
)
def rank_window_warm_blob_core(
    blob, layout, init, pagerank_cfg, spectrum_cfg, kernel="coo"
):
    """Blob twin of jax_tpu.rank_window_warm_core — the FUSED pair
    program: one staged buffer, one dispatch running the normal and
    abnormal PageRank solves plus the spectrum epilogue, exporting the
    converged state for the next window's warm start. ``init=None``
    (a pytree-structure change, so its own cached program) is the cold
    seed that still exports state."""
    from .jax_tpu import rank_window_warm_core

    graph = unpack_graph_blob(blob, layout)
    return rank_window_warm_core(
        graph, init, pagerank_cfg, spectrum_cfg, kernel
    )


rank_window_warm_blob_device = jax.jit(
    rank_window_warm_blob_core, static_argnums=(1, 3, 4, 5)
)


def stage_rank_window_warm(
    graph: WindowGraph,
    init,
    pagerank_cfg,
    spectrum_cfg,
    kernel,
    blob: bool,
):
    """stage_rank_window's warm/fused sibling: stage ONE window and run
    the pair program (both solves + spectrum epilogue) in ONE dispatch,
    threading ``init`` — the previous window's mapped (sv_n, rv_n, sv_a,
    rv_a) state, or None for a cold seed. Returns the 9-tuple of device
    handles; entries [5:9] are the state export the caller captures for
    the next window. Same witness/telemetry contract as
    stage_rank_window (the compile-witness program name is
    "blob.stage_rank_window_warm")."""
    from ..analysis import mrsan
    from ..obs.metrics import record_retrace
    from ..utils.guards import assert_device_owner

    assert_device_owner("blob.stage_rank_window_warm")
    if mrsan.witness_armed():
        mrsan.observe_compile_key(
            "blob.stage_rank_window_warm", kernel=kernel, graph=graph,
            occupancy=1,
        )
    if init is not None:
        init = tuple(jax.device_put(x) for x in init)
    if blob:
        blob_arr, layout = pack_graph_blob(graph)
        _account_staging(graph, "blob", 1)
        out = rank_window_warm_blob_device(
            jax.device_put(blob_arr), layout, init, pagerank_cfg,
            spectrum_cfg, kernel,
        )
        record_retrace(
            "rank_window_warm_blob", rank_window_warm_blob_device
        )
        return out
    from .jax_tpu import rank_window_warm_device

    _account_staging(graph, "tree", len(jax.tree.leaves(graph)))
    out = rank_window_warm_device(
        jax.device_put(graph), init, pagerank_cfg, spectrum_cfg, kernel
    )
    record_retrace("rank_window_warm", rank_window_warm_device)
    return out


def _rank_window_blob_checked_core(
    blob, layout, pagerank_cfg, spectrum_cfg, kernel="coo"
):
    from .jax_tpu import rank_window_checked_core

    graph = unpack_graph_blob(blob, layout)
    return rank_window_checked_core(
        graph, pagerank_cfg, spectrum_cfg, kernel
    )


def _rank_window_blob_checked_traced_core(
    blob, layout, pagerank_cfg, spectrum_cfg, kernel="coo"
):
    """Blob twin of jax_tpu.rank_window_checked_traced_core: checkify
    assertions AND the convergence trace in one blob-staged program, so
    device_checks stops dropping residual telemetry on this path."""
    from .jax_tpu import rank_window_checked_traced_core

    graph = unpack_graph_blob(blob, layout)
    return rank_window_checked_traced_core(
        graph, pagerank_cfg, spectrum_cfg, kernel
    )


_BLOB_CHECKED_JIT = None
_BLOB_CHECKED_TRACED_JIT = None


def _blob_checked_jit():
    global _BLOB_CHECKED_JIT
    if _BLOB_CHECKED_JIT is None:
        from jax.experimental import checkify

        _BLOB_CHECKED_JIT = jax.jit(
            checkify.checkify(
                _rank_window_blob_checked_core, errors=checkify.user_checks
            ),
            static_argnums=(1, 2, 3, 4),
        )
    return _BLOB_CHECKED_JIT


def _blob_checked_traced_jit():
    global _BLOB_CHECKED_TRACED_JIT
    if _BLOB_CHECKED_TRACED_JIT is None:
        from jax.experimental import checkify

        _BLOB_CHECKED_TRACED_JIT = jax.jit(
            checkify.checkify(
                _rank_window_blob_checked_traced_core,
                errors=checkify.user_checks,
            ),
            static_argnums=(1, 2, 3, 4),
        )
    return _BLOB_CHECKED_TRACED_JIT


def _account_staging(graph: WindowGraph, path: str, n_transfers: int):
    """Staging telemetry: bytes, transfer count, pad waste — the
    counters that turn compile storms and pad_policy overhead into data
    (obs.metrics). Pad waste is AUDITED per staged leaf against exact
    live extents (graph_staging_audit), not estimated from mean live
    fractions. Host-side arrays only; ~52 nbytes reads."""
    from ..obs.metrics import graph_staging_audit, record_staging

    total, pad = graph_staging_audit(graph)
    record_staging(path, total, n_transfers, pad)


def stage_rank_window(
    graph: WindowGraph,
    pagerank_cfg,
    spectrum_cfg,
    kernel,
    blob: bool,
    checked: bool = False,
    conv_trace: bool = False,
    explain=None,
):
    """The one single-device stage+dispatch seam both the backend
    (JaxBackend.rank_window) and the pipeline (TableRCA.launch_rank)
    call: blob staging when enabled, per-leaf device_put otherwise. The
    graph should already be device_subset-stripped for ``kernel``.
    Every dispatch records staged bytes/transfers and jit-cache growth
    into the metrics registry (obs.metrics).

    ``checked`` (RuntimeConfig.device_checks) dispatches the
    checkify-instrumented program instead — still blob-staged when
    ``blob`` is on, module-level jit cache either way — and raises
    ``checkify.JaxRuntimeError`` on an in-program invariant failure.
    ``conv_trace`` (RuntimeConfig.convergence_trace) dispatches the
    residual-traced program: the return grows to a 5-tuple whose last
    two entries are (residuals float32[2, I], n_iters int32), still all
    device values. ``checked`` composes with it: the checkify program
    has a residual-traced twin (rank_window_checked_traced), so
    device_checks + conv_trace yields the checked 5-tuple instead of
    silently dropping telemetry.

    ``explain`` (an ``ExplainConfig``, or None): dispatch the EXPLAINED
    traced twin instead — the return grows to the 10-tuple whose last
    five entries are the attribution tensors (explain.extract). The
    explained program always carries the convergence trace; it does not
    thread checkify (explain is an on-demand / incident-open path — the
    host-side score validation still applies), so ``checked`` is
    ignored for this dispatch.
    """
    from ..obs.metrics import record_retrace
    from ..utils.guards import assert_device_owner

    assert_device_owner("blob.stage_rank_window")
    from ..analysis import mrsan

    if mrsan.witness_armed():
        mrsan.observe_compile_key(
            "blob.stage_rank_window", kernel=kernel, graph=graph,
            occupancy=1,
        )

    if explain is not None and getattr(explain, "enabled", False):
        from ..explain.extract import (
            rank_window_explained_blob_device,
            rank_window_explained_device,
        )

        if blob:
            blob_arr, layout = pack_graph_blob(graph)
            _account_staging(graph, "blob", 1)
            out = rank_window_explained_blob_device(
                jax.device_put(blob_arr), layout, pagerank_cfg,
                spectrum_cfg, explain, kernel,
            )
            record_retrace(
                "rank_window_explained_blob",
                rank_window_explained_blob_device,
            )
            return out
        _account_staging(graph, "tree", len(jax.tree.leaves(graph)))
        out = rank_window_explained_device(
            jax.device_put(graph), pagerank_cfg, spectrum_cfg, explain,
            None, kernel,
        )
        record_retrace(
            "rank_window_explained", rank_window_explained_device
        )
        return out
    if checked:
        if blob:
            from jax.experimental import checkify

            blob_arr, layout = pack_graph_blob(graph)
            _account_staging(graph, "blob", 1)
            fn = (
                _blob_checked_traced_jit()
                if conv_trace
                else _blob_checked_jit()
            )
            err, out = fn(
                jax.device_put(blob_arr),
                layout,
                pagerank_cfg,
                spectrum_cfg,
                kernel,
            )
            checkify.check_error(err)
            return out
        from .jax_tpu import rank_window_checked, rank_window_checked_traced

        _account_staging(graph, "tree", len(jax.tree.leaves(graph)))
        fn = rank_window_checked_traced if conv_trace else rank_window_checked
        return fn(
            jax.device_put(graph), pagerank_cfg, spectrum_cfg, kernel
        )
    if blob:
        blob_arr, layout = pack_graph_blob(graph)
        _account_staging(graph, "blob", 1)
        fn = (
            rank_window_traced_blob_device
            if conv_trace
            else rank_window_blob_device
        )
        out = fn(
            jax.device_put(blob_arr), layout, pagerank_cfg, spectrum_cfg,
            None, kernel,
        )
        record_retrace(
            "rank_window_blob_traced" if conv_trace else "rank_window_blob",
            fn,
        )
        return out
    from .jax_tpu import rank_window_device, rank_window_traced_device

    _account_staging(graph, "tree", len(jax.tree.leaves(graph)))
    fn = rank_window_traced_device if conv_trace else rank_window_device
    out = fn(
        jax.device_put(graph), pagerank_cfg, spectrum_cfg, None, kernel
    )
    record_retrace(
        "rank_window_traced" if conv_trace else "rank_window", fn
    )
    return out


# Donated twins of the batched blob jits (built lazily once, module
# cached): the staged blob buffer is marked donated so XLA may reuse
# its HBM for outputs — under the dispatch router's double-buffering
# two staged batches are alive at once, and donation caps that at one
# blob plus the in-flight program's working set.
_DONATED_BLOB_JIT = None
_DONATED_TRACED_BLOB_JIT = None


def _donated_blob_jit():
    global _DONATED_BLOB_JIT
    if _DONATED_BLOB_JIT is None:
        _DONATED_BLOB_JIT = jax.jit(
            rank_windows_batched_blob_core,
            static_argnums=(1, 2, 3, 4),
            donate_argnums=(0,),
        )
    return _DONATED_BLOB_JIT


def _donated_traced_blob_jit():
    global _DONATED_TRACED_BLOB_JIT
    if _DONATED_TRACED_BLOB_JIT is None:
        _DONATED_TRACED_BLOB_JIT = jax.jit(
            rank_windows_traced_batched_blob_core,
            static_argnums=(1, 2, 3, 4),
            donate_argnums=(0,),
        )
    return _DONATED_TRACED_BLOB_JIT


def batched_blob_entry(conv_trace: bool, donate: bool):
    """The batched blob program jit for (conv_trace, donate) — the
    non-donated keys alias the module-level jits above (shared cache)."""
    if donate:
        return (
            _donated_traced_blob_jit()
            if conv_trace
            else _donated_blob_jit()
        )
    return (
        rank_windows_traced_batched_blob_device
        if conv_trace
        else rank_windows_batched_blob_device
    )


def stage_windows_batched(batched: WindowGraph, blob: bool):
    """Staging HALF of ``stage_rank_windows_batched``: pack (blob mode)
    and issue the H2D transfer — which proceeds asynchronously — and
    return an opaque staged handle for ``dispatch_windows_staged``.
    Splitting stage from dispatch is what lets the dispatch router
    double-buffer: batch N+1 stages through here while batch N's
    program is still executing, and nothing blocks until the consumer
    fetches results. The stacked graph should already be
    device_subset-stripped for its kernel.
    """
    from ..utils.guards import assert_device_owner

    assert_device_owner("blob.stage_windows_batched")
    if blob:
        blob_arr, layout = pack_graph_blob(batched)
        _account_staging(batched, "blob", 1)
        return ("blob", jax.device_put(blob_arr), layout)
    _account_staging(batched, "tree", len(jax.tree.leaves(batched)))
    return ("tree", jax.device_put(batched), None)


def dispatch_windows_staged(
    staged,
    pagerank_cfg,
    spectrum_cfg,
    kernel,
    conv_trace: bool = False,
    donate: bool = False,
):
    """Dispatch HALF: issue the vmapped batched rank program over an
    already-staged handle. Returns device output handles (dispatch is
    async — the caller's ``jax.device_get`` is the consumer edge).
    ``donate`` releases the staged blob's device buffer to the program
    (ignored in tree mode and on backends without donation)."""
    from ..obs.metrics import record_retrace
    from ..utils.guards import assert_device_owner

    assert_device_owner("blob.dispatch_windows_staged")

    if staged[0] == "blob":
        _, blob_dev, layout = staged
        fn = batched_blob_entry(conv_trace, donate)
        # blob_dev is not read again after a donating call — the buffer
        # belongs to XLA from here.
        out = fn(blob_dev, layout, pagerank_cfg, spectrum_cfg, kernel)
        record_retrace(
            "rank_windows_batched_blob_traced"
            if conv_trace
            else "rank_windows_batched_blob",
            fn,
        )
        return out
    # Tree mode: the batched jits divide the packed-block budget by the
    # resident window count themselves.
    from ..parallel.sharded_rank import (
        _rank_windows_batched_jit,
        _rank_windows_batched_traced_jit,
    )

    _, tree_dev, _ = staged
    fn = (
        _rank_windows_batched_traced_jit
        if conv_trace
        else _rank_windows_batched_jit
    )
    return fn(tree_dev, pagerank_cfg, spectrum_cfg, kernel)


def stage_rank_windows_batched(
    batched: WindowGraph,
    pagerank_cfg,
    spectrum_cfg,
    kernel,
    blob: bool,
    conv_trace: bool = False,
):
    """Batched twin of stage_rank_window (one vmapped program over a
    stacked graph). The stacked graph should already be subset-stripped.
    ``conv_trace`` appends per-window (residuals [B, 2, I],
    n_iters [B]) to the return tuple."""
    from ..obs.metrics import record_retrace
    from ..analysis import mrsan

    if mrsan.witness_armed():
        leaves = jax.tree.leaves(batched)
        mrsan.observe_compile_key(
            "blob.stage_rank_windows_batched", kernel=kernel,
            graph=batched,
            occupancy=int(leaves[0].shape[0]) if leaves else None,
        )

    if blob:
        blob_arr, layout = pack_graph_blob(batched)
        _account_staging(batched, "blob", 1)
        fn = (
            rank_windows_traced_batched_blob_device
            if conv_trace
            else rank_windows_batched_blob_device
        )
        out = fn(
            jax.device_put(blob_arr), layout, pagerank_cfg, spectrum_cfg,
            kernel,
        )
        record_retrace(
            "rank_windows_batched_blob_traced"
            if conv_trace
            else "rank_windows_batched_blob",
            fn,
        )
        return out
    from ..parallel.sharded_rank import (
        rank_windows_batched,
        rank_windows_batched_traced,
    )

    _account_staging(batched, "tree", len(jax.tree.leaves(batched)))
    fn = rank_windows_batched_traced if conv_trace else rank_windows_batched
    return fn(batched, pagerank_cfg, spectrum_cfg, kernel)
