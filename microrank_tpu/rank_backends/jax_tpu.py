"""TPU-native ranking backend (reference components C9-C14, redesigned).

The reference's ranking core is dense numpy matvecs over Python-dict-built
matrices (pagerank.py) plus a per-op Python loop for the spectrum
(online_rca.py:33-152). Here the whole window ranking —

    preference vector -> 25-step power iteration (both partitions)
    -> rescale -> spectrum counters -> formula -> top-k

— is ONE jit-compiled XLA program over padded COO arrays:

* SpMV is gather + segment-sum over the unique (op, trace) incidence
  entries (``p_sr``/``p_rs`` share the pattern, two value arrays) and the
  call edges (``p_ss``);
* the iteration is a ``lax.fori_loop`` (static trip count — the reference
  runs exactly 25 iterations with no convergence check, pagerank.py:117);
* both partitions iterate in the same program (XLA schedules them
  side by side);
* the 13 spectrum formulas are an elementwise [V] kernel fused by XLA;
* ranking ends with a two-key ``lax.sort`` on device (score descending,
  op index ascending — exactly tied scores break deterministically).

The function is vmap-able over a leading window-batch axis and is the unit
the sharded path (microrank_tpu.parallel) wraps with shard_map + psum.
"""

from __future__ import annotations

import functools
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..analysis.contracts import contract
from ..config import MicroRankConfig, PageRankConfig, SpectrumConfig
from ..graph.structures import PartitionGraph, WindowGraph
from ..ops.segment import coo_matvec
from ..spectrum.formulas import spectrum_scores


def preference_vector(
    g: PartitionGraph,
    anomaly: bool,
    cfg: PageRankConfig,
    trace_axis: str | None = None,
):
    """Personalized preference vector on the padded trace axis
    (reference: pagerank.py:68-85; paper Eq (7) behind preference="paper").

    ``trace_axis``: when the trace axis is SHARDED over that mesh axis
    (the packed sharded kernel), the per-trace arrays here are local
    blocks — the live mask offsets by the shard position and the two
    normalization sums are psum'd to their global values.

    Kind-collapsed graphs (``n_cols >= 0`` — collapse_window_graph): a
    column stands for ``kind`` identical traces, whose per-trace
    preference values are equal by construction, so the per-entry
    formulas are unchanged; only the two normalization sums weight each
    column by its multiplicity to recover the true per-trace totals
    (Σ_t 1/kind_t and Σ_t 1/len_t).
    """
    t_pad = g.kind.shape[0]
    base = 0 if trace_axis is None else lax.axis_index(trace_axis) * t_pad
    n_live = jnp.where(g.n_cols < 0, g.n_traces, g.n_cols)
    live = (base + jnp.arange(t_pad)) < n_live
    kind = g.kind.astype(jnp.float32)
    tlen = g.tracelen.astype(jnp.float32)
    # Collapsed columns: kind IS the multiplicity; uncollapsed: weight 1.
    mult = jnp.where(g.n_cols < 0, 1.0, kind)
    inv_kind = jnp.where(live, 1.0 / kind, 0.0)
    inv_len = jnp.where(live, 1.0 / tlen, 0.0)
    kind_sum = (mult * inv_kind).sum()
    num_sum = (mult * inv_len).sum()
    if trace_axis is not None:
        kind_sum = lax.psum(kind_sum, trace_axis)
        num_sum = lax.psum(num_sum, trace_axis)

    if not anomaly:
        pref = inv_kind / kind_sum
    elif cfg.preference == "reference":
        # The code's anomalous form (deviates from paper Eq (7) —
        # SURVEY.md §2.2 quirk #4): phi / num_sum / (kind/kind_sum*phi + 1/n).
        phi = jnp.float32(cfg.phi)
        pref = phi / num_sum / (kind / kind_sum * phi + inv_len)
    elif cfg.preference == "paper":
        phi = jnp.float32(cfg.phi)
        pref = phi * inv_len / num_sum + (1.0 - phi) * inv_kind / kind_sum
    else:
        raise ValueError(f"unknown preference form {cfg.preference!r}")
    return jnp.where(live, pref, 0.0).astype(jnp.float32)


def quantize_i8(x):
    """Symmetric per-vector scaled-int8 quantization — the fixed-point
    operand representation of the streaming-SpMV PPR formulation (arxiv
    2009.10443), applied to the kind kernel's iteration vectors:
    scale = max|x|/127 (guarded for the all-zero vector),
    q = round(x/scale) clamped to [-127, 127]. Returns (q int8,
    scale f32 0-d). Against the 0/1 int8 pattern matrix the int32
    accumulation is EXACT (|sum| <= 127*K << 2^31), so operand
    quantization is the only rounding and one f32 multiply undoes the
    scale."""
    amax = jnp.max(jnp.abs(x))
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(x / scale), -127.0, 127.0).astype(jnp.int8)
    return q, scale


def unpack_bits(bits, n_cols: int, dtype=jnp.float32):
    """Device-side bitmap expansion: uint8[V, C] -> dtype[V, n_cols].

    Inverse of host ``np.packbits(..., axis=1)`` (big-endian bit order) —
    pure shift/mask/reshape, no scatter or gather. ~0.2 ms for the 134 MB
    f32 result at the 1M-span scale, vs ~75 ms for the scatter it replaces.
    """
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    b = (bits[:, :, None] >> shifts) & jnp.uint8(1)
    return b.reshape(bits.shape[0], bits.shape[1] * 8)[:, :n_cols].astype(
        dtype
    )


def pack_edge_bits(child, parent, n_live, n_rows: int):
    """Device-side twin of the host bit-scatter (graph.build._scatter_bits):
    build the uint8[n_rows, ceil(n_rows/8)] call-edge bitmap from the
    (child, parent) edge list with ONE scatter-add of per-edge byte values
    (big-endian bit order, matching np.packbits).

    Edges are unique (child, parent) pairs, so adding each edge's power of
    two composes bytes exactly; entries past ``n_live`` are padding and
    contribute 0. This is the staging-side inverse trade of unpack_bits:
    the edge list is ~V*V/(8*C) times smaller than the bitmap (50-100x at
    the 1M-span scale), so shipping edges and packing on device cuts
    host->device bytes by ~10x while the per-iteration HBM traffic — the
    packed array the fori_loop streams — stays identical.
    """
    c_pad = child.shape[0]
    live = jnp.arange(c_pad, dtype=jnp.int32) < n_live
    bitval = jnp.where(live, jnp.int32(1) << (7 - (parent % 8)), 0)
    packed = (
        jnp.zeros((n_rows, (n_rows + 7) // 8), jnp.int32)
        .at[child, parent // 8]
        .add(bitval, mode="promise_in_bounds")
    )
    return packed.astype(jnp.uint8)


def _ss_packed_bits(g: PartitionGraph, v: int):
    """The call-edge bitmap for the packed kernels: host-packed
    (ss_stage="bits") or rebuilt on device from the edge list (the
    default staging profile — ~10x fewer host->device bytes)."""
    if g.ss_bits.shape[-1] > 0:
        return g.ss_bits
    if g.ss_child.shape[-1] > 0:
        return pack_edge_bits(g.ss_child, g.ss_parent, g.n_ss, v)
    raise ValueError(
        "packed kernels need the call-edge bitmap or edge list, but both "
        "were stripped — stage with device_subset(graph, 'packed') or "
        "build with aux='packed'/'all'"
    )


def _n_col_blocks(rows: int, words: int, limit_bytes: int) -> int:
    """Fewest power-of-two column blocks of a [rows, words] uint8 bitmap
    such that one unpacked f32 block fits ``limit_bytes`` (static shapes
    — pure trace-time Python). Word counts that don't divide evenly are
    fine: _blocked_bits_matvecs zero-pads the word axis up to the block
    multiple (zero bits are inert), so the cap is honored for any word
    count down to one-word blocks. Only a single-word column that still
    exceeds the cap (rows alone too large) warns and proceeds —
    correctness is unaffected."""
    n = 1
    while rows * (-(-words // n)) * 8 * 4 > limit_bytes and n < words:
        n *= 2
    if rows * (-(-words // n)) * 8 * 4 > limit_bytes:
        from ..utils.logging import get_logger

        get_logger("microrank_tpu.rank.packed_blocked").warning(
            "packed_block_bytes=%d not honorable: [%d, %d]-word bitmap "
            "at one-word blocks still unpacks %d bytes per block (the "
            "row count alone exceeds the cap)",
            limit_bytes, rows, words, rows * 8 * 4,
        )
    return n


def divide_block_budget(pagerank_cfg, kernel: str, n_resident: int):
    """Under vmap (or any dispatch holding ``n_resident`` windows live at
    once) each scan step of the blocked kernel materializes one unpacked
    block PER WINDOW, so the per-window cap must shrink by the batch size
    to keep the total intermediate within packed_block_bytes. Static
    trace-time transform (configs are jit cache keys)."""
    import dataclasses

    if kernel != "packed_blocked" or n_resident <= 1:
        return pagerank_cfg
    return dataclasses.replace(
        pagerank_cfg,
        packed_block_bytes=max(
            1, pagerank_cfg.packed_block_bytes // int(n_resident)
        ),
    )


def _blocked_bits_matvecs(bits, n_blocks: int, mat_dtype, with_bwd: bool):
    """Column-blocked twin of the packed kernel's matvec pair: unpack one
    [rows, cols/n_blocks] f32 block per scan step and accumulate
    ``y_fwd = B @ x_col`` (and, when ``with_bwd``, emit the per-block
    slices of ``y_bwd = x_row @ B``), so HBM never holds more than one
    unpacked block. Streams the same packed bytes per iteration as the
    unblocked kernel — the cost is scan-step launch overhead, not extra
    traffic.

    Returns ``pair(x_col, x_row) -> (y_fwd[rows], y_bwd[>=words*8]|None)``;
    ``x_col`` must already be padded to ``words*8`` entries. Word counts
    that don't divide ``n_blocks`` are zero-padded up to the block
    multiple (zero bits/entries are inert); callers slice ``y_bwd`` back
    to their true extent.
    """
    rows, words = bits.shape
    wb = -(-words // n_blocks)
    pad_w = wb * n_blocks - words
    if pad_w:
        bits = jnp.pad(bits, ((0, 0), (0, pad_w)))
    cols_b = wb * 8
    blocks = bits.reshape(rows, n_blocks, wb).transpose(1, 0, 2)

    def pair(x_col, x_row=None):
        xb = _pad_cols(x_col, n_blocks * cols_b).reshape(n_blocks, cols_b)

        def step(acc, inp):
            bits_b, x_b = inp
            m = unpack_bits(bits_b, cols_b, mat_dtype)
            y = acc + jnp.dot(
                m,
                x_b.astype(mat_dtype),
                preferred_element_type=jnp.float32,
            )
            if not with_bwd:
                return y, None
            return y, jnp.dot(
                x_row.astype(mat_dtype),
                m,
                preferred_element_type=jnp.float32,
            )

        y_fwd, y_bwd = lax.scan(
            step, jnp.zeros((rows,), jnp.float32), (blocks, xb)
        )
        return y_fwd, (y_bwd.reshape(-1) if with_bwd else None)

    return pair


def _pad_cols(x, total: int):
    return x if x.shape[0] == total else jnp.pad(x, (0, total - x.shape[0]))


def densify(g: PartitionGraph):
    """Scatter the COO entries into the dense reference-shaped matrices
    (pagerank.py:19-24) on device: [V, T] p_sr, [T, V] p_rs, [V, V] p_ss.

    Entries are unique pairs so scatter-add equals overwrite; padding rows
    carry value 0 and land harmlessly at index 0. Dense matvecs put the 25
    iterations on the MXU — the fastest path whenever (2*V*T + V^2) floats
    fit comfortably in HBM; the COO segment-sum path covers the rest.
    """
    v = g.cov_unique.shape[0]
    t = g.kind.shape[0]
    p_sr = jnp.zeros((v, t), jnp.float32).at[g.inc_op, g.inc_trace].add(
        g.sr_val
    )
    p_rs = jnp.zeros((t, v), jnp.float32).at[g.inc_trace, g.inc_op].add(
        g.rs_val
    )
    p_ss = jnp.zeros((v, v), jnp.float32).at[g.ss_child, g.ss_parent].add(
        g.ss_val
    )
    return p_ss, p_sr, p_rs


def _partition_setup(
    g: PartitionGraph,
    anomaly: bool,
    cfg: PageRankConfig,
    psum_axis: str | None = None,
    kernel: str = "coo",
):
    """One partition's iteration ingredients:
    (matvecs, pref, sv0, rv0, rv_axis).

    Factored out of partition_pagerank so rank_window_core can step BOTH
    partitions inside one fori_loop (their updates are independent; fusing
    them halves the loop-body op count, which matters on latency-sensitive
    runtimes).

    ``rv_axis`` is the mesh axis the trace vector ``rv`` is SHARDED over
    (the packed sharded kernel keeps rv distributed — its bitmap columns
    split over the shard axis), or None when rv is replicated (coo/csr
    shard the ENTRY axes instead and psum dense partials).
    """
    v = g.cov_unique.shape[0]
    t_pad = g.kind.shape[0]
    n_total = (g.n_ops + g.n_traces).astype(jnp.float32)
    rv_axis = (
        psum_axis
        if psum_axis is not None
        and kernel in ("packed", "packed_bf16", "kind")
        else None
    )
    t_base = 0 if rv_axis is None else lax.axis_index(rv_axis) * t_pad
    # Live trace COLUMNS: n_cols when kind-collapsed, n_traces otherwise
    # (n_total above always uses the TRUE trace count — the reference's
    # 1/(O+T) initial value is collapse-invariant).
    n_live_cols = jnp.where(g.n_cols < 0, g.n_traces, g.n_cols)
    trace_live = (t_base + jnp.arange(t_pad)) < n_live_cols

    pref = preference_vector(g, anomaly, cfg, rv_axis)
    d = jnp.float32(cfg.damping)
    alpha = jnp.float32(cfg.call_weight)

    # Entry-sharded kernels optionally combine their dense partials
    # with the compensated fold (PageRankConfig.compensated_psum,
    # default off — see the config comment: the per-shard partials'
    # own rounding dominates, so the compensated combine measured no
    # material parity gain for coo; kept as the opt-in evaluation
    # artifact of the ROADMAP compensated-scan item).
    compensate = bool(
        getattr(cfg, "compensated_psum", False)
        and kernel in ("coo", "csr", "pcsr", "pallas")
    )
    # Sparse-allreduce prototype (arxiv 1312.3020; ISSUE-11 satellite):
    # swap the dense psum of the [V]/[T] partials for a top-cap
    # (index, value) exchange. Opt-in and OFF by default — see the
    # config comment and DESIGN.md "Sparse allreduce evaluation".
    sparse = bool(
        getattr(cfg, "sparse_allreduce", False)
        and kernel in ("coo", "csr", "pcsr", "pallas")
    )
    sparse_cap = int(getattr(cfg, "sparse_allreduce_cap", 0))

    def reduce_shards(x):
        if psum_axis is None:
            return x
        if sparse:
            from ..ops.segment import sparse_psum

            return sparse_psum(x, psum_axis, cap=sparse_cap)
        if compensate:
            from ..ops.segment import compensated_psum

            return compensated_psum(x, psum_axis)
        return lax.psum(x, psum_axis)

    sv = jnp.where(g.op_present, 1.0 / n_total, 0.0).astype(jnp.float32)
    rv = jnp.where(trace_live, 1.0 / n_total, 0.0).astype(jnp.float32)

    if kernel in ("dense", "dense_bf16"):
        if psum_axis is not None:
            raise ValueError(
                "the dense kernel does not support entry-axis sharding; "
                "use kernel='coo' under shard_map"
            )
        p_ss, p_sr, p_rs = densify(g)
        if kernel == "dense_bf16":
            # bf16 operands, f32 accumulation: halves the HBM traffic of
            # the matrix reads (the iteration is bandwidth-bound) while
            # max-normalization keeps values in bf16's comfortable range;
            # rank parity is tested, score tolerance widens.
            p_ss = p_ss.astype(jnp.bfloat16)
            p_sr = p_sr.astype(jnp.bfloat16)
            p_rs = p_rs.astype(jnp.bfloat16)

            def matvecs(sv, rv):
                return (
                    jnp.dot(
                        p_sr,
                        rv.astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32,
                    )
                    + alpha
                    * jnp.dot(
                        p_ss,
                        sv.astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32,
                    ),
                    jnp.dot(
                        p_rs,
                        sv.astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32,
                    ),
                )

        else:

            def matvecs(sv, rv):
                return (
                    jnp.dot(p_sr, rv) + alpha * jnp.dot(p_ss, sv),
                    jnp.dot(p_rs, sv),
                )

    elif kernel == "coo":

        def matvecs(sv, rv):
            return (
                reduce_shards(
                    coo_matvec(g.inc_op, g.inc_trace, g.sr_val, rv, v)
                    + alpha
                    * coo_matvec(g.ss_child, g.ss_parent, g.ss_val, sv, v)
                ),
                reduce_shards(
                    coo_matvec(g.inc_trace, g.inc_op, g.rs_val, sv, t_pad)
                ),
            )

    elif kernel in ("packed", "packed_bf16"):
        # The MXU path without the scatter: every transition matrix is a
        # 0/1 pattern scaled along its source axis, so the program unpacks
        # the host-packed pattern bitmaps with shift/mask ops and applies
        # the scaling as elementwise vector products around plain dense
        # matvecs. One [V, T] matrix serves BOTH directions (p_sr uses it
        # as-is, p_rs is its transpose with a different scaling), halving
        # resident matrix bytes vs the dense kernel — and TPU matvecs beat
        # per-entry gathers/scatters by ~an order of magnitude here.
        #
        # Sharded (psum_axis set): the TRACE axis distributes — each
        # device holds a [V, T/S] bitmap column block, rv/inv_tracelen/
        # kind/tracelen live as local [T/S] blocks, the call-graph bitmap
        # and sv stay replicated. Per iteration: ONE psum combines the
        # b_cov @ rv partials (the b_ss term is replicated and must NOT
        # be summed), and y_r needs no collective at all (each device
        # computes its own rv block) — half the collectives of the
        # entry-sharded csr/coo path, on the fastest kernel.
        if g.cov_bits.shape[-1] == 0:
            raise ValueError(
                "kernel='packed' needs bitmaps, but this window was built "
                "without them (aux policy chose csr — past the dense "
                "budget — or aux='none') — build with aux='packed'/'all' "
                "or use kernel='csr'"
            )
        mat_dtype = (
            jnp.bfloat16 if kernel == "packed_bf16" else jnp.float32
        )
        b_cov = unpack_bits(g.cov_bits, t_pad, mat_dtype)
        # The call-edge bitmap arrives either host-packed (ss_stage="bits")
        # or — the default staging profile — as the raw edge list, packed
        # on device by one scatter-add (pack_edge_bits): same uint8 array,
        # ~10x fewer host->device bytes. Loop-invariant, so XLA builds it
        # once per program, not per iteration.
        b_ss = unpack_bits(_ss_packed_bits(g, v), v, mat_dtype)
        w_len = g.inv_tracelen
        w_cov = g.inv_cov_dup
        w_out = g.inv_outdeg

        # reduce_shards psums over psum_axis == rv_axis here: ONLY the
        # b_cov partials sum; the replicated b_ss term stays outside.
        def matvecs(sv, rv):
            return (
                reduce_shards(
                    jnp.dot(
                        b_cov,
                        (rv * w_len).astype(mat_dtype),
                        preferred_element_type=jnp.float32,
                    )
                )
                + alpha
                * jnp.dot(
                    b_ss,
                    (sv * w_out).astype(mat_dtype),
                    preferred_element_type=jnp.float32,
                ),
                jnp.dot(
                    (sv * w_cov).astype(mat_dtype),
                    b_cov,
                    preferred_element_type=jnp.float32,
                ),
            )

    elif kernel == "kind":
        # Kind-compressed, reduced-precision iteration (ROADMAP item 1;
        # representation per the FPGA streaming-SpMV PPR work, arxiv
        # 2009.10443, keeping the fused single-dispatch shape of
        # FUSED-PAGERANK, arxiv 2203.09284). Two changes vs "packed",
        # both aimed at the measured roofline (DESIGN.md "Device time
        # and utilization": the packed loop is capped by shift/mask
        # UNPACK ARITHMETIC over matrix cells, not by bandwidth or MXU):
        #
        #   * the coverage matrix is the MATERIALIZED int8 0/1 pattern
        #     over the kind-collapsed column axis (graph build already
        #     folded each kind's multiplicity/len into inv_tracelen and
        #     the preference sums weight by multiplicity — PageRank over
        #     weighted unique kinds is exactly the per-trace iteration).
        #     0/1 is exact in int8, the 8x byte cost over the bitmap is
        #     amortized by the dedup-factor column shrink, and the
        #     per-iteration unpack disappears: the matrix streams as-is
        #     (int8) or through one loop-invariant cast (bf16/f32);
        #   * the call-graph term never becomes a [V, V] matvec (the
        #     dominant cell count once the coverage axis collapsed): it
        #     is an O(C) scatter-free row-sum over the ss edge list —
        #     gather + compensated cumsum differenced at ss_indptr —
        #     and the call graph has C ~ V*fanout unique edges, a tiny
        #     fraction of V^2 cells.
        #
        # Precision (cfg.kind_precision): "f32" (default — the cast
        # matvec is bit-identical to the f32 packed kernel, so
        # auto-selection preserves every tight-parity guarantee) /
        # "bf16" cast the pattern once and run packed-style
        # mixed-precision matvecs (f32 accumulate via
        # preferred_element_type); "int8" keeps the pattern int8 and
        # QUANTIZES the operand vector per iteration (quantize_i8:
        # symmetric max|x|/127 scale), accumulating in int32 — exact
        # accumulation, operand quantization the only rounding, one f32
        # multiply rescales. The f64 sparse oracle pins tie-aware top-k
        # parity for every precision in the tests.
        #
        # Sharded (psum_axis set): the KIND column axis distributes
        # exactly like the packed kernel's trace axis — each device
        # holds a [V, K/S] pattern block and local [K/S] vectors, ONE
        # psum combines the coverage partials, y_r needs no collective,
        # and the O(C) ss row-sum is replicated work outside the psum
        # (the packed kernel's replicated-b_ss argument, at 1/V-th the
        # flops).
        if g.cov_i8.shape[-1] == 0:
            raise ValueError(
                "kernel='kind' needs the kind-compressed views, but "
                "this window was built without them — build with "
                "aux='kind' (collapse_kinds != 'off' resolves "
                "aux='auto' to it past the dedup threshold)"
            )
        if g.ss_indptr.shape[-1] == 0:
            raise ValueError(
                "kernel='kind' needs the call-edge row offsets — build "
                "with aux='kind'"
            )
        precision = str(getattr(cfg, "kind_precision", "bf16"))
        if precision not in ("int8", "bf16", "f32"):
            raise ValueError(
                f"unknown kind_precision {precision!r} "
                "(expected 'int8' | 'bf16' | 'f32')"
            )
        if precision == "int8":
            q_mat = g.cov_i8

            def cov_pair(x_col, x_row):
                qc, sc = quantize_i8(x_col)
                qr, sr = quantize_i8(x_row)
                y_fwd = sc * jnp.dot(
                    q_mat, qc, preferred_element_type=jnp.int32
                ).astype(jnp.float32)
                y_bwd = sr * jnp.dot(
                    qr, q_mat, preferred_element_type=jnp.int32
                ).astype(jnp.float32)
                return y_fwd, y_bwd

        else:
            mat_dtype = (
                jnp.bfloat16 if precision == "bf16" else jnp.float32
            )
            m = g.cov_i8.astype(mat_dtype)  # loop-invariant: cast once

            def cov_pair(x_col, x_row):
                return (
                    jnp.dot(
                        m,
                        x_col.astype(mat_dtype),
                        preferred_element_type=jnp.float32,
                    ),
                    jnp.dot(
                        x_row.astype(mat_dtype),
                        m,
                        preferred_element_type=jnp.float32,
                    ),
                )

        from ..ops.segment import compensated_cumsum

        def ss_rowsum(sv):
            # Scatter-free O(C) call-graph term: same compensated
            # prefix-difference as the csr kernel's rowsum (position-
            # independent rounding keeps exact ties exact), over the
            # REPLICATED edge list — base 0 in every layout.
            prod = g.ss_val * jnp.take(sv, g.ss_parent)
            hi, lo_c = compensated_cumsum(prod)
            z = jnp.zeros((1,), jnp.float32)
            hi = jnp.concatenate([z, hi])
            lo_c = jnp.concatenate([z, lo_c])
            a = g.ss_indptr[:-1]
            b = g.ss_indptr[1:]
            return (jnp.take(hi, b) - jnp.take(hi, a)) + (
                jnp.take(lo_c, b) - jnp.take(lo_c, a)
            )

        w_len = g.inv_tracelen
        w_cov = g.inv_cov_dup

        # reduce_shards psums over psum_axis == rv_axis here: ONLY the
        # coverage partials sum; the replicated ss term stays outside.
        def matvecs(sv, rv):
            y_cov, y_r = cov_pair(rv * w_len, sv * w_cov)
            return reduce_shards(y_cov) + alpha * ss_rowsum(sv), y_r

    elif kernel == "packed_blocked":
        # The at-scale packed path (VERDICT r3 #4): same math and same
        # per-iteration packed-byte traffic as "packed", but the bitmap's
        # column axis splits into power-of-two blocks streamed through a
        # lax.scan, so the unpacked f32 intermediate never exceeds
        # cfg.packed_block_bytes — usable far past the dense budget that
        # gates "packed" (which would otherwise fall back to the ~90x
        # slower csr kernel). Single-device only: the sharded packed
        # kernel already splits the trace axis across devices, which is
        # the multi-chip form of the same idea.
        if psum_axis is not None:
            raise ValueError(
                "kernel='packed_blocked' is single-device; shard with "
                "'packed' (trace-sharded) or 'csr'/'coo' (entry-sharded)"
            )
        if g.cov_bits.shape[-1] == 0:
            raise ValueError(
                "kernel='packed_blocked' needs bitmaps, but this window "
                "was built without them — build with aux='packed'/'all'"
            )
        mat_dtype = jnp.float32
        ss_packed = _ss_packed_bits(g, v)
        limit = int(cfg.packed_block_bytes)
        cov_words = g.cov_bits.shape[1]
        ss_words = ss_packed.shape[1]
        cov_pair = _blocked_bits_matvecs(
            g.cov_bits, _n_col_blocks(v, cov_words, limit), mat_dtype, True
        )
        ss_fwd = _blocked_bits_matvecs(
            ss_packed, _n_col_blocks(v, ss_words, limit), mat_dtype, False
        )
        w_len = g.inv_tracelen
        w_cov = g.inv_cov_dup
        w_out = g.inv_outdeg

        def matvecs(sv, rv):
            y_s_cov, y_r_full = cov_pair(
                _pad_cols(rv * w_len, cov_words * 8), sv * w_cov
            )
            y_ss, _ = ss_fwd(_pad_cols(sv * w_out, ss_words * 8))
            return y_s_cov + alpha * y_ss, y_r_full[:t_pad]

    elif kernel == "csr":
        # Scatter-free SpMV: gather -> cumsum -> difference at row
        # boundaries. XLA lowers TPU scatters to serialized updates (the
        # measured densify cost dwarfs the 25 matvecs), while cumsum is a
        # log-depth pass and gathers vectorize — so each SpMV touches the
        # entry list a constant number of times with no scatter anywhere.
        # Exactness: operand values are identical to the COO path (same
        # f32 vals, same products); only the summation tree differs
        # (prefix-sum differences vs segment scatter-adds), which is the
        # usual f32 reassociation tolerance the parity suite tests under.
        #
        # Sharded (psum_axis set): each device holds one CONTIGUOUS block
        # of the entry axis (shard_map block-splits the padded arrays) and
        # the indptrs are replicated, so a row's local sum is the prefix
        # difference over the row range CLAMPED to the local block; the
        # psum adds the per-shard partials. Rows crossing a shard boundary
        # are simply split across the adjacent shards.
        if g.inc_indptr_op.shape[-1] == 0:
            raise ValueError(
                "kernel='csr' needs the CSR views, but this window was "
                "built with aux='auto' inside the bitmap budget — build "
                "with aux='all' (or use kernel='packed')"
            )

        def csr_rowsum(prod, indptr):
            """LOCAL row sums (per-shard partial when sharded — the
            caller psums, combining vectors first to save collectives).

            The prefix sum is COMPENSATED (double-f32, ops.segment.
            compensated_cumsum): a plain f32 cumsum rounds each row's
            difference by its global prefix position, so two
            value-identical rows could emerge unequal and flip an exact
            score tie the per-row-summing kernels (coo/packed/dense)
            preserve — the root cause of the csr collapse-parity
            failure. The difference is taken per component and summed
            hi-first to keep the recovered row sum within ~1 ulp."""
            from ..ops.segment import compensated_cumsum

            hi, lo_c = compensated_cumsum(prod)
            z = jnp.zeros((1,), jnp.float32)
            hi = jnp.concatenate([z, hi])
            lo_c = jnp.concatenate([z, lo_c])
            n_local = prod.shape[0]
            base = (
                0
                if psum_axis is None
                else lax.axis_index(psum_axis) * n_local
            )
            a = jnp.clip(indptr[:-1], base, base + n_local) - base
            b = jnp.clip(indptr[1:], base, base + n_local) - base
            return (jnp.take(hi, b) - jnp.take(hi, a)) + (
                jnp.take(lo_c, b) - jnp.take(lo_c, a)
            )

        def matvecs(sv, rv):
            y_sr = csr_rowsum(
                g.sr_val_opmajor * jnp.take(rv, g.inc_trace_opmajor),
                g.inc_indptr_op,
            )
            y_ss = csr_rowsum(
                g.ss_val * jnp.take(sv, g.ss_parent), g.ss_indptr
            )
            y_rs = csr_rowsum(
                g.rs_val * jnp.take(sv, g.inc_op), g.inc_indptr_trace
            )
            # Two collectives per iteration (like the coo path), not three.
            return reduce_shards(y_sr + alpha * y_ss), reduce_shards(y_rs)

    elif kernel == "pcsr":
        # Partition-centric SpMV (Partition-Centric PageRank, arxiv
        # 1709.07122, adapted to the bipartite coverage SpMV pair; the
        # spectrum + tie-aware top-k epilogue stays fused in the same
        # program like every kernel here — the FUSED-PAGERANK shape,
        # arxiv 2203.09284). The csr kernel is gather/scatter-bound at
        # scale: each SpMV issues an E-entry random gather over the FULL
        # [T] trace vector (~0% HBM utilization measured — DESIGN.md),
        # and the coo path's scatter-add measures ~30x a vectorized pass
        # per entry on CPU. Here NEITHER appears:
        #
        #   * y_s (op axis): rv is reshaped into contiguous
        #     [P, PCSR_PART_TRACES] partition slices (the streaming
        #     load); the block tables gather only partition-LOCAL trace
        #     ids (a bounded small range), block row-sums reduce
        #     PCSR_BLOCK entries at a time, a compensated prefix over
        #     the per-partition BLOCK sums (ops.segment.
        #     compensated_cumsum — the same position-independent-
        #     rounding guarantee as the csr kernel's scan) is
        #     differenced at the dense per-partition offset table, and
        #     the [P, V] slab sums over partitions — a bounded dense
        #     accumulation into the output slab, no scatter;
        #   * y_r (trace axis): the output axis is DENSE, so the
        #     fixed-width ELL slab turns it into a gather from the
        #     small [V] vector plus a row sum — again no scatter.
        #
        # Sharded (psum_axis set): per-shard partition tables — the
        # PARTITION axis (and the ELL slab's trace axis) distribute;
        # each device produces dense [V]/[T] partials (its y_r rows at
        # their global trace offset, zeros elsewhere) and the same two
        # psums as the entry-sharded csr/coo path combine them.
        # stage_sharded re-pads the trace axis to S*shards so the slab
        # tiling is exact.
        if g.pc_trace.shape[-1] == 0:
            raise ValueError(
                "kernel='pcsr' needs the partition-centric views, but "
                "this window was built without them — build with "
                "aux='pcsr'/'all' (or let aux='auto' resolve past the "
                "bitmap budget)"
            )
        from ..graph.build import PCSR_BLOCK, PCSR_PART_TRACES
        from ..ops.segment import compensated_cumsum

        s_part = PCSR_PART_TRACES
        n_parts, e_blk = g.pc_trace.shape
        nb = e_blk // PCSR_BLOCK
        t_local = g.pc_ell_op.shape[0]
        if psum_axis is not None and (
            n_parts * s_part > t_pad or t_local > t_pad
        ):
            raise ValueError(
                "sharded pcsr needs the trace axis tiled exactly by the "
                f"partition tables (local {n_parts} partitions x "
                f"{s_part} traces, ell rows {t_local}, t_pad {t_pad}); "
                f"stack with trace_multiple={s_part} * shard count "
                "(parallel.stage_sharded does this)"
            )

        def matvecs(sv, rv):
            if psum_axis is None:
                rv2d = _pad_cols(rv, n_parts * s_part).reshape(
                    n_parts, s_part
                )
                t_base = 0
            else:
                t_base = lax.axis_index(psum_axis) * (n_parts * s_part)
                rv2d = lax.dynamic_slice(
                    rv, (t_base,), (n_parts * s_part,)
                ).reshape(n_parts, s_part)
            # Forward: contiguous slice load -> local small-range gather
            # -> block row-sums -> compensated prefix over block sums ->
            # offset-table difference -> bounded [P, V] slab.
            prod = g.pc_sr_val * jnp.take_along_axis(
                rv2d, g.pc_trace, axis=1
            )
            bs = prod.reshape(n_parts, nb, PCSR_BLOCK).sum(axis=-1)
            hi, lo = compensated_cumsum(bs, axis=-1)
            z = jnp.zeros((n_parts, 1), jnp.float32)
            hi = jnp.concatenate([z, hi], axis=1)
            lo = jnp.concatenate([z, lo], axis=1)
            a = g.pc_blk_indptr[:, :-1]
            b = g.pc_blk_indptr[:, 1:]
            y_parts = (
                jnp.take_along_axis(hi, b, axis=1)
                - jnp.take_along_axis(hi, a, axis=1)
            ) + (
                jnp.take_along_axis(lo, b, axis=1)
                - jnp.take_along_axis(lo, a, axis=1)
            )
            y_s = y_parts.sum(axis=0)
            # Backward: dense output axis — [T, W] slab gather from the
            # small sv vector + row sum.
            y_blk = (
                g.pc_ell_rs
                * jnp.take(sv, g.pc_ell_op, mode="clip")
            ).sum(axis=-1)
            if psum_axis is None:
                y_r = y_blk[:t_pad]
            else:
                # This shard's rows at their global trace offset; the
                # psum of the zero-elsewhere dense partials reassembles
                # the replicated [T] vector (same combine as csr/coo).
                y_r = lax.dynamic_update_slice(
                    jnp.zeros((t_pad,), jnp.float32), y_blk, (t_base,)
                )
            # Call edges stay a plain segment-sum: V is the small axis,
            # so both sides are already cache-range. Entry-sharded like
            # the coo path (per-shard partials, same psum).
            y_ss = coo_matvec(g.ss_child, g.ss_parent, g.ss_val, sv, v)
            return reduce_shards(y_s + alpha * y_ss), reduce_shards(y_r)

    elif kernel == "pallas":
        # One-hot MXU segment sums (ops/pallas_spmv.py): the scatter side
        # of each SpMV runs on the systolic array instead of serializing
        # on scatter-add. Interpret mode off-TPU keeps tests honest.
        from ..ops.pallas_spmv import coo_matvec_pallas

        # The axon TPU plugin reports backend "axon"; interpret only on CPU.
        interpret = jax.default_backend() == "cpu"

        def matvecs(sv, rv):
            return (
                reduce_shards(
                    coo_matvec_pallas(
                        g.inc_op, g.inc_trace, g.sr_val, rv, v, interpret
                    )
                    + alpha
                    * coo_matvec_pallas(
                        g.ss_child, g.ss_parent, g.ss_val, sv, v, interpret
                    )
                ),
                reduce_shards(
                    coo_matvec_pallas(
                        g.inc_trace, g.inc_op, g.rs_val, sv, t_pad, interpret
                    )
                ),
            )

    else:
        raise ValueError(f"unknown pagerank kernel {kernel!r}")

    return matvecs, pref, sv, rv, rv_axis


def _partition_step(
    matvecs, pref, sv, rv, cfg: PageRankConfig, rv_axis: str | None = None
):
    """One power-iteration step (pagerank.py:122-127):
    sv' = d*(p_sr @ rv + alpha * p_ss @ sv);
    rv' = d*(p_rs @ sv) + (1-d) * pref; both max-normalized.

    With ``rv_axis`` set (trace-sharded rv, packed sharded kernel) the
    rv normalization max is a pmax over the shards — a local max would
    normalize each block differently."""
    d = jnp.float32(cfg.damping)
    mv_s, mv_r = matvecs(sv, rv)
    sv_new = d * mv_s
    rv_new = d * mv_r + (1.0 - d) * pref
    if cfg.max_normalize_each_iter:
        sv_new = sv_new / jnp.max(sv_new)
        r_max = jnp.max(rv_new)
        if rv_axis is not None:
            r_max = lax.pmax(r_max, rv_axis)
        rv_new = rv_new / r_max
    return sv_new, rv_new


def _partition_finish(g: PartitionGraph, sv):
    """Final normalize + the reference's rescale (pagerank.py:93-112):
    returns (weight[V], score[V])."""
    score = sv / jnp.max(sv)
    total = jnp.where(g.op_present, score, 0.0).sum()
    weight = score * total / g.n_ops.astype(jnp.float32)
    return weight, score


def _iterate(step, carry, cfg: PageRankConfig, delta_axis: str | None = None):
    """Run ``step`` for cfg.iterations, or — when cfg.tol is set — until
    the L-inf change of every carried vector falls below tol (whichever
    comes first). The reference has no convergence check (its README flags
    that as a limitation for large systems); tol=None reproduces it.

    ``delta_axis``: mesh axis to pmax the convergence delta over when
    part of the carry is sharded (packed sharded kernel) — the
    while_loop predicate must be uniform across the shards."""
    if cfg.tol is None:
        return lax.fori_loop(0, cfg.iterations, lambda i, c: step(c), carry)
    tol = jnp.float32(cfg.tol)

    def cond(state):
        i, _, delta = state
        return (i < cfg.iterations) & (delta > tol)

    def body(state):
        i, c, _ = state
        new = step(c)
        delta = jax.tree.reduce(
            jnp.maximum,
            jax.tree.map(lambda a, b: jnp.max(jnp.abs(a - b)), new, c),
        )
        if delta_axis is not None:
            delta = lax.pmax(delta, delta_axis)
        return i + 1, new, delta

    # Initial delta: +inf carrying the SAME varying-axes (vma) type as
    # the body's delta — under shard_map the carry derives from sharded
    # inputs, and a plain scalar literal would mismatch the loop-carry
    # type. Deriving it from the carry (then overwriting the value)
    # reproduces the body's vma exactly.
    delta0 = jax.tree.reduce(
        jnp.maximum, jax.tree.map(lambda a: jnp.max(jnp.abs(a)), carry)
    )
    if delta_axis is not None:
        delta0 = lax.pmax(delta0, delta_axis)
    delta0 = delta0 * 0 + jnp.float32(jnp.inf)

    _, carry, _ = lax.while_loop(
        cond, body, (jnp.int32(0), carry, delta0)
    )
    return carry


def partition_pagerank(
    g: PartitionGraph,
    anomaly: bool,
    cfg: PageRankConfig,
    psum_axis: str | None = None,
    kernel: str = "coo",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Power-iterate one partition; returns (weight[V], score[V]).

    ``weight`` is the reference's rescaled output
    (score * sum(scores) / n_ops, pagerank.py:106-107); ``score`` the raw
    max-normalized PageRank vector. Ops absent from the partition have no
    incoming entries, stay at 0, and cannot perturb present ops — so
    running on the shared window vocab is exact.

    ``psum_axis``: when called under shard_map with the COO *entry* axes
    (inc_*/ss_*) sharded across that mesh axis, each device segment-sums
    its entry shard into full dense [V]/[T] partials and the psum combines
    them — the ranking vectors stay replicated (V and T vectors are small;
    the entries are the big axis). This is the whole multi-chip story for
    the SpMV (SURVEY.md C18/C19 plan).
    """
    matvecs, pref, sv, rv, rv_axis = _partition_setup(
        g, anomaly, cfg, psum_axis, kernel
    )
    sv, rv = _iterate(
        lambda c: _partition_step(matvecs, pref, *c, cfg, rv_axis),
        (sv, rv),
        cfg,
        delta_axis=rv_axis,
    )
    return _partition_finish(g, sv)


def spectrum_counters(
    a_weight,
    a_graph: PartitionGraph,
    n_weight,
    n_graph: PartitionGraph,
    cfg: SpectrumConfig,
):
    """The method-independent spectrum counters {ef, nf, ep, np} over the
    shared op vocab [V] (reference: online_rca.py:43-69, including the
    asymmetric only-in-normal branch at :65-66). Returns
    (ef, nf, ep, np_, valid)."""
    eps = jnp.float32(cfg.eps)
    a_present = a_graph.op_present
    n_present = n_graph.op_present
    a_cov = a_graph.cov_unique.astype(jnp.float32)
    n_cov = n_graph.cov_unique.astype(jnp.float32)
    a_len = a_graph.n_traces.astype(jnp.float32)
    n_len = n_graph.n_traces.astype(jnp.float32)

    ef = jnp.where(a_present, a_weight * a_cov, eps)
    nf = jnp.where(a_present, a_weight * (a_len - a_cov), eps)
    ep = jnp.where(
        a_present,
        jnp.where(n_present, n_weight * n_cov, eps),
        (1.0 + n_weight) * n_cov,
    )
    np_ = jnp.where(
        a_present,
        jnp.where(n_present, n_weight * (n_len - n_cov), eps),
        n_len - n_cov,
    )
    valid = a_present | n_present
    return ef, nf, ep, np_, valid


def window_spectrum(
    a_weight,
    a_graph: PartitionGraph,
    n_weight,
    n_graph: PartitionGraph,
    cfg: SpectrumConfig,
):
    """Spectrum counters + formula over the shared op vocab [V]
    (reference: online_rca.py:43-142). Returns (scores[V], valid[V])."""
    ef, nf, ep, np_, valid = spectrum_counters(
        a_weight, a_graph, n_weight, n_graph, cfg
    )
    scores = spectrum_scores(ef, nf, ep, np_, cfg.method)
    return jnp.where(valid, scores, -jnp.inf), valid


_tiebreak_warned = False


def validate_tiebreak(cfg: SpectrumConfig) -> None:
    """Device-path check of SpectrumConfig.tiebreak: unknown values raise;
    "insertion" (the oracle-only reference-compat order) warns once per
    process that the device program always uses the name/index tie key —
    lax.sort has no notion of dict insertion order to reproduce."""
    if cfg.tiebreak == "name":
        return
    if cfg.tiebreak == "insertion":
        global _tiebreak_warned
        if not _tiebreak_warned:
            _tiebreak_warned = True
            from ..utils.logging import get_logger

            get_logger("microrank_tpu.rank_backends").warning(
                "tiebreak='insertion' is oracle-only; the device ranking "
                "breaks exact score ties by ascending op name instead"
            )
        return
    raise ValueError(f"unknown tiebreak {cfg.tiebreak!r}")


def top_k_tiebroken(scores, k: int):
    """Top-k by score descending, op index ascending on EXACT score ties.

    The reference's tie order is dict insertion order under a stable sort
    (online_rca.py:144-152) — an accident of hash ordering. Here ties
    break by vocab index; the graph build interns the window vocab in
    name-sorted order, so that is ascending op name in every backend and
    kernel, and rankings stay reproducible even under tarantula-style
    score saturation (many ops at exactly 1.0). Implemented as one
    two-key ``lax.sort`` over [V] — the score vector is op-vocab-sized,
    so the full sort costs noise next to the power iteration.

    Returns (top_scores[k], top_idx[k]) like ``lax.top_k``.
    """
    # +0.0 canonicalizes -0.0 so the float total order XLA sorts by
    # cannot split scores Python compares equal.
    neg = -(scores + 0.0)
    idx = jnp.arange(scores.shape[0], dtype=jnp.int32)
    neg_sorted, idx_sorted = lax.sort((neg, idx), num_keys=2)
    return -neg_sorted[:k], idx_sorted[:k]


def _finish_topk(graph: WindowGraph, n_weight, a_weight, spectrum_cfg):
    """Spectrum + top-k tail shared by the plain and convergence-traced
    rankings: returns (top_idx int32[k], top_scores float32[k],
    n_valid int32)."""
    scores, valid = window_spectrum(
        a_weight, graph.abnormal, n_weight, graph.normal, spectrum_cfg
    )
    k = min(spectrum_cfg.n_rows, scores.shape[0])
    top_scores, top_idx = top_k_tiebroken(scores, k)
    n_valid = jnp.minimum(valid.sum(), k).astype(jnp.int32)
    return top_idx.astype(jnp.int32), top_scores, n_valid


@contract(
    graph="windowgraph",
    returns=("int32[K]", "float32[K]", "int32[]"),
)
def rank_window_core(
    graph: WindowGraph,
    pagerank_cfg: PageRankConfig,
    spectrum_cfg: SpectrumConfig,
    psum_axis: str | None = None,
    kernel: str = "coo",
    init=None,
):
    """The full single-window ranking: both partitions' power iterations,
    spectrum, top-k. Pure traced function — jit it (single device), vmap
    it (window batches), or call it under shard_map with the entry axes
    sharded and ``psum_axis`` set (multi-chip).

    ``init``: optional warm-start (sv_n, rv_n, sv_a, rv_a) vectors (the
    previous overlapping window's converged state mapped across the
    window delta — rank_backends.warm); None is the cold uniform start.

    Returns (top_idx int32[k], top_scores float32[k], n_valid int32):
    indices into the shared window op vocab, score-descending;
    entries beyond ``n_valid`` are padding (score -inf).
    """
    n_weight, a_weight = window_weights(
        graph, pagerank_cfg, psum_axis, kernel, init
    )
    return _finish_topk(graph, n_weight, a_weight, spectrum_cfg)


@contract(
    graph="windowgraph",
    returns=(
        "int32[K]", "float32[K]", "int32[]", "float32[2,I]", "int32[]"
    ),
)
def rank_window_traced_core(
    graph: WindowGraph,
    pagerank_cfg: PageRankConfig,
    spectrum_cfg: SpectrumConfig,
    psum_axis: str | None = None,
    kernel: str = "coo",
    init=None,
):
    """rank_window_core plus the device-side convergence trace
    (RuntimeConfig.convergence_trace — the pipelines' default program).

    Extra returns, carried in the SAME result blob so telemetry adds no
    host sync or extra fetch RPC:

    * ``residuals`` float32[2, iterations] — per-partition (normal,
      abnormal) L-inf change of the ranking vectors at each step, taken
      AFTER max-normalization; entries past ``n_iters`` are 0;
    * ``n_iters`` int32 — steps actually run (== ``cfg.iterations``
      unless a convergence tol stopped the while_loop early).

    Cost: one elementwise |new - old| + max reduce over the [V]/[T]
    vectors per step — O(V+T) next to the matvecs' O(V*T/8) streamed
    bytes; measured <1% on the 1M-span replay.
    """
    n_weight, a_weight, residuals, n_iters = window_weights_traced(
        graph, pagerank_cfg, psum_axis, kernel, init
    )
    top_idx, top_scores, n_valid = _finish_topk(
        graph, n_weight, a_weight, spectrum_cfg
    )
    return top_idx, top_scores, n_valid, residuals, n_iters


def _warm_override(graph: WindowGraph, cold, init, psum_axis):
    """Replace the cold-start iteration vectors with a warm-start init
    (the down payment on ROADMAP item 2): ``init`` is a
    (sv_n, rv_n, sv_a, rv_a) tuple of float32 vectors padded to the
    graph's axes — ``rank_backends.warm.map_warm_state`` builds it
    host-side across the window delta (op names for sv, the kind
    retention map's column identities for rv). Entries at padding
    positions are masked off, and a side whose init carries no mass (an
    all-miss mapping) falls back to its cold vector, so the program can
    never divide by a zero max on a bad map. Scale is irrelevant under
    max_normalize_each_iter; without it the first normalization inside
    _partition_finish still absorbs it.
    """
    if init is None:
        return cold
    if psum_axis is not None:
        raise ValueError(
            "warm-start init is single-device only (the trace-sharded "
            "kernels keep rv as local blocks); dispatch warm windows "
            "unsharded"
        )
    (sv_n_c, rv_n_c), (sv_a_c, rv_a_c) = cold
    sv_n_i, rv_n_i, sv_a_i, rv_a_i = (
        jnp.asarray(x, jnp.float32) for x in init
    )

    def pick(g, sv_c, rv_c, sv_i, rv_i):
        t_pad = g.kind.shape[0]
        n_live = jnp.where(g.n_cols < 0, g.n_traces, g.n_cols)
        sv_i = jnp.where(g.op_present, sv_i, 0.0)
        rv_i = jnp.where(jnp.arange(t_pad) < n_live, rv_i, 0.0)
        return (
            jnp.where(jnp.max(sv_i) > 0, sv_i, sv_c),
            jnp.where(jnp.max(rv_i) > 0, rv_i, rv_c),
        )

    return (
        pick(graph.normal, sv_n_c, rv_n_c, sv_n_i, rv_n_i),
        pick(graph.abnormal, sv_a_c, rv_a_c, sv_a_i, rv_a_i),
    )


def window_weights(
    graph: WindowGraph,
    pagerank_cfg: PageRankConfig,
    psum_axis: str | None = None,
    kernel: str = "coo",
    init=None,
):
    """Both partitions' PageRank weights, iterated together.

    Both partitions step inside ONE fori_loop (their iterations are
    independent; fusing halves the loop-body op count and lets XLA
    schedule the small partition's matvecs into the big one's gaps).
    Per-partition math is identical to partition_pagerank.
    ``init``: optional warm-start (sv_n, rv_n, sv_a, rv_a) override
    (_warm_override). Returns (n_weight[V], a_weight[V]).
    """
    mv_n, pref_n, sv_n, rv_n, ax_n = _partition_setup(
        graph.normal, False, pagerank_cfg, psum_axis, kernel
    )
    mv_a, pref_a, sv_a, rv_a, ax_a = _partition_setup(
        graph.abnormal, True, pagerank_cfg, psum_axis, kernel
    )
    (sv_n, rv_n), (sv_a, rv_a) = _warm_override(
        graph, ((sv_n, rv_n), (sv_a, rv_a)), init, psum_axis
    )

    def step(carry):
        (sv_n, rv_n), (sv_a, rv_a) = carry
        return (
            _partition_step(mv_n, pref_n, sv_n, rv_n, pagerank_cfg, ax_n),
            _partition_step(mv_a, pref_a, sv_a, rv_a, pagerank_cfg, ax_a),
        )

    (sv_n, rv_n), (sv_a, rv_a) = _iterate(
        step, ((sv_n, rv_n), (sv_a, rv_a)), pagerank_cfg, delta_axis=ax_n
    )
    n_weight, _ = _partition_finish(graph.normal, sv_n)
    a_weight, _ = _partition_finish(graph.abnormal, sv_a)
    return n_weight, a_weight


def window_weights_traced(
    graph: WindowGraph,
    pagerank_cfg: PageRankConfig,
    psum_axis: str | None = None,
    kernel: str = "coo",
    init=None,
):
    """window_weights plus the per-partition convergence trace.

    Same fused both-partitions loop; each step ALSO records the L-inf
    change of every carried vector, per partition, into a
    float32[2, iterations] buffer (row 0 normal, row 1 abnormal) that
    rides the program's outputs — no host sync anywhere (mrlint R1: the
    residuals stay device values until the caller's one batched fetch).
    When ``cfg.tol`` is set the while_loop stops early exactly like
    ``_iterate`` (joint predicate over both partitions) and the trace's
    tail past ``n_iters`` stays 0.

    Returns (n_weight[V], a_weight[V], residuals[2, I], n_iters int32).
    """
    n_weight, a_weight, _, _, residuals, n_iters, _, _ = (
        window_weights_full(graph, pagerank_cfg, psum_axis, kernel, init)
    )
    return n_weight, a_weight, residuals, n_iters


def window_weights_full(
    graph: WindowGraph,
    pagerank_cfg: PageRankConfig,
    psum_axis: str | None = None,
    kernel: str = "coo",
    init=None,
):
    """window_weights_traced plus the FINAL trace-partition vectors —
    the rank-provenance seam (explain/): the per-trace PPR mass ``rv``
    at convergence is what the coverage-column attribution decomposes
    (contribution of trace t to suspect v = p_sr[v, t] * rv[t]).

    Returns (n_weight[V], a_weight[V], rv_n[T_n], rv_a[T_a],
    residuals[2, I], n_iters int32, score_n[V], score_a[V]) — the score
    vectors are the final max-normalized sv per partition, which with
    the rv vectors form the warm-start state the next overlapping
    window can iterate from (``init``: the (sv_n, rv_n, sv_a, rv_a)
    override; see _warm_override). Under the trace-sharded packed/kind
    kernels the rv vectors stay LOCAL blocks (the explain epilogue
    all-gathers them where needed).
    """
    cfg = pagerank_cfg
    mv_n, pref_n, sv_n, rv_n, ax_n = _partition_setup(
        graph.normal, False, cfg, psum_axis, kernel
    )
    mv_a, pref_a, sv_a, rv_a, ax_a = _partition_setup(
        graph.abnormal, True, cfg, psum_axis, kernel
    )
    (sv_n, rv_n), (sv_a, rv_a) = _warm_override(
        graph, ((sv_n, rv_n), (sv_a, rv_a)), init, psum_axis
    )
    n_steps = int(cfg.iterations)

    def part_delta(new, old, axis):
        d = jnp.maximum(
            jnp.max(jnp.abs(new[0] - old[0])),
            jnp.max(jnp.abs(new[1] - old[1])),
        )
        if axis is not None:
            # Sharded rv (packed kernels): the local block max must
            # combine across shards or each device would record its own
            # residual and the tol predicate could diverge.
            d = lax.pmax(d, axis)
        return d

    def step(carry):
        old_n, old_a = carry
        new_n = _partition_step(mv_n, pref_n, *old_n, cfg, ax_n)
        new_a = _partition_step(mv_a, pref_a, *old_a, cfg, ax_a)
        deltas = jnp.stack(
            [part_delta(new_n, old_n, ax_n), part_delta(new_a, old_a, ax_a)]
        )
        return (new_n, new_a), deltas

    carry0 = ((sv_n, rv_n), (sv_a, rv_a))
    # Zero residual buffer carrying the carry-derived varying-axes type
    # (the same shard_map vma workaround as _iterate's delta0): a plain
    # zeros literal would mismatch the loop-carry type under shard_map.
    # Differencing carry0 against itself is an O(V+T) no-op, NOT a step
    # evaluation — it exists only to inherit the carry's vma.
    d0 = jnp.stack(
        [
            part_delta(carry0[0], carry0[0], ax_n),
            part_delta(carry0[1], carry0[1], ax_a),
        ]
    )
    res0 = jnp.zeros((2, n_steps), jnp.float32) + d0[:, None]

    if cfg.tol is None:

        def body(i, state):
            c, res = state
            new, deltas = step(c)
            return new, res.at[:, i].set(deltas)

        carry, residuals = lax.fori_loop(
            0, n_steps, body, (carry0, res0)
        )
        n_iters = jnp.int32(n_steps)
    else:
        tol = jnp.float32(cfg.tol)

        def cond(state):
            i, _, delta, _ = state
            return (i < n_steps) & (delta > tol)

        def body(state):
            i, c, _, res = state
            new, deltas = step(c)
            return (
                i + 1,
                new,
                jnp.max(deltas),
                res.at[:, i].set(deltas),
            )

        delta0 = jnp.max(d0) * 0 + jnp.float32(jnp.inf)
        n_iters, carry, _, residuals = lax.while_loop(
            cond, body, (jnp.int32(0), carry0, delta0, res0)
        )
    (sv_n, rv_n), (sv_a, rv_a) = carry
    n_weight, score_n = _partition_finish(graph.normal, sv_n)
    a_weight, score_a = _partition_finish(graph.abnormal, sv_a)
    return (
        n_weight, a_weight, rv_n, rv_a, residuals, jnp.int32(n_iters),
        score_n, score_a,
    )


@contract(
    graph="windowgraph",
    returns=("int32[M,K]", "float32[M,K]", "int32[]"),
)
def rank_window_all_methods_core(
    graph: WindowGraph,
    pagerank_cfg: PageRankConfig,
    spectrum_cfg: SpectrumConfig,
    psum_axis: str | None = None,
    kernel: str = "coo",
):
    """Rank one window under EVERY spectrum formula in one program.

    The power iterations and the spectrum counters are method-independent
    — only the final elementwise formula + top-k differ — so comparing all
    13 methods (the paper's Tables 4-6 axis) costs one dispatch instead of
    13. Returns (top_idx int32[M, k], top_scores float32[M, k],
    n_valid int32) with M = len(spectrum.formulas.METHODS), rows in
    METHODS order; ``spectrum_cfg.method`` is ignored.
    """
    from ..spectrum.formulas import METHODS

    n_weight, a_weight = window_weights(graph, pagerank_cfg, psum_axis, kernel)
    ef, nf, ep, np_, valid = spectrum_counters(
        a_weight, graph.abnormal, n_weight, graph.normal, spectrum_cfg
    )
    k = min(spectrum_cfg.n_rows, valid.shape[0])
    tops = []
    for method in METHODS:  # static unroll — method is a trace constant
        scores = jnp.where(
            valid, spectrum_scores(ef, nf, ep, np_, method), -jnp.inf
        )
        top_scores, top_idx = top_k_tiebroken(scores, k)
        tops.append((top_idx.astype(jnp.int32), top_scores))
    n_valid = jnp.minimum(valid.sum(), k).astype(jnp.int32)
    return (
        jnp.stack([t[0] for t in tops]),
        jnp.stack([t[1] for t in tops]),
        n_valid,
    )


@contract(
    graph="windowgraph",
    returns=("int32[K]", "float32[K]", "int32[]"),
)
def rank_window_checked_core(
    graph: WindowGraph,
    pagerank_cfg: PageRankConfig,
    spectrum_cfg: SpectrumConfig,
    kernel: str = "coo",
):
    """rank_window_core plus in-program checkify assertions (SURVEY.md
    §5 sanitizers row): the finite-score invariant is checked on the
    padded [k] outputs INSIDE the compiled program, before they ever
    reach the host — vs RuntimeConfig.validate_numerics, which only sees
    fetched host values."""
    from jax.experimental import checkify

    top_idx, top_scores, n_valid = rank_window_core(
        graph, pagerank_cfg, spectrum_cfg, None, kernel
    )
    live = jnp.arange(top_scores.shape[0]) < n_valid
    checkify.check(
        jnp.all(jnp.where(live, jnp.isfinite(top_scores), True)),
        "non-finite ranked score inside the device program "
        "(preference vector or spectrum formula produced NaN/inf)",
    )
    checkify.check(
        jnp.logical_and(n_valid >= 0, n_valid <= top_scores.shape[0]),
        "n_valid outside [0, k]",
    )
    return top_idx, top_scores, n_valid


@contract(
    graph="windowgraph",
    returns=(
        "int32[K]", "float32[K]", "int32[]", "float32[2,I]", "int32[]"
    ),
)
def rank_window_checked_traced_core(
    graph: WindowGraph,
    pagerank_cfg: PageRankConfig,
    spectrum_cfg: SpectrumConfig,
    kernel: str = "coo",
):
    """The residual-traced twin of rank_window_checked_core: the same
    in-program checkify assertions AND the convergence trace in one
    program, so ``device_checks`` no longer silently drops the
    per-window iteration/residual telemetry (the carried-over PR 2 gap).
    Extra finite-residual check: a NaN residual means the iteration
    itself diverged before the spectrum could mask it."""
    from jax.experimental import checkify

    top_idx, top_scores, n_valid, residuals, n_iters = (
        rank_window_traced_core(
            graph, pagerank_cfg, spectrum_cfg, None, kernel
        )
    )
    live = jnp.arange(top_scores.shape[0]) < n_valid
    checkify.check(
        jnp.all(jnp.where(live, jnp.isfinite(top_scores), True)),
        "non-finite ranked score inside the device program "
        "(preference vector or spectrum formula produced NaN/inf)",
    )
    checkify.check(
        jnp.logical_and(n_valid >= 0, n_valid <= top_scores.shape[0]),
        "n_valid outside [0, k]",
    )
    live_it = jnp.arange(residuals.shape[1]) < n_iters
    checkify.check(
        jnp.all(
            jnp.where(live_it[None, :], jnp.isfinite(residuals), True)
        ),
        "non-finite power-iteration residual inside the device program "
        "(the ranking vectors diverged)",
    )
    return top_idx, top_scores, n_valid, residuals, n_iters


def _checked_jit():
    # Module-level cached jit (built lazily once): a per-call
    # jax.jit(checkify.checkify(lambda ...)) would retrace and recompile
    # every invocation.
    global _CHECKED_JIT
    if _CHECKED_JIT is None:
        from jax.experimental import checkify

        _CHECKED_JIT = jax.jit(
            checkify.checkify(
                rank_window_checked_core, errors=checkify.user_checks
            ),
            static_argnums=(1, 2, 3),
        )
    return _CHECKED_JIT


def _checked_traced_jit():
    global _CHECKED_TRACED_JIT
    if _CHECKED_TRACED_JIT is None:
        from jax.experimental import checkify

        _CHECKED_TRACED_JIT = jax.jit(
            checkify.checkify(
                rank_window_checked_traced_core, errors=checkify.user_checks
            ),
            static_argnums=(1, 2, 3),
        )
    return _CHECKED_TRACED_JIT


_CHECKED_JIT = None
_CHECKED_TRACED_JIT = None


@contract(
    graph="windowgraph",
    returns=("int32[K]", "float32[K]", "int32[]"),
)
def rank_window_checked(
    graph: WindowGraph,
    pagerank_cfg: PageRankConfig,
    spectrum_cfg: SpectrumConfig,
    kernel: str = "coo",
):
    """checkify-instrumented window rank. Raises
    ``checkify.JaxRuntimeError`` naming the failed check. Opt-in via
    RuntimeConfig.device_checks (adds an error-state thread through the
    program); the default host-side validation stays on either way.
    Compilation is cached module-level, same as rank_window_device."""
    from jax.experimental import checkify

    err, out = _checked_jit()(graph, pagerank_cfg, spectrum_cfg, kernel)
    checkify.check_error(err)
    return out


@contract(
    graph="windowgraph",
    returns=(
        "int32[K]", "float32[K]", "int32[]", "float32[2,I]", "int32[]"
    ),
)
def rank_window_checked_traced(
    graph: WindowGraph,
    pagerank_cfg: PageRankConfig,
    spectrum_cfg: SpectrumConfig,
    kernel: str = "coo",
):
    """rank_window_checked plus the device convergence trace — the
    program ``device_checks`` + ``convergence_trace`` dispatches, so
    telemetry keeps flowing under checkify instrumentation."""
    from jax.experimental import checkify

    err, out = _checked_traced_jit()(
        graph, pagerank_cfg, spectrum_cfg, kernel
    )
    checkify.check_error(err)
    return out


@contract(
    graph="windowgraph",
    returns=(
        "int32[K]", "float32[K]", "int32[]", "float32[2,I]", "int32[]",
        "float32[V]", "float32[T]", "float32[V]", "float32[U]",
    ),
)
def rank_window_warm_core(
    graph: WindowGraph,
    init,
    pagerank_cfg: PageRankConfig,
    spectrum_cfg: SpectrumConfig,
    kernel: str = "coo",
):
    """The warm-start ranking program (the stream engine's open-incident
    dispatch): rank_window_traced_core's 5 outputs PLUS the converged
    per-partition state (score_n[V], rv_n[T_n], score_a[V], rv_a[T_a])
    riding the same fetch, so the NEXT overlapping window can start its
    iteration from this one's fixed point instead of the uniform vector.
    ``init`` is the mapped (sv_n, rv_n, sv_a, rv_a) tuple or None (a
    cold solve that still exports its state — the seam's first window).
    With a convergence tol configured the residual trace proves the
    iteration count drops; without one the cost is identical to the
    traced program.
    """
    n_weight, a_weight, rv_n, rv_a, residuals, n_iters, sc_n, sc_a = (
        window_weights_full(graph, pagerank_cfg, None, kernel, init)
    )
    top_idx, top_scores, n_valid = _finish_topk(
        graph, n_weight, a_weight, spectrum_cfg
    )
    return (
        top_idx, top_scores, n_valid, residuals, n_iters,
        sc_n, rv_n, sc_a, rv_a,
    )


rank_window_device = jax.jit(rank_window_core, static_argnums=(1, 2, 3, 4))
rank_window_traced_device = jax.jit(
    rank_window_traced_core, static_argnums=(1, 2, 3, 4)
)
rank_window_warm_device = jax.jit(
    rank_window_warm_core, static_argnums=(2, 3, 4)
)
rank_window_all_methods_device = jax.jit(
    rank_window_all_methods_core, static_argnums=(1, 2, 3, 4)
)


_PACKED_UNUSED = (
    # The packed kernel reads only the bitmaps/edge list, inverse vectors,
    # and the per-axis stats; the COO incidence arrays (the big ones —
    # ~19 of 28 MB at the 1M-span scale) never reach the traced branch.
    # Partition-centric tables (aux="all" builds) are pcsr-only.
    "inc_op", "inc_trace", "sr_val", "rs_val", "ss_val",
    "inc_trace_opmajor", "sr_val_opmajor", "cov_i8",
    "pc_trace", "pc_sr_val", "pc_blk_indptr", "pc_ell_op", "pc_ell_rs",
)
# The pcsr kernel reads the partition tables, the call-edge list and the
# per-axis stats; the flat incidence copies (values live in the binned
# tables), CSR views, bitmaps and inverse vectors never reach its traced
# branch — at the 10M-span scale the inverse trace vector alone is an
# [T] array worth stripping.
_PCSR_UNUSED = (
    "inc_op", "inc_trace", "sr_val", "rs_val",
    "inc_trace_opmajor", "sr_val_opmajor",
    "inc_indptr_op", "inc_indptr_trace", "ss_indptr",
    "cov_bits", "ss_bits", "inv_tracelen", "inv_cov_dup", "inv_outdeg",
    "cov_i8",
)
_PC_FIELDS = ("pc_trace", "pc_sr_val", "pc_blk_indptr", "pc_ell_op", "pc_ell_rs")
# The kind kernel reads cov_i8, the inverse vectors, the ss edge values
# + parents + row offsets, and the per-axis stats. Everything else —
# the COO incidence arrays, CSR op-major copies, BOTH bitmaps (the int8
# pattern replaces cov_bits on device; the ss term is a row-sum, never
# a bitmap matvec), ss_child (its information lives in ss_indptr) and
# the partition-centric tables — stays on the host.
_KIND_UNUSED = (
    "inc_op", "inc_trace", "sr_val", "rs_val",
    "inc_trace_opmajor", "sr_val_opmajor",
    "inc_indptr_op", "inc_indptr_trace",
    "cov_bits", "ss_bits", "ss_child",
) + _PC_FIELDS
_KERNEL_UNUSED_FIELDS = {
    # Default ss_stage="edges": the V*V/8-byte call-edge bitmap stays on
    # the host too — the kernel rebuilds it on device from the (much
    # smaller) ss edge list (pack_edge_bits). ~10x fewer staged bytes at
    # the 1M-span scale; ss_stage="bits" restores the host-packed profile.
    ("packed", "edges"): _PACKED_UNUSED + ("ss_bits",),
    ("packed_bf16", "edges"): _PACKED_UNUSED + ("ss_bits",),
    ("packed_blocked", "edges"): _PACKED_UNUSED + ("ss_bits",),
    ("packed", "bits"): _PACKED_UNUSED + ("ss_child", "ss_parent"),
    ("packed_bf16", "bits"): _PACKED_UNUSED + ("ss_child", "ss_parent"),
    ("packed_blocked", "bits"): _PACKED_UNUSED + ("ss_child", "ss_parent"),
    # The csr kernel reads rs_val+inc_op (trace-major), ss_val+ss_parent,
    # and the CSR views — not inc_trace/ss_child/sr_val (their information
    # lives in the indptrs and the op-major copies) or the bitmaps
    # (already empty under the aux policy).
    ("csr", "edges"): ("inc_trace", "ss_child", "sr_val", "cov_bits",
                       "ss_bits", "cov_i8") + _PC_FIELDS,
    ("csr", "bits"): ("inc_trace", "ss_child", "sr_val", "cov_bits",
                      "ss_bits", "cov_i8") + _PC_FIELDS,
    ("pcsr", "edges"): _PCSR_UNUSED,
    ("pcsr", "bits"): _PCSR_UNUSED,
    ("kind", "edges"): _KIND_UNUSED,
    ("kind", "bits"): _KIND_UNUSED,
}


def device_subset(
    graph: WindowGraph, kernel: str, ss_stage: str = "edges"
) -> WindowGraph:
    """Drop the fields ``kernel`` never reads (replaced by empty arrays)
    before staging the graph on device — ~10x fewer host->device bytes for
    the packed kernel. Safe under jit: the kernel string is static, so the
    dropped fields' branches are never traced.

    ``ss_stage`` (packed kernels): "edges" (default) keeps the call-edge
    list and drops the host-packed ss bitmap — the device program rebuilds
    it (pack_edge_bits) from ~50-100x fewer bytes; "bits" stages the
    host-packed bitmap and drops the edge list (no device scatter).
    """
    if ss_stage not in ("edges", "bits"):
        raise ValueError(f"unknown ss_stage {ss_stage!r}")
    fields = _KERNEL_UNUSED_FIELDS.get((kernel, ss_stage), ())
    if not fields:
        return graph

    def strip(p: PartitionGraph) -> PartitionGraph:
        repl = {}
        for f in fields:
            arr = getattr(p, f)  # shape/dtype only — no np.asarray, which
            # would round-trip device-resident arrays through the host
            # Zero only the LAST axis: leading batch/row dims survive so
            # vmap/stacked graphs keep consistent mapped-axis sizes.
            repl[f] = np.zeros(tuple(arr.shape[:-1]) + (0,), arr.dtype)
        return p._replace(**repl)

    return WindowGraph(
        normal=strip(graph.normal), abnormal=strip(graph.abnormal)
    )


def graph_device_bytes(graph: WindowGraph) -> int:
    """Host->device bytes this graph ships when staged as-is (sum of
    leaf nbytes — call AFTER device_subset so stripped fields count 0).
    The dispatch router's size signal: batches whose summed footprint
    crosses DispatchConfig.sharded_bytes_threshold route to the mesh.
    Shape/dtype arithmetic only — no np.asarray, which would round-trip
    device-resident arrays through the host."""
    total = 0
    for leaf in jax.tree.leaves(graph):
        n = 1
        for d in leaf.shape:
            n *= int(d)
        total += n * int(np.dtype(leaf.dtype).itemsize)
    return total


def choose_kernel(
    graph: WindowGraph,
    dense_budget_bytes: int | None = None,
    prefer_bf16: bool = False,
) -> str:
    """auto kernel policy, by PRESENCE of the auxiliary views the build
    constructed (graph.build.resolve_aux holds the actual budget policy, so
    build and kernel choice cannot disagree). Rationale, from measured v5e
    costs at the 1M-span scale (scatter ~75 ms each, 1M-entry gather ~8 ms
    *per iteration*, dense matvec sub-ms): "packed" bitmap-expanded MXU
    matvecs when the full unpacked f32 matrices fit ``dense_budget_bytes``,
    "packed_blocked" (column-blocked unpack, bounded intermediate) when
    only the bitmaps fit, "pcsr" partition-centric streaming SpMV
    (gather-free over the big trace axis, entry-linear memory) past
    both, "csr" when only the legacy CSR views were built, "coo" as the
    last resort (e.g. a stacked batch that mixed aux modes).

    ``prefer_bf16`` (RuntimeConfig.prefer_bf16 on the pipeline paths):
    resolve the in-budget bitmap path to "packed_bf16" — measured 1.55x
    faster per iteration (80.7 vs 124.7 us at the 1M-span shape,
    BENCH_r04) with rank parity tested; f32 "packed" remains the choice
    when bit-level score reproduction matters."""
    from ..graph.build import DEFAULT_DENSE_BUDGET_BYTES, packed_unpacked_bytes

    if dense_budget_bytes is None:
        dense_budget_bytes = DEFAULT_DENSE_BUDGET_BYTES
    parts = (graph.normal, graph.abnormal)
    # [-1] indexing so batched ([B, ...]-leading) graphs work too.
    # Kind-compressed views exist only when the build measured a dedup
    # factor past the threshold (graph.build.resolve_aux) — presence IS
    # the auto-select decision, same rule as every other view family.
    if all(int(g.cov_i8.shape[-1]) > 0 for g in parts):
        return "kind"
    if all(int(g.cov_bits.shape[-1]) > 0 for g in parts):
        unpacked = packed_unpacked_bytes(
            int(parts[0].cov_unique.shape[-1]),
            tuple(int(g.kind.shape[-1]) for g in parts),
        )
        if unpacked <= dense_budget_bytes:
            return "packed_bf16" if prefer_bf16 else "packed"
        return "packed_blocked"
    if all(int(g.pc_trace.shape[-1]) > 0 for g in parts):
        return "pcsr"
    if all(int(g.inc_indptr_op.shape[-1]) > 0 for g in parts):
        return "csr"
    return "coo"


def prepare_window_graph(span_df, normal_ids, abnormal_ids, config):
    """Host half of a device rank, shared by JaxBackend and the serve
    batcher: build the padded window graph under the config's pad
    policy, resolve kernel="auto", and strip the fields the kernel
    never reads. Returns ``(graph, op_names, kernel)`` with the graph
    already ``device_subset``-stripped for ``kernel``.

    Self-tracing: the whole host build is one ``build`` span under the
    caller's ambient trace context — on the serve/stream paths that
    context was attached by the build worker pool, so the span records
    the build's true thread and its causal parent (the window/request
    root).
    """
    graph, op_names, kernel, _ = _prepare_window_graph(
        span_df, normal_ids, abnormal_ids, config, retain_columns=False
    )
    return graph, op_names, kernel


def prepare_window_graph_explained(span_df, normal_ids, abnormal_ids, config):
    """prepare_window_graph plus the coverage-column retention context
    the explain subsystem needs to name traces behind device-side
    column attributions: returns ``(graph, op_names, kernel, ectx)``
    where ``ectx`` is an ``explain.bundle.ExplainContext`` (per
    partition: column -> trace id of the kind representative, and the
    column multiplicities)."""
    from ..explain.bundle import ExplainContext

    graph, op_names, kernel, retained = _prepare_window_graph(
        span_df, normal_ids, abnormal_ids, config, retain_columns=True
    )
    ids_n, ids_a, (map_n, map_a) = retained
    ectx = ExplainContext.from_build(graph, ids_n, ids_a, map_n, map_a)
    return graph, op_names, kernel, ectx


def prepare_window_graph_delta(
    span_df,
    normal_ids,
    abnormal_ids,
    config,
    state=None,
    start_us=None,
    end_us=None,
):
    """prepare_window_graph_explained's incremental sibling
    (RuntimeConfig.delta_build): build through
    graph.build_window_graph_delta, threading the previous window's
    ``DeltaBuildState``. Returns ``(graph, op_names, kernel, ectx,
    state, route, reason)`` — the leading 4 match the explained
    prepare's contract (the stream engine's rank path never branches),
    ``state`` is what the NEXT window passes back in, and
    ``route``/``reason`` are the build-route telemetry ("delta" or
    "cold" + why), also recorded into microrank_build_route_total and
    the run journal here so every caller pays the same observability.
    """
    from ..explain.bundle import ExplainContext
    from ..graph.build import (
        aux_for_kernel,
        build_window_graph_delta,
        kind_dedup_ratio,
    )
    from ..obs.journal import emit_current
    from ..obs.metrics import record_build_route, record_kind_dedup
    from ..obs.spans import get_tracer
    from .base import validate_partitions

    import time as _time

    normal_ids = list(normal_ids)
    abnormal_ids = list(abnormal_ids)
    validate_partitions(normal_ids, abnormal_ids)
    validate_tiebreak(config.spectrum)
    rt = config.runtime
    t0 = _time.perf_counter()
    with get_tracer().span("build", service="pipeline"):
        res = build_window_graph_delta(
            span_df,
            normal_ids,
            abnormal_ids,
            state=state,
            start_us=start_us,
            end_us=end_us,
            pad_policy=rt.pad_policy,
            min_pad=rt.min_pad,
            aux=aux_for_kernel(rt.kernel),
            dense_budget_bytes=rt.dense_budget_bytes,
            collapse=rt.collapse_kinds,
            kind_dedup_threshold=rt.kind_dedup_threshold,
            max_changed_fraction=rt.delta_max_changed,
        )
        kernel = rt.kernel
        if kernel == "auto":
            kernel = choose_kernel(
                res.graph, rt.dense_budget_bytes, rt.prefer_bf16
            )
        record_kind_dedup(kind_dedup_ratio(res.graph))
        record_build_route(res.route)
        emit_current(
            "build_route",
            route=res.route,
            reason=res.reason,
            build_ms=round((_time.perf_counter() - t0) * 1e3, 3),
        )
    ectx = ExplainContext.from_build(
        res.graph, res.normal_trace_ids, res.abnormal_trace_ids,
        res.column_map[0], res.column_map[1],
    )
    return (
        device_subset(res.graph, kernel), res.op_names, kernel, ectx,
        res.state, res.route, res.reason,
    )


def _prepare_window_graph(
    span_df, normal_ids, abnormal_ids, config, retain_columns: bool
):
    from ..graph.build import aux_for_kernel, build_window_graph
    from ..obs.spans import get_tracer
    from .base import validate_partitions

    normal_ids = list(normal_ids)
    abnormal_ids = list(abnormal_ids)
    validate_partitions(normal_ids, abnormal_ids)
    validate_tiebreak(config.spectrum)
    rt = config.runtime
    with get_tracer().span("build", service="pipeline"):
        out = build_window_graph(
            span_df,
            normal_ids,
            abnormal_ids,
            pad_policy=rt.pad_policy,
            min_pad=rt.min_pad,
            aux=aux_for_kernel(rt.kernel),
            dense_budget_bytes=rt.dense_budget_bytes,
            collapse=rt.collapse_kinds,
            retain_columns=retain_columns,
            kind_dedup_threshold=rt.kind_dedup_threshold,
        )
        graph, op_names = out[0], out[1]
        retained = (
            (out[2], out[3], out[4]) if retain_columns else None
        )
        kernel = rt.kernel
        if kernel == "auto":
            kernel = choose_kernel(
                graph, rt.dense_budget_bytes, rt.prefer_bf16
            )
        from ..graph.build import kind_dedup_ratio
        from ..obs.metrics import record_kind_dedup

        record_kind_dedup(kind_dedup_ratio(graph))
    return device_subset(graph, kernel), op_names, kernel, retained


class JaxBackend:
    """The ``rank_backends`` seam's device implementation.

    Host side builds the padded COO window graph; everything after that is
    the jitted device program above. See NumpyRefBackend for the oracle
    twin behind the same interface.
    """

    name = "jax"

    def __init__(self, config: MicroRankConfig = MicroRankConfig()):
        self.config = config
        # Device convergence telemetry of the most recent rank_window
        # call ({"iterations", "final_residual", "residuals"}), or None
        # when convergence_trace is off — the pandas pipeline journals it.
        self.last_convergence = None

    def rank_window(
        self, span_df, normal_ids, abnormal_ids
    ) -> Tuple[List[str], List[float]]:
        rt = self.config.runtime
        graph, op_names, kernel = prepare_window_graph(
            span_df, normal_ids, abnormal_ids, self.config
        )
        from ..utils.guards import contract_checks
        from .blob import stage_rank_window

        # The checkify program has a residual-traced twin
        # (rank_window_checked_traced), so device_checks no longer
        # disables the convergence trace.
        conv = bool(rt.convergence_trace)
        # validate_numerics also arms the trace-time @contract checks on
        # the rank entry points (analysis.contracts) — one knob, both
        # the host-side score validation and the signature contracts.
        with contract_checks(rt.validate_numerics):
            out = stage_rank_window(
                graph,
                self.config.pagerank,
                self.config.spectrum,
                kernel,
                rt.blob_staging,
                checked=rt.device_checks,
                conv_trace=conv,
            )
        # One batched fetch — piecemeal int()/float() conversions on device
        # arrays each pay a full RPC round trip on tunneled-TPU runtimes;
        # the convergence trace rides the same fetch.
        out = jax.device_get(out)
        top_idx, top_scores, n_valid = out[:3]
        self.last_convergence = None
        if conv:
            from ..obs.metrics import record_convergence

            residuals, n_iters = out[3], out[4]
            res = np.asarray(
                residuals,
                np.float64,  # mrlint: disable=R2(host-side summary of an already-fetched trace; never re-enters a jnp expression)
            )
            n_it = int(n_iters)
            final = (
                float(res[:, n_it - 1].max()) if n_it else float("nan")
            )
            record_convergence(kernel, n_it, final)
            self.last_convergence = {
                "iterations": n_it,
                "final_residual": final,
                "residuals": {
                    "normal": [float(x) for x in res[0, :n_it]],
                    "abnormal": [float(x) for x in res[1, :n_it]],
                },
            }
        n = int(n_valid)
        idx = [int(i) for i in top_idx[:n]]
        scores = [float(s) for s in top_scores[:n]]
        if rt.validate_numerics:
            from ..utils.guards import assert_finite_scores

            assert_finite_scores(scores, "JaxBackend.rank_window")
        return [op_names[i] for i in idx], scores

    def rank_window_all_methods(self, span_df, normal_ids, abnormal_ids):
        """Rank under every spectrum formula in one device dispatch.

        Returns {method: ([op names], [scores])} in METHODS order — the
        cheap way to produce a paper-style per-formula comparison.
        """
        from ..spectrum.formulas import METHODS

        rt = self.config.runtime
        graph, op_names, kernel = prepare_window_graph(
            span_df, normal_ids, abnormal_ids, self.config
        )
        from ..utils.guards import contract_checks

        with contract_checks(rt.validate_numerics):
            top_idx, top_scores, n_valid = jax.device_get(
                rank_window_all_methods_device(
                    jax.device_put(graph),
                    self.config.pagerank,
                    self.config.spectrum,
                    None,
                    kernel,
                )
            )
        n = int(n_valid)
        return {
            m: (
                [op_names[int(i)] for i in top_idx[mi, :n]],
                [float(s) for s in top_scores[mi, :n]],
            )
            for mi, m in enumerate(METHODS)
        }
