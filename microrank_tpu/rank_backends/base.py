"""The backend seam (BASELINE.json north star: ``rank_backends/`` with
numpy-reference and jax-tpu implementations selected at the orchestrator
entry). A backend ranks one detection window given the span DataFrame and
the two trace partitions."""

from __future__ import annotations

from typing import List, Protocol, Tuple, runtime_checkable


def validate_partitions(normal_ids, abnormal_ids) -> None:
    """Both partitions must be non-empty to rank a window.

    The reference guards this at the orchestrator (online_rca.py:176-178)
    and crashes deep inside numpy if bypassed; backends here fail fast and
    identically instead.
    """
    if not normal_ids or not abnormal_ids:
        raise ValueError(
            "rank_window requires non-empty normal AND abnormal trace "
            f"partitions (got {len(list(normal_ids))} normal / "
            f"{len(list(abnormal_ids))} abnormal); windows that fail to "
            "partition should be skipped, as the reference does at "
            "online_rca.py:176-178"
        )


@runtime_checkable
class RankBackend(Protocol):
    name: str

    def rank_window(
        self, span_df, normal_ids, abnormal_ids
    ) -> Tuple[List[str], List[float]]:
        """Rank one window's suspect operations.

        Returns (op_names, scores), score-descending, at most
        ``top_max + extra_rows`` entries (reference: online_rca.py:144-152).
        """
        ...
