"""Warm-start state capture + mapping across the sliding-window delta.

Sliding windows overlap by construction, and an open incident re-ranks
near-identical graphs every window — yet the cold program runs every
power iteration from the uniform 1/(O+T) vector. This module is the
HOST half of the warm-start seam (the down payment on ROADMAP item 2):
it captures the converged per-partition state a warm rank program
exports (``rank_window_warm_core``: max-normalized score[V] + trace
mass rv[T] per partition) and maps it onto the NEXT window's axes:

* the op axis maps by NAME — both windows intern their vocab in sorted
  name order, but membership shifts with the delta, so the join is the
  name itself;
* the trace/kind column axis maps by the kind retention map's column
  identity — each collapsed column's REPRESENTATIVE trace id
  (explain.bundle.ExplainContext, identity mapping uncollapsed).
  Overlapping windows share trace ids, so a surviving kind's mass
  carries over; a regrouped or departed kind simply misses.

Misses map to 0 and are refilled by the iteration in one step (the
matvec + preference term); a fully-missed side falls back to the cold
vector inside the program (jax_tpu._warm_override), so a bad map can
degrade warm-start back to cold but never corrupt a ranking. With a
convergence tol configured the payoff is measurable: iteration counts
drop window over window (the residual-traced outputs prove it — see
tests/test_kind_kernel.py's sliding replay).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np


@dataclass
class WarmState:
    """One ranked window's converged iteration state + the axis labels
    needed to re-map it onto a later window."""

    op_names: List[str]
    col_ids_n: List           # normal partition: per-column trace id
    col_ids_a: List           # abnormal partition: per-column trace id
    score_n: np.ndarray       # float32[V_prev] final normalized sv
    rv_n: np.ndarray          # float32[T_prev] final trace/kind mass
    score_a: np.ndarray
    rv_a: np.ndarray


def capture_warm_state(op_names, ectx, fetched) -> WarmState:
    """Fold a warm program's fetched state tail (score_n, rv_n,
    score_a, rv_a — host arrays) into a WarmState keyed by this
    window's op names and the retention context's per-column trace
    ids."""
    sc_n, rv_n, sc_a, rv_a = (np.asarray(x, np.float32) for x in fetched)
    return WarmState(
        op_names=list(op_names),
        col_ids_n=list(ectx.normal_trace_ids),
        col_ids_a=list(ectx.abnormal_trace_ids),
        score_n=sc_n,
        rv_n=rv_n,
        score_a=sc_a,
        rv_a=rv_a,
    )


def _map_axis(
    prev_vals: np.ndarray, prev_keys, new_keys, pad: int
) -> np.ndarray:
    """Value-carrying join: out[i] = prev_vals[prev_index[new_keys[i]]]
    (0 on a miss), zero-padded to ``pad``."""
    index = {k: i for i, k in enumerate(prev_keys)}
    out = np.zeros(pad, np.float32)
    for i, k in enumerate(new_keys):
        j = index.get(k)
        if j is not None and j < len(prev_vals):
            out[i] = prev_vals[j]
    return out


def map_warm_state(
    prev: Optional[WarmState], op_names, ectx, graph
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]]:
    """The (sv_n, rv_n, sv_a, rv_a) init tuple for a NEW window's warm
    rank, mapped from ``prev`` across the window delta — or None when
    there is nothing to map (a cold first window)."""
    if prev is None:
        return None
    v_pad = int(graph.normal.cov_unique.shape[-1])
    t_pad_n = int(graph.normal.kind.shape[-1])
    t_pad_a = int(graph.abnormal.kind.shape[-1])
    sv_n = _map_axis(prev.score_n, prev.op_names, op_names, v_pad)
    sv_a = _map_axis(prev.score_a, prev.op_names, op_names, v_pad)
    rv_n = _map_axis(
        prev.rv_n, prev.col_ids_n, ectx.normal_trace_ids, t_pad_n
    )
    rv_a = _map_axis(
        prev.rv_a, prev.col_ids_a, ectx.abnormal_trace_ids, t_pad_a
    )
    return sv_n, rv_n, sv_a, rv_a
