"""Segment-reduction primitives — the workhorse ops of the device pipeline.

Everything the reference does with dense matvecs and Python loops reduces
to gathers + segment sums over padded COO arrays: XLA lowers these to
efficient scatter-adds on TPU, they are trivially vmap-able over window
batches, and sharding the *entry* axis (with a psum of the dense partials)
is the whole distribution story (SURVEY.md C18/C19 plan).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def coo_matvec(rows, cols, vals, x, n_rows: int):
    """y = A @ x for COO entries A[rows[i], cols[i]] = vals[i].

    Padding entries must carry ``vals == 0`` (rows/cols may be any valid
    index); they then contribute nothing. ``n_rows`` is static.
    """
    return jax.ops.segment_sum(
        vals * jnp.take(x, cols, mode="clip"), rows, num_segments=n_rows
    )


def segment_count(ids, n_segments: int, live=None):
    ones = jnp.ones(ids.shape, dtype=jnp.int32)
    if live is not None:
        ones = jnp.where(live, ones, 0)
    return jax.ops.segment_sum(ones, ids, num_segments=n_segments)


def masked_max(x, mask, fill=-jnp.inf):
    return jnp.max(jnp.where(mask, x, fill))


def masked_sum(x, mask):
    return jnp.sum(jnp.where(mask, x, 0))
