"""Segment-reduction primitives — the workhorse ops of the device pipeline.

Everything the reference does with dense matvecs and Python loops reduces
to gathers + segment sums over padded COO arrays: XLA lowers these to
efficient scatter-adds on TPU, they are trivially vmap-able over window
batches, and sharding the *entry* axis (with a psum of the dense partials)
is the whole distribution story (SURVEY.md C18/C19 plan).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def coo_matvec(rows, cols, vals, x, n_rows: int):
    """y = A @ x for COO entries A[rows[i], cols[i]] = vals[i].

    Padding entries must carry ``vals == 0`` (rows/cols may be any valid
    index); they then contribute nothing. ``n_rows`` is static.
    """
    return jax.ops.segment_sum(
        vals * jnp.take(x, cols, mode="clip"), rows, num_segments=n_rows
    )


def _two_sum(a, b):
    """Knuth TwoSum: s + e == a + b exactly (s = fl(a+b), e the rounding
    error). Pure adds/subtracts — no FMA contraction can break it, and
    XLA does not reassociate float adds."""
    s = a + b
    t = s - a
    e = (a - (s - t)) + (b - t)
    return s, e


def compensated_cumsum(x, axis: int = 0):
    """Double-f32 inclusive prefix sum along ``axis``: returns (hi, lo)
    with ``hi[i] + lo[i]`` carrying the prefix sum to ~2x f32 precision.

    A plain f32 ``jnp.cumsum`` makes each element's rounding depend on
    its global prefix position — two value-identical rows of a CSR
    matrix land on different prefixes and round differently, which is
    exactly how the csr kernel once broke exact score ties the other
    kernels (per-row summation trees) preserved
    (tests/test_collapse.py::test_collapse_rank_parity_per_kernel[csr]).
    Compensating the scan keeps the error per prefix at ~1 ulp
    regardless of position. Cost: 7 adds per combine instead of 1, on a
    [E] vector — noise next to the gathers around it.
    """
    zeros = jnp.zeros_like(x)

    def comb(a, b):
        hi, e = _two_sum(a[0], b[0])
        return hi, e + a[1] + b[1]

    hi, lo = lax.associative_scan(comb, (x, zeros), axis=axis)
    return hi, lo


def compensated_psum(x, axis_name: str):
    """Cross-shard sum with a compensated, position-independent combine:
    ``all_gather`` the per-shard partials and fold them with TwoSum in
    shard order, so every element's cross-shard tree is identical and
    the recovered sum stays within ~1 ulp of the exact value.

    Why: entry-axis sharding (coo/csr) splits each row's entry list at
    fixed block boundaries, so a row straddling a shard boundary gets a
    DIFFERENT summation tree than a value-identical row that landed
    inside one shard — the same position-dependent rounding shape as
    the plain-cumsum csr bug ``compensated_cumsum`` fixed, now across
    shards instead of along the prefix. A plain ``psum`` bakes that
    reassociation in; compensating the fold bounds it below tie-flip
    scale. Cost: S× the collective bytes of a psum (S = shard count, a
    [S, V]/[S, T] gather of vectors that are small by design) plus 7
    adds per element per shard — noise next to the SpMV gathers.
    """
    parts = lax.all_gather(x, axis_name)  # [S, ...]; S static at trace
    hi = parts[0]
    lo = jnp.zeros_like(hi)
    for i in range(1, parts.shape[0]):
        hi, e = _two_sum(hi, parts[i])
        lo = lo + e
    return hi + lo


def sparse_psum(x, axis_name: str, cap: int = 0):
    """Cross-shard sum of a dense vector with sparse support, exchanged
    as (index, value) pairs instead of the dense vector (the sparse
    allreduce of arxiv 1312.3020, prototyped for the per-iteration
    [V]/[T] partial combine on the fleet's DCN hop).

    Each shard selects its ``cap`` largest-|value| entries (``cap`` 0 or
    >= n keeps the whole axis), one ``all_gather`` moves the [S, cap]
    index and value planes, and a local scatter-add rebuilds the dense
    result — EXACT whenever every shard's partial really has at most
    ``cap`` nonzeros, because dropped entries are then exact zeros and
    the scatter-add reassociation is the only divergence from ``psum``
    (same class as the psum's own combine order). Wire bytes:
    ``S*cap*8`` vs the dense ring's ``~2*n*4`` per shard — a win only
    when the per-shard support is genuinely sparse (``cap << n/S``…),
    which is the power-law-graph hypothesis this prototype measures.
    Non-1D inputs fall back to a plain ``psum`` (the dense [V]/[T]
    partials this targets are 1-D inside the per-window kernel).
    """
    if x.ndim != 1:
        return lax.psum(x, axis_name)
    n = int(x.shape[0])
    k = n if cap <= 0 else min(int(cap), n)
    _, idx = lax.top_k(jnp.abs(x), k)
    vals = jnp.take(x, idx)
    idx_all = lax.all_gather(idx, axis_name)    # [S, k]
    val_all = lax.all_gather(vals, axis_name)
    return (
        jnp.zeros_like(x)
        .at[idx_all.reshape(-1)]
        .add(val_all.reshape(-1))
    )


def segment_count(ids, n_segments: int, live=None):
    ones = jnp.ones(ids.shape, dtype=jnp.int32)
    if live is not None:
        ones = jnp.where(live, ones, 0)
    return jax.ops.segment_sum(ones, ids, num_segments=n_segments)


def masked_max(x, mask, fill=-jnp.inf):
    return jnp.max(jnp.where(mask, x, fill))


def masked_sum(x, mask):
    return jnp.sum(jnp.where(mask, x, 0))
