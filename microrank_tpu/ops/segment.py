"""Segment-reduction primitives — the workhorse ops of the device pipeline.

Everything the reference does with dense matvecs and Python loops reduces
to gathers + segment sums over padded COO arrays: XLA lowers these to
efficient scatter-adds on TPU, they are trivially vmap-able over window
batches, and sharding the *entry* axis (with a psum of the dense partials)
is the whole distribution story (SURVEY.md C18/C19 plan).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def coo_matvec(rows, cols, vals, x, n_rows: int):
    """y = A @ x for COO entries A[rows[i], cols[i]] = vals[i].

    Padding entries must carry ``vals == 0`` (rows/cols may be any valid
    index); they then contribute nothing. ``n_rows`` is static.
    """
    return jax.ops.segment_sum(
        vals * jnp.take(x, cols, mode="clip"), rows, num_segments=n_rows
    )


def _two_sum(a, b):
    """Knuth TwoSum: s + e == a + b exactly (s = fl(a+b), e the rounding
    error). Pure adds/subtracts — no FMA contraction can break it, and
    XLA does not reassociate float adds."""
    s = a + b
    t = s - a
    e = (a - (s - t)) + (b - t)
    return s, e


def compensated_cumsum(x):
    """Double-f32 inclusive prefix sum: returns (hi, lo) with
    ``hi[i] + lo[i]`` carrying the prefix sum to ~2x f32 precision.

    A plain f32 ``jnp.cumsum`` makes each element's rounding depend on
    its global prefix position — two value-identical rows of a CSR
    matrix land on different prefixes and round differently, which is
    exactly how the csr kernel once broke exact score ties the other
    kernels (per-row summation trees) preserved
    (tests/test_collapse.py::test_collapse_rank_parity_per_kernel[csr]).
    Compensating the scan keeps the error per prefix at ~1 ulp
    regardless of position. Cost: 7 adds per combine instead of 1, on a
    [E] vector — noise next to the gathers around it.
    """
    zeros = jnp.zeros_like(x)

    def comb(a, b):
        hi, e = _two_sum(a[0], b[0])
        return hi, e + a[1] + b[1]

    hi, lo = lax.associative_scan(comb, (x, zeros))
    return hi, lo


def segment_count(ids, n_segments: int, live=None):
    ones = jnp.ones(ids.shape, dtype=jnp.int32)
    if live is not None:
        ones = jnp.where(live, ones, 0)
    return jax.ops.segment_sum(ones, ids, num_segments=n_segments)


def masked_max(x, mask, fill=-jnp.inf):
    return jnp.max(jnp.where(mask, x, fill))


def masked_sum(x, mask):
    return jnp.sum(jnp.where(mask, x, 0))
