from .segment import coo_matvec, masked_max, masked_sum, segment_count

__all__ = ["coo_matvec", "masked_max", "masked_sum", "segment_count"]
