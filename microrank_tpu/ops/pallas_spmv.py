"""Pallas TPU kernel: COO segment-sum as one-hot MXU matmuls.

XLA lowers ``jax.ops.segment_sum`` to scatter-add, which serializes on the
TPU's vector unit. The TPU-native alternative implemented here keeps the
systolic array busy instead: for each block of COO entries, build a
one-hot matrix ``[BLOCK, V_TILE]`` in VMEM (an iota comparison — pure VPU)
and accumulate ``prod[None, :] @ onehot`` into a VMEM accumulator with the
MXU. The grid is (row-tiles, entry-blocks); TPU grids execute sequentially
over the last dimension, so the accumulator scratch carries across entry
blocks and each row-tile writes once at the end.

Cost: O(E * V) MACs instead of O(E) scatters — a good trade on TPU
whenever the scatter would serialize (and exact: one-hot entries are 0/1,
accumulation is f32).

Usage: ``coo_matvec_pallas(rows, cols, vals, x, n_rows)`` — same contract
as ops.segment.coo_matvec (padding entries must carry vals == 0).
Requires n_rows to be a multiple of 128 (the caller pads; structures
pad_to guarantees pow2 >= 128 for real workloads) and entries to be a
multiple of the block size.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from jax.experimental.pallas import tpu as pltpu

# Block shapes must align to the XLA 1-D layout tile (1024 elements for
# s32/f32 on v5e) once the padded array exceeds one tile — Mosaic rejects
# a 512 block on an 8192-element operand with "XLA layout {0:T(1024)}
# does not match Mosaic layout {0:T(512)}". A block that covers the WHOLE
# (sub-1024) array is fine, which is why the row_tile clamp below may
# yield 512 for a 512-row output and still compile.
ENTRY_BLOCK = 1024
ROW_TILE = 2048


def _spmv_kernel(rows_ref, prod_ref, y_ref, acc_ref, *, row_tile: int):
    j = pl.program_id(1)
    n_j = pl.num_programs(1)
    i = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    rows = rows_ref[:]          # [BLOCK] int32 (global row ids)
    prod = prod_ref[:]          # [BLOCK] f32
    base = i * row_tile
    local = rows - base
    onehot = (
        local[:, None]
        == jax.lax.broadcasted_iota(jnp.int32, (rows.shape[0], row_tile), 1)
    ).astype(jnp.float32)
    acc_ref[:] += jnp.dot(
        prod[None, :], onehot, preferred_element_type=jnp.float32
    )[0]

    @pl.when(j == n_j - 1)
    def _emit():
        y_ref[:] = acc_ref[:]


@functools.partial(
    jax.jit, static_argnames=("n_rows", "interpret", "entry_block", "row_tile")
)
def coo_segment_sum_pallas(
    rows,
    prod,
    n_rows: int,
    interpret: bool = False,
    entry_block: int = ENTRY_BLOCK,
    row_tile: int = ROW_TILE,
):
    """y[r] = sum of prod[e] where rows[e] == r, via one-hot MXU matmuls.

    ``rows`` int32[E], ``prod`` float32[E]; E padded to entry_block and
    n_rows padded to row_tile multiples by this wrapper.
    """
    e = rows.shape[0]
    e_pad = ((e + entry_block - 1) // entry_block) * entry_block
    if e_pad != e:
        rows = jnp.pad(rows, (0, e_pad - e))
        prod = jnp.pad(prod, (0, e_pad - e))
    row_tile = min(row_tile, max(128, n_rows))
    v_pad = ((n_rows + row_tile - 1) // row_tile) * row_tile

    grid = (v_pad // row_tile, e_pad // entry_block)
    kernel = functools.partial(_spmv_kernel, row_tile=row_tile)
    y = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((v_pad,), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((entry_block,), lambda i, j: (j,)),
            pl.BlockSpec((entry_block,), lambda i, j: (j,)),
        ],
        out_specs=pl.BlockSpec((row_tile,), lambda i, j: (i,)),
        scratch_shapes=[pltpu.VMEM((row_tile,), jnp.float32)],
        interpret=interpret,
    )(rows.astype(jnp.int32), prod.astype(jnp.float32))
    return y[:n_rows]


def coo_matvec_pallas(
    rows, cols, vals, x, n_rows: int, interpret: bool = False
):
    """Drop-in for ops.segment.coo_matvec using the one-hot MXU kernel.

    The x-gather stays in XLA (one vectorized gather); only the scatter
    side moves into Pallas.
    """
    prod = vals * jnp.take(x, cols, mode="clip")
    return coo_segment_sum_pallas(rows, prod, n_rows, interpret=interpret)
