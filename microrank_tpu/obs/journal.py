"""Per-run JSONL journal: one machine-readable event per window.

``windows.jsonl`` (pipeline.results) records WHAT was ranked; the
journal records HOW the run behaved — per-window timings, device
convergence (iterations + residual), queue depth at dispatch, and a
host-contention sample — so a replay whose throughput was quietly eaten
by host load (the round-5 artifact undersold the build 1.7x exactly this
way) is self-flagging. Events:

* ``run_start`` — config digest (backend/kernel/pad_policy/...), host
  snapshot, schema version;
* ``window`` — one per emitted WindowResult: bounds, outcome, partition
  sizes, timings dict, rank_iterations / rank_residual (device
  convergence trace), kernel, queue_depth, host sample;
* ``follow_poll`` — one per follow-mode poll: size, horizon, counters;
* ``jit_cache_miss`` — one per first-seen compile key at a dispatch
  seam while the compile witness is armed: entry-point ``program``,
  ``kernel``, ``occupancy``, the shape ``key``, and whether the static
  key-space analysis (``analysis.shapes``) ``predicted`` it — an
  unpredicted key is a model gap the ``witness`` CLI replays;
* ``run_end`` — totals + a flat telemetry summary (retraces, staged
  bytes).

The writer appends line-buffered JSON under a lock (the async fetch
worker can finalize windows while the main thread emits); every event
carries ``ts`` (epoch seconds) and ``schema``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path
from typing import Optional

SCHEMA_VERSION = 1

JOURNAL_NAME = "journal.jsonl"


class RunJournal:
    """Append-only JSONL event writer for one pipeline run."""

    def __init__(self, path, sentinel=None, max_bytes: int = 0):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()
        # Size-based rotation (ObsConfig.journal_max_bytes): 0 = never.
        self.max_bytes = int(max_bytes or 0)
        self._rotations = len(journal_parts(self.path))
        if sentinel is None:
            from .host import ContentionSentinel

            sentinel = ContentionSentinel()
        self.sentinel = sentinel

    def emit(self, event: str, **fields) -> None:
        rec = {"event": event, "ts": time.time(),
               "schema": SCHEMA_VERSION, **fields}
        line = json.dumps(rec) + "\n"
        with self._lock:
            self._maybe_rotate(len(line))
            with open(self.path, "a") as f:
                f.write(line)

    def _maybe_rotate(self, incoming: int) -> None:
        """Rotate the live file to ``journal.jsonl.<n>`` when the next
        line would push it past ``max_bytes``. fsync BEFORE the rename:
        the rotated part is immutable history from the moment it gets
        its final name, so it must be durable under that name — a crash
        mid-rotation can only lose lines still in the live file's page
        cache, never a sealed part. Caller holds ``self._lock``."""
        if not self.max_bytes:
            return
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return
        if size + incoming <= self.max_bytes:
            return
        try:
            with open(self.path, "a") as f:
                f.flush()
                # mrlint: disable=R12(durability contract: fsync-before-rename must serialize with emit() writers under the same lock; bounded by local-disk latency, no network I/O)
                os.fsync(f.fileno())
            self._rotations += 1
            part = self.path.with_name(
                f"{self.path.name}.{self._rotations}"
            )
            os.replace(self.path, part)
        except OSError:  # pragma: no cover - exotic filesystems
            return
        rot = {
            "event": "journal_rotated", "ts": time.time(),
            "schema": SCHEMA_VERSION, "part": part.name,
            "part_bytes": size, "rotation": self._rotations,
        }
        with open(self.path, "a") as f:
            f.write(json.dumps(rot) + "\n")

    def run_start(self, **config_fields) -> None:
        self.emit("run_start", host=self.sentinel.sample(), **config_fields)

    def window(self, result, queue_depth: Optional[int] = None) -> None:
        """One emitted WindowResult -> one journal event. Samples host
        contention inline (two syscalls + one /proc read)."""
        outcome = (
            "ranked" if result.ranking
            else ("skipped" if result.skipped_reason else "clean")
        )
        self.emit(
            "window",
            start=result.start,
            end=result.end,
            anomaly=bool(result.anomaly),
            outcome=outcome,
            skipped_reason=result.skipped_reason,
            n_traces=result.n_traces,
            n_abnormal=result.n_abnormal,
            timings=result.timings,
            rank_iterations=result.rank_iterations,
            rank_residual=result.rank_residual,
            kernel=result.kernel,
            route=getattr(result, "route", None),
            kind_dedup=result.kind_dedup,
            ingest_rejected=getattr(result, "ingest_rejected", 0),
            degraded_input=bool(
                getattr(result, "degraded_input", False)
            ),
            queue_depth=(
                queue_depth if queue_depth is not None
                else result.queue_depth
            ),
            top1=(result.ranking[0][0] if result.ranking else None),
            host=self.sentinel.sample(),
        )

    def run_end(self, **fields) -> None:
        from .metrics import snapshot_to_result_fields

        self.emit(
            "run_end",
            host=self.sentinel.sample(),
            telemetry=snapshot_to_result_fields(),
            **fields,
        )
        # Durability edge: run_end is the record a post-mortem reads
        # first — force it (and everything before it) to disk so a
        # crash right after the drain cannot truncate the journal.
        self.sync()

    def sync(self) -> None:
        """flush+fsync the journal file (run_end, SIGTERM drain, and
        every flight-recorder dump call this): ``emit`` leaves each
        line in the page cache when its handle closes; only an fsync
        guarantees a crash never truncates the last incident's
        events."""
        with self._lock:
            if not self.path.exists():
                return
            try:
                with open(self.path, "a") as f:
                    f.flush()
                    # mrlint: disable=R12(durability contract: the fsync must serialize with emit() writers under the same lock so it covers every line already written; bounded by local-disk latency, no network I/O)
                    os.fsync(f.fileno())
            except OSError:  # pragma: no cover - exotic filesystems
                pass


# ---------------------------------------------------------------------------
# Current-journal registry.  Deep seams (the compile witness inside the
# dispatch router) have no journal handle threaded to them; run entries
# register theirs here so those seams can emit without plumbing the
# writer through every signature.  One journal per process at a time —
# the same invariant the metrics registry already relies on.

_current: Optional[RunJournal] = None
_current_lock = threading.Lock()


def set_current_journal(journal: Optional["RunJournal"]) -> None:
    """Register (or clear, with None) the process-wide journal."""
    global _current
    with _current_lock:
        _current = journal


def current_journal() -> Optional["RunJournal"]:
    with _current_lock:
        return _current


def emit_current(event: str, **fields) -> None:
    """Emit on the registered journal if one exists; silently a no-op
    otherwise (bench/test paths that never open a journal)."""
    j = current_journal()
    if j is not None:
        j.emit(event, **fields)


def journal_parts(path) -> list:
    """Rotated parts of a journal (``journal.jsonl.<n>``) in rotation
    order — the live file is NOT included."""
    p = Path(path)
    parts = []
    for cand in p.parent.glob(p.name + ".*"):
        suffix = cand.name[len(p.name) + 1:]
        if suffix.isdigit():
            parts.append((int(suffix), cand))
    return [c for _, c in sorted(parts)]


def read_journal(path) -> list:
    """Parse a journal back into event dicts (tests, ``cli stats``).
    Rotated parts (``journal.jsonl.<n>``, oldest first) are read before
    the live file, so consumers see one contiguous event stream."""
    out = []
    p = Path(path)
    for part in [*journal_parts(p), p]:
        if not part.exists():
            continue
        for line in part.read_text().splitlines():
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
