"""Host-contention sentinel: loadavg + CPU-steal sampling.

Round 5's verdict measured the replay 1.7x slower in the artifact of
record than the build achieved — the host was contended during the
recorded run and nothing flagged it. This sentinel makes contention a
recorded fact: the run journal samples it per window and ``bench.py``
embeds a start/end sample in its JSON artifact, so a number taken on a
busy machine carries its own asterisk.

Two signals:

* **normalized load** — 1-minute loadavg / CPU count. > ~1.2 means
  runnable threads queued behind the pipeline's own (the pipeline is
  single-process + 2 worker threads; it should not saturate a machine);
* **steal fraction** — the delta of /proc/stat's ``steal`` jiffies over
  total jiffies since the previous sample: time the hypervisor ran
  someone else while this VM wanted the CPU. Invisible to loadavg,
  common on oversubscribed cloud hosts.

Non-Linux (no /proc) degrades to loadavg only; platforms without
``os.getloadavg`` report zeros rather than raising — telemetry must
never take down the pipeline.
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional, Tuple

DEFAULT_LOAD_THRESHOLD = 1.2   # normalized 1-min load
DEFAULT_STEAL_THRESHOLD = 0.05  # 5% of CPU time stolen


def _read_proc_stat() -> Optional[Tuple[int, int]]:
    """(steal_jiffies, total_jiffies) from /proc/stat's cpu line."""
    try:
        with open("/proc/stat") as f:
            line = f.readline()
    except OSError:
        return None
    parts = line.split()
    if not parts or parts[0] != "cpu":
        return None
    try:
        vals = [int(x) for x in parts[1:]]
    except ValueError:
        return None
    # user nice system idle iowait irq softirq steal guest guest_nice
    steal = vals[7] if len(vals) > 7 else 0
    return steal, sum(vals)


class ContentionSentinel:
    """Stateful sampler — steal needs a previous sample to difference."""

    def __init__(
        self,
        load_threshold: float = DEFAULT_LOAD_THRESHOLD,
        steal_threshold: float = DEFAULT_STEAL_THRESHOLD,
    ):
        self.load_threshold = float(load_threshold)
        self.steal_threshold = float(steal_threshold)
        self._prev_stat = _read_proc_stat()
        self._prev_ts = time.time()

    def sample(self) -> Dict[str, float]:
        """One contention sample. Cheap (two syscalls + one /proc read)
        — safe to call per window."""
        try:
            load1, load5, _ = os.getloadavg()
        except (OSError, AttributeError):
            load1 = load5 = 0.0
        cpus = os.cpu_count() or 1
        norm = load1 / cpus

        steal_ratio = 0.0
        cur = _read_proc_stat()
        if cur is not None and self._prev_stat is not None:
            d_steal = cur[0] - self._prev_stat[0]
            d_total = cur[1] - self._prev_stat[1]
            if d_total > 0:
                steal_ratio = max(0.0, d_steal / d_total)
        self._prev_stat = cur
        self._prev_ts = time.time()

        contended = (
            norm > self.load_threshold
            or steal_ratio > self.steal_threshold
        )
        sample = {
            "load1": round(load1, 3),
            "load5": round(load5, 3),
            "cpus": cpus,
            "norm_load": round(norm, 4),
            "steal_ratio": round(steal_ratio, 5),
            "contended": bool(contended),
        }
        # Mirror into the live gauges so /metrics scrapes see it too.
        try:
            from .metrics import host_load_gauge, host_steal_gauge

            host_load_gauge().set(norm)
            host_steal_gauge().set(steal_ratio)
        except Exception:
            pass
        return sample
