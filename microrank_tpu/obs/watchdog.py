"""SLO self-watchdog: the fleet watches itself with its own machinery.

MicroRank's thesis is trace-based RCA of OTHER systems; PR 7's dogfood
proved the span ring can rank the pipeline's own slowest stage. This
module closes the loop continuously: the coordinator evaluates the
system's OWN golden signals from the federated fleet registry —

* per-stage latency budgets (``microrank_stage_seconds`` over-budget
  fraction vs the stage error budget),
* error/degraded rate (skipped stream windows + degraded serves over
  windows processed),
* fleet watermark lag (max per-host gauge vs budget),
* pipeline queue depth (max per-host gauge vs budget)

— as MULTI-WINDOW BURN RATES: each eval appends a snapshot to a ring,
and a signal breaches only when both the fast window (last
``fast_windows`` evals — reactive) and the slow window (last
``slow_windows`` — flap-damping) burn past the threshold. Breaches
open SELF-incidents through the unmodified
:class:`~microrank_tpu.stream.incidents.IncidentTracker`: the ranked
"window" is the breaching signals sorted by burn (suspect =
``stage:<s>@<host>`` when one host dominates the recent cost),
fingerprint-deduped across evals, resolved after sustained recovery,
journaled/webhooked/flight-dumped like any fault. This is the sensor
layer ROADMAP item 5's adaptive shedding actuates on.
"""

from __future__ import annotations

import math
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.logging import get_logger

log = get_logger("microrank_tpu.obs.watchdog")

SELF_INCIDENT_LOG = "self_incidents.jsonl"


def _ratio(bad: float, total: float) -> float:
    return bad / total if total > 0 else 0.0


class _Snapshot:
    """One eval's raw signal readings (cumulative pairs for ratio
    signals, instantaneous values for gauge signals)."""

    __slots__ = ("t", "ratio", "gauge")

    def __init__(self, t: float):
        self.t = t
        self.ratio: Dict[str, Tuple[float, float]] = {}  # (bad, total)
        self.gauge: Dict[str, float] = {}                # burn units


class SLOWatchdog:
    """Burn-rate evaluator over a registry view, reporting into an
    IncidentTracker the caller owns (UNMODIFIED machinery — the
    watchdog is just another ranked-window producer)."""

    def __init__(
        self,
        config,
        tracker,
        view: Callable[[], "object"],
        clock=time.monotonic,
        wall=time.time,
    ):
        self.cfg = config
        self.tracker = tracker
        self.view = view
        self.clock = clock
        self.wall = wall
        self._ring: "deque[_Snapshot]" = deque(
            maxlen=max(2, int(config.slow_windows) + 1)
        )
        self._last_eval: Optional[float] = None
        self.evals = 0
        self.breaches = 0
        # Per-stage budgets in seconds (overrides on top of the
        # uniform default).
        self._budgets = {
            str(s): float(b) / 1e3 for s, b in config.stage_budgets
        }
        self._default_budget = float(config.stage_budget_ms) / 1e3

    # ---------------------------------------------------------- snapshot
    def _stage_budget(self, stage: str) -> float:
        return self._budgets.get(stage, self._default_budget)

    @staticmethod
    def _counter_sum(reg, name: str, **labels) -> float:
        m = reg.get(name)
        if m is None or m.kind != "counter":
            return 0.0
        total = 0.0
        for s in m.samples():
            if all(s["labels"].get(k) == v for k, v in labels.items()):
                total += float(s["value"])
        return total

    def _snapshot(self, reg) -> _Snapshot:
        snap = _Snapshot(self.clock())
        # Per-stage latency: over-budget observation count from the
        # cumulative histogram (budget snaps to the first bucket bound
        # >= the configured value — the resolution the data has).
        hist = reg.get("microrank_stage_seconds")
        if hist is not None and hist.kind == "histogram":
            per_stage: Dict[str, Tuple[float, float]] = {}
            bounds = list(hist.buckets)
            for s in hist.samples():
                stage = s["labels"].get("stage", "")
                budget = self._stage_budget(stage)
                idx = len(bounds)
                for j, b in enumerate(bounds):
                    if b >= budget:
                        idx = j + 1  # buckets[:idx] are within budget
                        break
                ok = sum(s["buckets"][:idx])
                total = int(s["count"])
                bad, tot = per_stage.get(stage, (0.0, 0.0))
                per_stage[stage] = (bad + (total - ok), tot + total)
            for stage, (bad, tot) in per_stage.items():
                snap.ratio[f"stage:{stage}"] = (float(bad), float(tot))
        # Error/degraded rate over windows processed.
        windows = self._counter_sum(reg, "microrank_stream_windows_total")
        skipped = self._counter_sum(
            reg, "microrank_stream_windows_total", outcome="skipped"
        )
        degraded = self._counter_sum(reg, "microrank_serve_degraded_total")
        snap.ratio["error_rate"] = (skipped + degraded, windows)
        # Gauge signals: worst host, in budget units.
        for signal, name, budget in (
            (
                "watermark_lag",
                "microrank_fleet_host_watermark_lag_seconds",
                float(self.cfg.watermark_lag_budget_seconds),
            ),
            (
                "queue_depth",
                "microrank_fleet_host_queue_depth",
                float(self.cfg.queue_depth_budget),
            ),
        ):
            g = reg.get(name)
            if g is None or budget <= 0:
                continue
            worst = max(
                (float(s["value"]) for s in g.samples()), default=0.0
            )
            snap.gauge[signal] = worst / budget
        return snap

    # -------------------------------------------------------------- burn
    def _burn(self, window: int) -> Dict[str, float]:
        """Burn rate per signal over the last ``window`` snapshots
        (fewer early in the run: multi-window alerting degrades to
        since-start, which only makes the slow window stricter)."""
        if len(self._ring) < 2:
            return {}
        now = self._ring[-1]
        base = self._ring[max(0, len(self._ring) - 1 - window)]
        burns: Dict[str, float] = {}
        for sig, (bad, tot) in now.ratio.items():
            b0, t0 = base.ratio.get(sig, (0.0, 0.0))
            dbad, dtot = bad - b0, tot - t0
            if dtot < float(self.cfg.min_samples):
                burns[sig] = 0.0
                continue
            budget = (
                float(self.cfg.stage_error_budget)
                if sig.startswith("stage:")
                else float(self.cfg.error_budget)
            )
            burns[sig] = (
                _ratio(dbad, dtot) / budget if budget > 0 else math.inf
            )
        for sig in now.gauge:
            vals = [
                s.gauge[sig]
                for s in list(self._ring)[-(window + 1):]
                if sig in s.gauge
            ]
            burns[sig] = sum(vals) / len(vals) if vals else 0.0
        return burns

    def _attribute_host(self, reg, stage: str) -> Optional[str]:
        """Name the host whose recent per-stage cost dominates (the
        per-host breakdown gauge the delta fold maintains)."""
        g = reg.get("microrank_fleet_host_stage_ms")
        if g is None:
            return None
        costs = sorted(
            (
                (float(s["value"]), s["labels"].get("host", ""))
                for s in g.samples()
                if s["labels"].get("stage") == stage
            ),
            reverse=True,
        )
        if not costs:
            return None
        if len(costs) == 1:
            return costs[0][1]
        lead, runner = costs[0], costs[1]
        factor = float(self.cfg.host_attribution_factor)
        if runner[0] <= 0 or lead[0] >= factor * runner[0]:
            return lead[1]
        return None

    # -------------------------------------------------------------- eval
    def evaluate(self, force: bool = False) -> List[str]:
        """One watchdog tick: snapshot the view, compute fast+slow
        burns, drive the tracker. Returns the breaching signal names
        (empty = healthy eval). Rate-limited to ``eval_seconds``
        unless forced; called from the coordinator's reaper thread,
        OUTSIDE the fleet lock."""
        from .metrics import (
            record_watchdog_breach,
            record_watchdog_burn,
            record_watchdog_eval,
        )

        now = self.clock()
        if (
            not force
            and self._last_eval is not None
            and now - self._last_eval < float(self.cfg.eval_seconds)
        ):
            return []
        self._last_eval = now
        self.evals += 1
        record_watchdog_eval()
        reg = self.view()
        self._ring.append(self._snapshot(reg))
        fast = self._burn(int(self.cfg.fast_windows))
        slow = self._burn(int(self.cfg.slow_windows))
        threshold = float(self.cfg.burn_threshold)
        breaching: List[Tuple[str, float]] = []
        for sig, fb in fast.items():
            sb = slow.get(sig, 0.0)
            record_watchdog_burn(sig, "fast", fb)
            record_watchdog_burn(sig, "slow", sb)
            if fb >= threshold and sb >= threshold:
                breaching.append((sig, max(fb, sb)))
                record_watchdog_breach(sig)
        label = str(int(self.wall()))
        if breaching:
            self.breaches += 1
            breaching.sort(key=lambda x: (-x[1], x[0]))
            ranking = []
            for sig, burn in breaching:
                name = sig
                if sig.startswith("stage:"):
                    host = self._attribute_host(reg, sig.split(":", 1)[1])
                    if host:
                        name = f"{sig}@{host}"
                ranking.append((name, round(burn, 4)))
            log.warning(
                "watchdog breach: %s",
                ", ".join(f"{n} burn={b}" for n, b in ranking),
            )
            self.tracker.observe_ranked(f"watchdog-{label}", ranking)
            return [n for n, _ in ranking]
        self.tracker.observe_healthy(f"watchdog-{label}")
        return []
