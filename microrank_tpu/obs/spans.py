"""Span-level self-tracing: the pipeline's own distributed trace.

MicroRank's premise is that parent-linked spans localize latency root
causes — yet until this module the serve/stream/dispatch pipeline (a
multi-threaded system: scheduler thread, build worker pool, engine
thread, double-buffered staging) emitted only aggregate metrics and
per-window journal lines, so a degraded dispatch or a slow stage was
invisible as a causal chain. Here every stage at the journal's existing
choke points emits a span:

* a **trace** is one unit of pipeline work — a streaming window
  (``trace_id = "win-<start>"``), a serve request (``trace_id =
  request_id``), or an offline replay window;
* a **span** is one stage of that trace: ingest/parse, detect, graph
  ``build`` (on the worker pool), ``staging``, ``device_dispatch``,
  ``result_fetch``, ``incident`` lifecycle — parent-linked through a
  ``contextvars`` trace context that callers explicitly carry across
  threads (``current_context()`` at submit, ``attach()`` on the
  worker);
* completed spans land in a bounded in-memory **ring** (a locked
  deque), cheap enough to stay on in production: the per-span cost is
  a contextvar read plus the deque append (~2 us next to
  millisecond-scale stages; ``bench.py`` reports the replay overhead
  as ``trace_overhead``).

The flight recorder (``obs.flight``) dumps the ring as Perfetto JSON
and as MicroRank's OWN span CSV schema, so ``cli run`` over a dump
ranks the pipeline's slowest stage — the dogfood path.

Chaos hook: ``ObsConfig.inject_stage_sleep_ms`` sleeps inside every
``inject_every``-th span named ``inject_stage`` — the dogfood test
slows the build pool this way and asserts the self-rank blames it.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional

# The ambient trace context of the current thread of execution. Worker
# threads do NOT inherit it implicitly — the pool/scheduler seams
# capture it at submit time and attach it on the worker (that explicit
# hand-off IS the cross-thread propagation this module exists to test).
_CTX: "contextvars.ContextVar[Optional[SpanContext]]" = (
    contextvars.ContextVar("microrank_span_ctx", default=None)
)


@dataclass(frozen=True)
class SpanContext:
    """What a child span needs from its parent: the trace it belongs to
    and the span id to parent-link against."""

    trace_id: str
    span_id: str


@dataclass
class Span:
    """One completed pipeline stage."""

    trace_id: str
    span_id: str
    parent_id: Optional[str]
    name: str                    # stage name (the journal's vocabulary)
    service: str                 # subsystem: pipeline|stream|serve|dispatch
    thread: str                  # recording thread's name
    start_us: int                # epoch microseconds
    dur_us: int
    attrs: Dict[str, object] = field(default_factory=dict)


class SpanTracer:
    """Bounded-ring span recorder with contextvar trace propagation.

    Thread-safe: spans complete on whichever thread ran the stage; the
    ring append holds one lock for a deque push. ``enabled=False``
    makes every API a near-no-op (one attribute read) so the tracer can
    stay wired unconditionally.
    """

    def __init__(
        self,
        capacity: int = 8192,
        enabled: bool = True,
        inject_stage: str = "",
        inject_sleep_ms: float = 0.0,
        inject_every: int = 1,
    ):
        self.enabled = bool(enabled)
        self.capacity = max(16, int(capacity))
        self._ring: "deque[Span]" = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self.recorded = 0            # lifetime spans (ring may have fewer)
        self.inject_stage = inject_stage
        self.inject_sleep_ms = float(inject_sleep_ms)
        self.inject_every = max(1, int(inject_every))
        self._inject_seen = 0

    # ------------------------------------------------------------ context
    def new_trace(self, trace_id: str) -> SpanContext:
        """Root context for one unit of pipeline work (window/request).
        Children parent-link to the root span id; the root span itself
        is recorded explicitly by the owner via :meth:`record_span`."""
        return SpanContext(str(trace_id), f"s{next(self._ids):08x}")

    @staticmethod
    def current_context() -> Optional[SpanContext]:
        """The ambient context on THIS thread (capture before handing
        work to a pool; attach it on the worker)."""
        return _CTX.get()

    @contextlib.contextmanager
    def attach(self, ctx: Optional[SpanContext]) -> Iterator[None]:
        """Install ``ctx`` as the ambient context for the block — the
        explicit cross-thread hand-off. ``None`` is a no-op (spans in
        the block start fresh traces)."""
        if ctx is None:
            yield
            return
        token = _CTX.set(ctx)
        try:
            yield
        finally:
            _CTX.reset(token)

    # -------------------------------------------------------------- spans
    @contextlib.contextmanager
    def span(
        self,
        name: str,
        service: str = "pipeline",
        ctx: Optional[SpanContext] = None,
        **attrs,
    ) -> Iterator[Optional[SpanContext]]:
        """Record one stage span around the block.

        Parentage: ``ctx`` when given, else the ambient context; with
        neither, the span roots a fresh anonymous trace. The span's own
        context is ambient inside the block, so nested stages (the
        router's staging/dispatch/fetch under a window's rank) link up
        without threading anything through signatures.
        """
        if not self.enabled:
            yield None
            return
        parent = ctx if ctx is not None else _CTX.get()
        trace_id = (
            parent.trace_id if parent else f"trace-{next(self._ids):08x}"
        )
        own = SpanContext(trace_id, f"s{next(self._ids):08x}")
        token = _CTX.set(own)
        start_us = int(time.time() * 1e6)
        p0 = time.perf_counter()
        try:
            # FaultPlan seam at span ENTRY: a ``stage:<name>`` latency
            # spec sleeps here, INSIDE both the span's timed region and
            # the StageTimings timer wrapping it — so the injected
            # slowness lands in the span duration AND the stage_seconds
            # histogram the SLO watchdog reads, exactly like a real
            # slow stage. Host-scoped specs make per-host stage faults
            # drivable in a fleet. (The legacy ObsConfig knob keeps its
            # exit-side hook below.)
            self._chaos_stage(name)
            yield own
        finally:
            self._maybe_inject(name)
            dur_us = int((time.perf_counter() - p0) * 1e6)
            _CTX.reset(token)
            self._record(
                Span(
                    trace_id=trace_id,
                    span_id=own.span_id,
                    parent_id=parent.span_id if parent else None,
                    name=str(name),
                    service=str(service),
                    thread=threading.current_thread().name,
                    start_us=start_us,
                    dur_us=dur_us,
                    attrs=dict(attrs) if attrs else {},
                )
            )

    def record_span(
        self,
        name: str,
        ctx: SpanContext,
        start_us: int,
        dur_us: int,
        service: str = "pipeline",
        parent_id: Optional[str] = None,
        **attrs,
    ) -> None:
        """Record a span whose lifetime was tracked externally — the
        per-window/per-request ROOT span, whose start and end straddle
        async hand-offs no single ``with`` block can wrap. ``ctx`` is
        the root context children already parent-linked against."""
        if not self.enabled:
            return
        self._record(
            Span(
                trace_id=ctx.trace_id,
                span_id=ctx.span_id,
                parent_id=parent_id,
                name=str(name),
                service=str(service),
                thread=threading.current_thread().name,
                start_us=int(start_us),
                dur_us=max(0, int(dur_us)),
                attrs=dict(attrs) if attrs else {},
            )
        )

    def _chaos_stage(self, name: str) -> None:
        """The unified chaos surface's stage seam. No plan installed:
        one module-global read and return."""
        from ..chaos.faults import get_fault_plan, maybe_inject

        if get_fault_plan() is None:
            return
        maybe_inject(f"stage:{name}")

    def _maybe_inject(self, name: str) -> None:
        """The chaos hook: sleep inside every ``inject_every``-th span
        named ``inject_stage`` (still inside the span's timed region,
        so the recorded duration carries the fault — exactly what a
        genuinely slow stage would look like)."""
        if self.inject_sleep_ms <= 0 or name != self.inject_stage:
            return
        # Spans complete on whichever thread ran the stage; the firing
        # decision shares the ring's lock (mrlint R10) and the sleep
        # itself stays outside it (R12).
        with self._lock:
            self._inject_seen += 1
            fire = (self._inject_seen - 1) % self.inject_every == 0
        if fire:
            # Legacy knob aliased onto the unified chaos surface: the
            # firing is recorded like any FaultPlan injection
            # (microrank_fault_injections_total + journal), the sleep
            # itself stays here.
            from ..chaos.faults import record_injection

            record_injection(
                f"stage:{self.inject_stage}", "latency",
                value=self.inject_sleep_ms,
            )
            time.sleep(self.inject_sleep_ms / 1e3)

    def _record(self, span: Span) -> None:
        with self._lock:
            self._ring.append(span)
            self.recorded += 1
        from .metrics import spans_recorded

        spans_recorded().inc()

    # ------------------------------------------------------------ reading
    def snapshot(self) -> List[Span]:
        """Stable copy of the ring, oldest first (the flight recorder's
        read path)."""
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def dropped(self) -> int:
        """Lifetime spans that fell off the ring."""
        with self._lock:
            return self.recorded - len(self._ring)


_tracer_lock = threading.Lock()
_tracer: Optional[SpanTracer] = None


def get_tracer() -> SpanTracer:
    """The process tracer every instrumentation point records into.
    Starts DISABLED — pipelines arm it from their config at run start
    (``configure_tracer``), so library imports and unit tests pay
    nothing."""
    global _tracer
    with _tracer_lock:
        if _tracer is None:
            _tracer = SpanTracer(enabled=False)
        return _tracer


def set_tracer(tracer: Optional[SpanTracer]) -> None:
    global _tracer
    with _tracer_lock:
        _tracer = tracer


def configure_tracer(obs_config) -> SpanTracer:
    """Install a fresh tracer per ObsConfig (run entry points call this:
    TableRCA.run, StreamEngine.run, ServeService.start). A fresh ring
    per run means a flight dump never mixes two runs' spans."""
    tracer = SpanTracer(
        capacity=obs_config.span_ring,
        enabled=obs_config.spans,
        inject_stage=obs_config.inject_stage,
        inject_sleep_ms=obs_config.inject_stage_sleep_ms,
        inject_every=obs_config.inject_every,
    )
    set_tracer(tracer)
    return tracer
