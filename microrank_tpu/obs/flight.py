"""Incident flight recorder: dump the span ring when something breaks.

Three triggers, all "the moment an operator will want a causal trace":
incident open (stream), degraded dispatch (serve's numpy_ref fallback),
and SIGTERM drain. A dump is one directory under ``out_dir/flight/``:

* ``trace.json``  — Chrome/Perfetto trace-event JSON (load in
  ``ui.perfetto.dev`` — threads are tracks, spans are slices);
* ``spans.csv``   — the SAME spans in MicroRank's OWN input schema
  (io.schema canonical columns: stage name -> operationName, subsystem
  -> serviceName/podName, trace context -> traceID/spanID/ParentSpanId)
  so ``cli run --normal <healthy dump> --abnormal <this dump>`` ranks
  the pipeline's own slowest stage — the dogfood path that proves the
  RCA math on ourselves;
* ``events.jsonl`` — journal events correlated to the ring's time range
  (the journal is fsync'd first — a crash right after the dump cannot
  truncate the incident's events);
* ``metrics.json`` / ``metrics.prom`` — the registry snapshot;
* ``manifest.json`` — reason, time range, span/trace counts, drops.

Dumps are rate-limited (``ObsConfig.flight_min_interval_seconds``) so
an incident storm cannot fill the disk; suppressed dumps are counted.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import List, Optional

import numpy as np

from ..utils.logging import get_logger
from .spans import Span, get_tracer

log = get_logger("microrank_tpu.obs.flight")

FLIGHT_DIR = "flight"


def _iso_us(us: int) -> str:
    return str(np.datetime64(int(us), "us"))


def spans_to_rows(spans: List[Span]) -> List[dict]:
    """Render ring spans as rows of the canonical span schema.

    ``startTime``/``endTime`` are TRACE-level bounds (the loader's
    contract — io.schema documents them as trace start/end), computed
    per trace id over the dump; ``duration`` stays per-span (µs), which
    is what the SLO detector compares. ``podName`` mirrors the
    subsystem so pod-level ranking names read ``<service>_<stage>``.
    """
    bounds = {}
    for s in spans:
        lo, hi = bounds.get(s.trace_id, (s.start_us, s.start_us + s.dur_us))
        bounds[s.trace_id] = (
            min(lo, s.start_us), max(hi, s.start_us + s.dur_us)
        )
    rows = []
    for s in spans:
        lo, hi = bounds[s.trace_id]
        rows.append(
            {
                "traceID": s.trace_id,
                "spanID": s.span_id,
                "ParentSpanId": s.parent_id or "",
                "operationName": s.name,
                "serviceName": s.service,
                "podName": s.service,
                "duration": int(s.dur_us),
                "startTime": _iso_us(lo),
                "endTime": _iso_us(hi),
            }
        )
    return rows


def write_spans_csv(spans: List[Span], path) -> None:
    import csv

    cols = [
        "traceID", "spanID", "ParentSpanId", "operationName",
        "serviceName", "podName", "duration", "startTime", "endTime",
    ]
    with open(path, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=cols)
        w.writeheader()
        for row in spans_to_rows(spans):
            w.writerow(row)


def chrome_events(
    spans: List[Span], pid: int = 1, process_name: Optional[str] = None
) -> List[dict]:
    """Chrome trace events for one process's spans ("X" complete
    events; one tid per recording thread, named via "M" metadata).
    ``pid``/``process_name`` let the fleet plane merge several
    processes' rings into ONE Perfetto dump with distinct tracks."""
    tids = {}
    events = []
    for s in spans:
        tid = tids.setdefault(s.thread, len(tids) + 1)
        events.append(
            {
                "name": s.name,
                "cat": s.service,
                "ph": "X",
                "ts": s.start_us,
                "dur": max(1, s.dur_us),
                "pid": pid,
                "tid": tid,
                "args": {
                    "trace_id": s.trace_id,
                    "span_id": s.span_id,
                    "parent_id": s.parent_id,
                    **s.attrs,
                },
            }
        )
    meta = [
        {
            "name": "thread_name",
            "ph": "M",
            "pid": pid,
            "tid": tid,
            "args": {"name": thread},
        }
        for thread, tid in tids.items()
    ]
    if process_name is not None:
        meta.insert(
            0,
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": process_name},
            },
        )
    return meta + events


def write_chrome_trace(spans: List[Span], path) -> None:
    """Chrome trace-event JSON for one process (see chrome_events)."""
    Path(path).write_text(
        json.dumps(
            {"traceEvents": chrome_events(spans), "displayTimeUnit": "ms"}
        )
    )


class FlightRecorder:
    """Owns the dump directory, the rate limit, and the journal handle
    to fsync+correlate. One per run (serve service / stream engine)."""

    def __init__(
        self,
        out_dir,
        obs_config,
        journal=None,
        tracer=None,
    ):
        from ..utils.guards import TrackedLock, register_shared

        self.base = Path(out_dir) / FLIGHT_DIR
        self.cfg = obs_config
        self.journal = journal
        self._tracer = tracer
        # Incident-open (engine), degraded-dispatch (scheduler) and
        # SIGTERM (main) triggers race into the rate limiter — a
        # registered mrsan shared object (R10's runtime twin).
        self._lock = TrackedLock("flight_recorder")
        register_shared("flight_recorder", {"flight_recorder"})
        self._last_mono: Optional[float] = None
        self.dumps = 0

    @property
    def tracer(self):
        return self._tracer if self._tracer is not None else get_tracer()

    def dump(self, reason: str, extra: Optional[dict] = None) -> Optional[Path]:
        """Write one flight dump; returns its directory, or None when
        the recorder is disabled or the rate limit suppressed it.
        ``extra`` lands under the manifest's ``fleet`` key — the
        coordinator cross-links the worker rings it asked for there."""
        from .metrics import record_flight_dump

        if not self.cfg.flight:
            return None
        from ..utils.guards import note_shared_access

        with self._lock:
            note_shared_access("flight_recorder")
            now = time.monotonic()
            if (
                self._last_mono is not None
                and now - self._last_mono
                < max(0.0, float(self.cfg.flight_min_interval_seconds))
            ):
                record_flight_dump("suppressed")
                return None
            self._last_mono = now
            self.dumps += 1
            stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
            dump_dir = self.base / f"{stamp}-{self.dumps:02d}-{reason}"
        dump_dir.mkdir(parents=True, exist_ok=True)
        tracer = self.tracer
        spans = tracer.snapshot()
        write_spans_csv(spans, dump_dir / "spans.csv")
        write_chrome_trace(spans, dump_dir / "trace.json")
        n_events = self._dump_journal(spans, dump_dir)
        from . import get_registry
        from .metrics import ensure_catalog

        ensure_catalog()
        get_registry().write_snapshot(dump_dir)
        t_lo = min((s.start_us for s in spans), default=0)
        t_hi = max((s.start_us + s.dur_us for s in spans), default=0)
        (dump_dir / "manifest.json").write_text(
            json.dumps(
                {
                    "reason": reason,
                    "ts": time.time(),
                    "spans": len(spans),
                    "traces": len({s.trace_id for s in spans}),
                    "spans_dropped": tracer.dropped,
                    "ring_capacity": tracer.capacity,
                    "t_min_us": t_lo,
                    "t_max_us": t_hi,
                    "journal_events": n_events,
                    **({"fleet": extra} if extra else {}),
                },
                indent=2,
            )
        )
        record_flight_dump(reason)
        log.info(
            "flight dump (%s): %d spans / %d traces -> %s",
            reason, len(spans), len({s.trace_id for s in spans}), dump_dir,
        )
        return dump_dir

    def _dump_journal(self, spans: List[Span], dump_dir: Path) -> int:
        """fsync the run journal, then copy the events overlapping the
        ring's time range (±2 s slack) next to the spans."""
        if self.journal is None:
            return 0
        from .journal import read_journal

        self.journal.sync()
        if not spans:
            return 0
        t_lo = min(s.start_us for s in spans) / 1e6 - 2.0
        t_hi = max(s.start_us + s.dur_us for s in spans) / 1e6 + 2.0
        events = [
            e
            for e in read_journal(self.journal.path)
            if t_lo <= float(e.get("ts", 0.0)) <= t_hi
        ]
        with open(dump_dir / "events.jsonl", "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        return len(events)
