"""End-to-end telemetry: metrics registry, run journal, host sentinel.

The observability seam the rest of the framework records into —
see ``registry`` (Counter/Gauge/Histogram + Prometheus/JSON exposition),
``metrics`` (the canonical metric set + recording helpers), ``journal``
(per-run JSONL event log), ``host`` (contention sentinel), ``server``
(the ``--metrics-port`` HTTP endpoint, incl. ``/profilez``),
``spans`` (the self-tracing span ring + trace-context propagation),
``flight`` (the incident flight recorder) and ``profiler``
(sampled jax.profiler sessions + HBM gauges). ``cli stats`` re-exposes
a finished run's snapshot offline. The fleet tier federates all of it:
``fleetplane`` (heartbeat metrics deltas folded into one fleet
registry, merged fleet journal + cross-process Perfetto trace) and
``watchdog`` (multi-window burn-rate SLO evaluation over the fleet
registry, opening self-incidents through the stream tracker).
"""

from .flight import FLIGHT_DIR, FlightRecorder
from .host import ContentionSentinel
from .journal import (
    JOURNAL_NAME,
    RunJournal,
    current_journal,
    emit_current,
    journal_parts,
    read_journal,
    set_current_journal,
)
from .registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    diff_registries,
    get_registry,
    merge_registries,
    registry_from_json,
    set_registry,
)
from .spans import (
    Span,
    SpanContext,
    SpanTracer,
    configure_tracer,
    get_tracer,
    set_tracer,
)

__all__ = [
    "ContentionSentinel",
    "Counter",
    "FLIGHT_DIR",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "JOURNAL_NAME",
    "MetricsRegistry",
    "RunJournal",
    "Span",
    "SpanContext",
    "SpanTracer",
    "configure_tracer",
    "current_journal",
    "diff_registries",
    "emit_current",
    "get_registry",
    "get_tracer",
    "merge_registries",
    "journal_parts",
    "read_journal",
    "registry_from_json",
    "set_current_journal",
    "set_registry",
    "set_tracer",
]
