"""End-to-end telemetry: metrics registry, run journal, host sentinel.

The observability seam the rest of the framework records into —
see ``registry`` (Counter/Gauge/Histogram + Prometheus/JSON exposition),
``metrics`` (the canonical metric set + recording helpers), ``journal``
(per-run JSONL event log), ``host`` (contention sentinel) and ``server``
(the ``--metrics-port`` HTTP endpoint). ``cli stats`` re-exposes a
finished run's snapshot offline.
"""

from .host import ContentionSentinel
from .journal import JOURNAL_NAME, RunJournal, read_journal
from .registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    diff_registries,
    get_registry,
    registry_from_json,
    set_registry,
)

__all__ = [
    "ContentionSentinel",
    "Counter",
    "Gauge",
    "Histogram",
    "JOURNAL_NAME",
    "MetricsRegistry",
    "RunJournal",
    "diff_registries",
    "get_registry",
    "read_journal",
    "registry_from_json",
    "set_registry",
]
