"""The repo's canonical metric set + the recording helpers hot paths call.

Every metric the pipeline emits is declared here ONCE (name, help,
labels, buckets), so exposition stays consistent across the backend, the
table/pandas runners, follow mode and the bench — and ``cli stats`` can
document what a snapshot contains by construction. Helpers are plain
functions over the process registry; the hot-path cost is a dict lookup
plus a locked float add.

Naming: ``microrank_<noun>_<unit>`` with ``_total`` on counters, the
Prometheus convention.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from .registry import Counter, Gauge, Histogram, get_registry

# Iteration-count buckets: the reference runs exactly 25; tol runs vary.
ITER_BUCKETS = (1, 2, 4, 8, 12, 16, 20, 25, 32, 50, 100, 200)
# Residuals decay geometrically from O(1); log-spaced down to f32 noise.
RESIDUAL_BUCKETS = tuple(10.0 ** -e for e in range(12, -1, -1))
BYTE_BUCKETS = tuple(float(1 << s) for s in range(10, 34, 2))


def stage_seconds() -> Histogram:
    return get_registry().histogram(
        "microrank_stage_seconds",
        "Wall-clock of each pipeline stage (StageTimings feed)",
        labelnames=("stage",),
    )


def windows_total() -> Counter:
    return get_registry().counter(
        "microrank_windows_total",
        "Detection windows processed, by outcome",
        labelnames=("outcome",),  # ranked | clean | skipped
    )


def rank_iterations() -> Histogram:
    return get_registry().histogram(
        "microrank_rank_iterations",
        "Power-iteration steps per ranked window (device-side trace)",
        labelnames=("kernel",),
        buckets=ITER_BUCKETS,
    )


def rank_final_residual() -> Histogram:
    return get_registry().histogram(
        "microrank_rank_final_residual",
        "Final L-inf power-iteration residual per ranked window "
        "(max over both partitions)",
        labelnames=("kernel",),
        buckets=RESIDUAL_BUCKETS,
    )


def staged_bytes() -> Counter:
    return get_registry().counter(
        "microrank_staged_bytes_total",
        "Host->device bytes staged for rank programs",
        labelnames=("path",),  # blob | tree | sharded
    )


def staged_pad_bytes() -> Counter:
    return get_registry().counter(
        "microrank_staged_pad_bytes_total",
        "Padding-waste bytes inside staged graphs, audited per staged "
        "leaf against its exact live extents (pad_policy overhead: "
        "padded minus true bytes)",
        labelnames=("path",),
    )


def staging_transfers() -> Counter:
    return get_registry().counter(
        "microrank_staging_transfers_total",
        "Host->device staging transfers issued",
        labelnames=("path",),
    )


def jit_retraces() -> Counter:
    return get_registry().counter(
        "microrank_jit_retraces_total",
        "New jit cache entries per rank program (first compile counts; "
        "a growing count across same-shaped windows is a compile storm "
        "— check pad_policy)",
        labelnames=("program",),
    )


def jit_cache_misses() -> Counter:
    return get_registry().counter(
        "microrank_jit_cache_misses_total",
        "First-seen compile keys observed at dispatch seams by the "
        "compile witness (analysis.mrsan) — each is one trace+compile "
        "the warmup manifest did not absorb; cross-checked against the "
        "static key-space prediction (analysis.shapes R13-R16)",
        labelnames=("program",),
    )


def pipeline_inflight() -> Gauge:
    return get_registry().gauge(
        "microrank_pipeline_inflight",
        "Rank dispatches currently in flight (windows, or groups on the "
        "chunked lane)",
        labelnames=("lane",),  # window | chunk
    )


def follow_polls() -> Counter:
    return get_registry().counter(
        "microrank_follow_polls_total", "Follow-mode file polls"
    )


def follow_parse_failures() -> Counter:
    return get_registry().counter(
        "microrank_follow_parse_failures_total",
        "Follow-mode ingest parse failures (torn tail lines retried)",
    )


def follow_rotations() -> Counter:
    return get_registry().counter(
        "microrank_follow_rotations_total",
        "Follow-mode file rotations/truncations detected "
        "(size < last seen size)",
    )


def serve_requests() -> Counter:
    return get_registry().counter(
        "microrank_serve_requests_total",
        "RCA service requests, by outcome",
        # ranked | clean | skipped | rejected | failed
        labelnames=("outcome",),
    )


def serve_queue_depth() -> Gauge:
    return get_registry().gauge(
        "microrank_serve_queue_depth",
        "Requests admitted and not yet answered (admission-control "
        "depth; 429s start past ServeConfig.max_queue_depth)",
    )


def serve_batch_windows() -> Histogram:
    return get_registry().histogram(
        "microrank_serve_batch_windows",
        "Windows coalesced per device dispatch (micro-batch occupancy; "
        "a mass at 1 under concurrent load means buckets never match — "
        "check pad_policy and max_wait_ms)",
        buckets=(1, 2, 3, 4, 6, 8, 12, 16, 24, 32),
    )


def serve_last_batch_gauge() -> Gauge:
    return get_registry().gauge(
        "microrank_serve_last_batch_windows",
        "Occupancy of the most recent non-warmup device dispatch",
    )


def serve_degraded() -> Counter:
    return get_registry().counter(
        "microrank_serve_degraded_total",
        "Requests answered by the numpy_ref fallback after a failed "
        "device dispatch (responses carry degraded=true)",
    )


def serve_stage_seconds() -> Histogram:
    return get_registry().histogram(
        "microrank_serve_stage_seconds",
        "Wall-clock of each request stage in the RCA service",
        labelnames=("stage",),  # queue | build | rank | total
    )


def stream_windows() -> Counter:
    return get_registry().counter(
        "microrank_stream_windows_total",
        "Streaming windows closed at the watermark, by outcome",
        # ranked | clean | empty | skipped | warmup
        labelnames=("outcome",),
    )


def stream_dispatches() -> Counter:
    return get_registry().counter(
        "microrank_stream_dispatches_total",
        "Anomaly-GATED device rank dispatches in streaming mode (the "
        "detector runs on every window; graph build + device rank only "
        "on abnormal ones — this staying below the window counter IS "
        "the gate working)",
    )


def stream_late_spans() -> Counter:
    return get_registry().counter(
        "microrank_stream_late_spans_total",
        "Spans dropped for arriving past the watermark (older than "
        "every window they belong to, beyond allowed lateness)",
    )


def stream_incidents() -> Counter:
    return get_registry().counter(
        "microrank_stream_incidents_total",
        "Incident lifecycle transitions",
        labelnames=("transition",),  # open | update | resolve | suppressed
    )


def stream_open_incidents() -> Gauge:
    return get_registry().gauge(
        "microrank_stream_open_incidents",
        "Incidents currently open in the streaming engine",
    )


def dispatch_routes() -> Counter:
    return get_registry().counter(
        "microrank_dispatch_route_total",
        "Device dispatches issued by the adaptive router, by route "
        "(vmapped = single-device batched program, sharded = mesh "
        "shard_map program)",
        labelnames=("route",),  # vmapped | sharded
    )


def dispatch_windows() -> Histogram:
    return get_registry().histogram(
        "microrank_dispatch_windows",
        "Windows per router dispatch, by route (stream burst coalescing "
        "and serve micro-batching both land here; mass at 1 under "
        "bursty load means buckets never match)",
        labelnames=("route",),
        buckets=(1, 2, 3, 4, 6, 8, 12, 16, 24, 32),
    )


def dispatch_overlap_seconds() -> Counter:
    return get_registry().counter(
        "microrank_dispatch_overlap_seconds_total",
        "Staging seconds (host blob pack + H2D transfer) the router "
        "overlapped with an in-flight device dispatch — staging time "
        "taken OFF the critical path by double-buffering",
    )


def compile_cache_events() -> Counter:
    return get_registry().counter(
        "microrank_compile_cache_events_total",
        "Persistent-compile-cache events: hit/miss per observed "
        "compile (cache dir entry count unchanged/grew), warm_start "
        "when a warmup manifest from a previous process was found and "
        "replayed, manifest_write per manifest update",
        labelnames=("event",),  # hit | miss | warm_start | manifest_write
    )


def build_pool_inflight() -> Gauge:
    return get_registry().gauge(
        "microrank_build_pool_inflight",
        "Host graph builds currently running on build-pool workers "
        "(stream engine + serve scheduler share the pool seam)",
    )


def build_pool_builds() -> Counter:
    return get_registry().counter(
        "microrank_build_pool_builds_total",
        "Host graph builds completed on build-pool workers",
    )


def spans_recorded() -> Counter:
    return get_registry().counter(
        "microrank_spans_recorded_total",
        "Pipeline self-tracing spans recorded into the bounded ring "
        "(obs.spans; the flight recorder dumps the ring on incident "
        "open / degraded dispatch / SIGTERM)",
    )


def flight_dumps() -> Counter:
    return get_registry().counter(
        "microrank_flight_dumps_total",
        "Flight-recorder dumps written to out_dir/flight/, by trigger "
        '(reason="suppressed" counts dumps the min-interval rate limit '
        "swallowed)",
        labelnames=("reason",),  # incident | degraded | sigterm | ...
    )


def device_hbm_bytes() -> Gauge:
    return get_registry().gauge(
        "microrank_device_hbm_bytes",
        "Device memory at the last sampled dispatch "
        "(Device.memory_stats; unset on backends without stats)",
        labelnames=("kind",),  # live | peak
    )


def kernel_ms_per_iter() -> Gauge:
    return get_registry().gauge(
        "microrank_kernel_ms_per_iter",
        "Per-iteration device time of the power-iteration kernel, "
        "measured by trip-count differencing (bench.py "
        "_profile_device_time — the loop body isolated from the RPC "
        "floor)",
        labelnames=("kernel",),
    )


def kind_dedup_gauge() -> Gauge:
    return get_registry().gauge(
        "microrank_kind_dedup_ratio",
        "Trace-kind dedup factor of the most recent built window (true "
        "traces / distinct kind columns, both partitions; 1.0 on an "
        "uncollapsed build) — the measured signal behind the "
        "kernel='kind' auto-select threshold "
        "(RuntimeConfig.kind_dedup_threshold)",
    )


def record_kind_dedup(ratio: float) -> None:
    """Per-window dedup-factor telemetry (host side, at graph build)."""
    kind_dedup_gauge().set(float(ratio))


def profile_sessions() -> Counter:
    return get_registry().counter(
        "microrank_profile_sessions_total",
        "jax.profiler trace sessions captured, by trigger",
        labelnames=("trigger",),  # endpoint | every_n
    )


def explain_bundles() -> Counter:
    return get_registry().counter(
        "microrank_explain_bundles_total",
        "Explain bundles materialized (rank provenance: per-suspect "
        "counter decomposition + contributing traces), by trigger",
        labelnames=("trigger",),  # incident | request | cli | on_demand
    )


def mrsan_checks() -> Counter:
    return get_registry().counter(
        "microrank_mrsan_checks_total",
        "mrsan device-ownership seam checks performed while the "
        "runtime sanitizers were armed (RuntimeConfig.sanitizers) — a "
        "clean run with zero here means the sanitizer never looked",
        labelnames=("seam",),
    )


def mrsan_violations() -> Counter:
    return get_registry().counter(
        "microrank_mrsan_violations_total",
        "mrsan runtime violations: cross-thread-device (a jax seam "
        "entered off the owner thread — mrlint R8's runtime twin), "
        "collective-divergence (per-shard collective multisets "
        "diverged on the mesh — R9's), shared-state-race (a "
        "registered object's candidate lockset emptied — R10's), "
        "lock-order (an armed acquire closed a cycle in the observed "
        "acquisition DAG — R11's), or compile-witness (a jit compile "
        "key outside the statically predicted key space — R13-R16's)",
        labelnames=("kind",),
    )


def mrsan_collectives() -> Counter:
    return get_registry().counter(
        "microrank_mrsan_collectives_total",
        "Mesh collectives observed by the mrsan interposition at "
        "runtime, summed over shards",
        labelnames=("op",),
    )


def mrsan_lockset_checks() -> Counter:
    return get_registry().counter(
        "microrank_mrsan_lockset_checks_total",
        "mrsan Eraser-style lockset validations on registered shared "
        "objects (utils.guards.note_shared_access) while the runtime "
        "sanitizers were armed — mrlint R10's runtime twin; a clean "
        "run with zero here means the checker never looked",
        labelnames=("object",),
    )


def retry_attempts() -> Counter:
    return get_registry().counter(
        "microrank_retry_attempts_total",
        "Retry attempts (second and later tries) through the unified "
        "retry policy (chaos.retry), by seam — a healthy seam exposes "
        "this at zero",
        labelnames=("seam",),
    )


def retry_exhausted() -> Counter:
    return get_registry().counter(
        "microrank_retry_exhausted_total",
        "Retried calls that gave up after the policy's max attempts, "
        "by seam (the caller's containment/degradation path took over)",
        labelnames=("seam",),
    )


def breaker_state() -> Gauge:
    return get_registry().gauge(
        "microrank_breaker_state",
        "Circuit breaker state per retried seam: 0=closed, 1=open "
        "(fast-failing), 2=half-open (probing)",
        labelnames=("seam",),
    )


def fault_injections() -> Counter:
    return get_registry().counter(
        "microrank_fault_injections_total",
        "Faults injected by the chaos harness (chaos.faults: a seeded "
        "FaultPlan or a legacy inject_* knob), by seam and kind — "
        "nonzero only when chaos is armed",
        labelnames=("seam", "kind"),
    )


def webhook_dropped() -> Counter:
    return get_registry().counter(
        "microrank_webhook_dropped_total",
        "Incident webhook events dropped after exhausting the sink's "
        "bounded retry queue (max attempts reached or queue overflow)",
    )


def checkpoint_events() -> Counter:
    return get_registry().counter(
        "microrank_checkpoint_events_total",
        "Engine state-checkpoint events: write per durable state.ckpt, "
        "restore on a successful --resume, rejected when a corrupt/"
        "incompatible checkpoint was refused (cold start), "
        "crash_injected when the chaos seam killed a write between tmp "
        "and rename (the previous checkpoint survives)",
        labelnames=("event",),  # write | restore | rejected | crash_injected
    )


def policy_events() -> Counter:
    return get_registry().counter(
        "microrank_policy_events_total",
        "Tuned-policy resolutions (scenarios.policy): applied when a "
        "persisted policy.json supplied at least one field, override "
        "when explicit config won every tuned field, default when no "
        "policy file exists, rejected when a stale/mismatched policy "
        "was refused WHOLE (cold start on built-in defaults), disabled "
        "under tuned_policy=off; one sample per lane startup",
        labelnames=("lane", "outcome"),
    )


def fleet_heartbeats() -> Counter:
    return get_registry().counter(
        "microrank_fleet_heartbeats_total",
        "Worker heartbeats received by the fleet coordinator",
        labelnames=("host",),
    )


def fleet_reports() -> Counter:
    return get_registry().counter(
        "microrank_fleet_reports_total",
        "Per-window worker reports by disposition: accepted into a "
        "seal slot, duplicate (same host re-reported a pending window "
        "— the resume-rejoin dedup), late (window already sealed), "
        "buffered (worker-side park while the coordinator was "
        "unreachable), dropped (worker buffer overflow)",
        labelnames=("status",),
    )


def fleet_workers_gauge() -> Gauge:
    return get_registry().gauge(
        "microrank_fleet_workers",
        "Fleet membership by worker state (lease-derived)",
        labelnames=("state",),  # alive | dead | done
    )


def fleet_reassignments() -> Counter:
    return get_registry().counter(
        "microrank_fleet_reassignments_total",
        "Source-partition moves between workers (lease expiry takes a "
        "dead host's partitions to survivors; a rejoin rebalances "
        "them back)",
    )


def fleet_sealed_windows() -> Counter:
    return get_registry().counter(
        "microrank_fleet_sealed_windows_total",
        "Windows sealed at the fleet watermark, by merged outcome",
        labelnames=("outcome",),  # ranked | healthy
    )


def fleet_host_spans_rate() -> Gauge:
    return get_registry().gauge(
        "microrank_fleet_host_spans_per_second",
        "Per-host ingest throughput from the last heartbeat "
        "(spans processed / worker uptime)",
        labelnames=("host",),
    )


def fleet_host_watermark_lag() -> Gauge:
    return get_registry().gauge(
        "microrank_fleet_host_watermark_lag_seconds",
        "Per-host event-time lag behind the fleet's furthest-ahead "
        "reporter (the host holding the fleet watermark back reads "
        "largest)",
        labelnames=("host",),
    )


def fleet_host_queue_depth() -> Gauge:
    return get_registry().gauge(
        "microrank_fleet_host_queue_depth",
        "Per-host pipelined windows in flight (build submitted, rank "
        "pending) from the last heartbeat",
        labelnames=("host",),
    )


def fleet_host_stage_ms() -> Gauge:
    return get_registry().gauge(
        "microrank_fleet_host_stage_ms",
        "Per-host mean stage latency (ms) over the last heartbeat's "
        "metrics delta — the recent cost signal, not the run-cumulative "
        "mean",
        labelnames=("host", "stage"),
    )


def fleet_metric_deltas() -> Counter:
    return get_registry().counter(
        "microrank_fleet_metric_deltas_total",
        "Heartbeat metrics deltas by disposition: applied into the "
        "fleet registry, stale (already-folded seq retransmit), torn "
        "(CRC mismatch), version (schema mismatch), ahead "
        "(out-of-sync seq — worker told to resync), truncated "
        "(worker dropped metrics to fit the byte bound), rejected "
        "(malformed payload)",
        labelnames=("status",),
    )


def fleet_series_dropped() -> Counter:
    return get_registry().counter(
        "microrank_fleet_series_dropped_total",
        "Host-labeled series refused by the fleet registry's "
        "cardinality cap (expected_hosts + grace) instead of growing "
        "without bound",
    )


def watchdog_evals() -> Counter:
    return get_registry().counter(
        "microrank_watchdog_evals_total",
        "SLO self-watchdog burn-rate evaluations over the fleet "
        "registry",
    )


def watchdog_breaches() -> Counter:
    return get_registry().counter(
        "microrank_watchdog_breaches_total",
        "Watchdog evals where a golden signal burned past threshold "
        "in BOTH the fast and the slow window, by signal",
        labelnames=("signal",),
    )


def watchdog_burn() -> Gauge:
    return get_registry().gauge(
        "microrank_watchdog_burn_rate",
        "Last evaluated burn rate per golden signal (1.0 = consuming "
        "the error budget exactly at the sustainable rate)",
        labelnames=("signal", "window"),  # window: fast | slow
    )


def ingest_rejected() -> Counter:
    return get_registry().counter(
        "microrank_ingest_rejected_total",
        "Span rows refused by admission (ingest/), by reason — every "
        "counted row also lands exactly once in the dead-letter store "
        "(quarantine.jsonl) with the same reason",
        labelnames=("reason",),  # ingest.quarantine.REASONS
    )


def ingest_admitted() -> Counter:
    return get_registry().counter(
        "microrank_ingest_admitted_total",
        "Span rows admitted past the ingest validation ladder "
        "(the clean subset detect/build actually sees)",
    )


def ingest_clamped() -> Counter:
    return get_registry().counter(
        "microrank_ingest_clamped_total",
        "Rows NORMALIZED (kept) by admission rather than rejected: "
        "clock_skew = timestamps clamped to the window-relative bound, "
        "orphan_stitched = broken parent links cleared (span becomes a "
        "trace root)",
        labelnames=("kind",),  # clock_skew | orphan_stitched
    )


def ingest_quarantine_dropped() -> Counter:
    return get_registry().counter(
        "microrank_ingest_quarantine_dropped_total",
        "Dead-letter records dropped because quarantine.jsonl reached "
        "its byte cap (IngestConfig.quarantine_max_bytes) — hostile "
        "data must not become a disk-filling attack",
    )


def ingest_window_ops() -> Gauge:
    return get_registry().gauge(
        "microrank_ingest_window_ops",
        "Distinct operations in the most recently admitted window "
        "(post-budget: bounded by IngestConfig.max_ops_per_window — "
        "the vocab-growth guard's observable)",
    )


def host_load_gauge() -> Gauge:
    return get_registry().gauge(
        "microrank_host_norm_load",
        "1-minute load average / CPU count at the last sample",
    )


def warehouse_segments() -> Counter:
    return get_registry().counter(
        "microrank_warehouse_segments_total",
        "Warehouse segments sealed, by tier (warm = one window per "
        "segment at flush, cold = compacted multi-window)",
        labelnames=("tier",),
    )


def warehouse_windows() -> Counter:
    return get_registry().counter(
        "microrank_warehouse_windows_total",
        "Window records sealed into warehouse segments, by tier "
        "(a window counts once per tier it transits)",
        labelnames=("tier",),
    )


def warehouse_spans() -> Counter:
    return get_registry().counter(
        "microrank_warehouse_spans_total",
        "Span rows sealed into WARM warehouse segments (the at-rest "
        "copy of every admitted span; compaction does not re-count)",
    )


def warehouse_bytes() -> Counter:
    return get_registry().counter(
        "microrank_warehouse_bytes_total",
        "Compressed segment bytes written, by tier — against "
        "ingest-side volume this is the at-rest compression observable",
        labelnames=("tier",),
    )


def warehouse_replays() -> Counter:
    return get_registry().counter(
        "microrank_warehouse_replays_total",
        "Time-travel replay verdicts per stored window: match = the "
        "re-ranked top-k tie-aware-agrees with the stored verdict",
        labelnames=("verdict",),  # match | mismatch
    )


def host_steal_gauge() -> Gauge:
    return get_registry().gauge(
        "microrank_host_steal_ratio",
        "CPU steal fraction over the last sample interval",
    )


def sched_dispatches() -> Counter:
    return get_registry().counter(
        "microrank_sched_dispatch_windows_total",
        "Windows dispatched by the unified device scheduler, by "
        "priority lane and tenant — the fair-share observable: "
        "per-tenant rates under sustained contention converge to "
        "SchedConfig.tenant_weights",
        labelnames=("lane", "tenant"),
    )


def sched_parked() -> Gauge:
    return get_registry().gauge(
        "microrank_sched_parked_windows",
        "Entries currently parked in the shared window store, by lane "
        "(incident | serve | backfill)",
        labelnames=("lane",),
    )


def sched_expired() -> Counter:
    return get_registry().counter(
        "microrank_sched_expired_total",
        "Parked entries whose deadline lapsed before dequeue — the "
        "scheduler answered them (504) instead of burning device time "
        "on an abandoned request",
    )


def sched_throttled() -> Counter:
    return get_registry().counter(
        "microrank_sched_throttled_total",
        "Batches dispatched while their tenant's token bucket was "
        "empty (quotas are soft: the batch still ran because nothing "
        "in-quota was ready — work-conserving by design)",
        labelnames=("tenant",),
    )


def sched_wait_seconds() -> Histogram:
    return get_registry().histogram(
        "microrank_sched_wait_seconds",
        "Seconds a batch's oldest entry sat parked before dispatch, "
        "by lane — incident staying at the low buckets while backfill "
        "absorbs the queueing IS the priority policy working",
        labelnames=("lane",),
        buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                 1.0, 2.5, 5.0),
    )


def warm_shapes() -> Counter:
    return get_registry().counter(
        "microrank_warm_shapes_total",
        "Shape-faithful warmup replays of recorded production pad "
        "buckets at startup (warmed = program traced/reloaded, "
        "skipped = recorded signature no longer matches this build, "
        "failed = dispatch raised)",
        labelnames=("outcome",),  # warmed | skipped | failed
    )


def build_routes() -> Counter:
    return get_registry().counter(
        "microrank_build_route_total",
        "Window graph builds by route: delta = assembled incrementally "
        "from the previous window's per-trace caches (O(changed "
        "traces)), cold = full rebuild (first window, churn past the "
        "threshold, unseen op names, pad-bucket shift, or an integrity "
        "checksum mismatch)",
        labelnames=("route",),  # delta | cold
    )


def ensure_catalog() -> None:
    """Register the whole canonical metric set in the current registry
    (no samples added). Snapshot/exposition paths call this so a scrape
    or `cli stats` always shows the full catalog — a retrace counter at
    its HELP/TYPE header with no growth is itself information."""
    for ctor in (
        stage_seconds, windows_total, rank_iterations,
        rank_final_residual, staged_bytes, staged_pad_bytes,
        staging_transfers, jit_retraces, jit_cache_misses,
        pipeline_inflight,
        follow_polls, follow_parse_failures, follow_rotations,
        serve_requests, serve_queue_depth, serve_batch_windows,
        serve_last_batch_gauge, serve_degraded, serve_stage_seconds,
        stream_windows, stream_dispatches, stream_late_spans,
        stream_incidents, stream_open_incidents,
        dispatch_routes, dispatch_windows, dispatch_overlap_seconds,
        compile_cache_events,
        build_pool_inflight, build_pool_builds,
        spans_recorded, flight_dumps, device_hbm_bytes,
        kernel_ms_per_iter, profile_sessions, explain_bundles,
        mrsan_checks, mrsan_violations, mrsan_collectives,
        mrsan_lockset_checks,
        retry_attempts, retry_exhausted, breaker_state,
        fault_injections, webhook_dropped, checkpoint_events,
        policy_events,
        fleet_heartbeats, fleet_reports, fleet_workers_gauge,
        fleet_reassignments, fleet_sealed_windows, fleet_host_spans_rate,
        fleet_host_watermark_lag, fleet_host_queue_depth,
        fleet_host_stage_ms, fleet_metric_deltas, fleet_series_dropped,
        watchdog_evals, watchdog_breaches, watchdog_burn,
        ingest_rejected, ingest_admitted, ingest_clamped,
        ingest_quarantine_dropped, ingest_window_ops,
        host_load_gauge, host_steal_gauge,
        warehouse_segments, warehouse_windows, warehouse_spans,
        warehouse_bytes, warehouse_replays,
        sched_dispatches, sched_parked, sched_expired,
        sched_throttled, sched_wait_seconds, warm_shapes,
        build_routes,
    ):
        ctor()


# ---------------------------------------------------------------------------
# Recording helpers


def record_window_outcome(outcome: str) -> None:
    windows_total().inc(outcome=outcome)


def record_convergence(
    kernel: str, n_iters: int, final_residual: float
) -> None:
    """Per-window convergence telemetry (host side, post-fetch)."""
    rank_iterations().observe(float(n_iters), kernel=kernel)
    if np.isfinite(final_residual):
        rank_final_residual().observe(float(final_residual), kernel=kernel)


def record_serve_request(outcome: str, total_seconds: float = None) -> None:
    serve_requests().inc(outcome=outcome)
    if total_seconds is not None:
        serve_stage_seconds().observe(float(total_seconds), stage="total")


def record_serve_batch(occupancy: int, degraded: int = 0) -> None:
    serve_batch_windows().observe(float(occupancy))
    serve_last_batch_gauge().set(float(occupancy))
    if degraded:
        serve_degraded().inc(float(degraded))


def record_stream_window(outcome: str) -> None:
    stream_windows().inc(outcome=outcome)


def record_stream_dispatch() -> None:
    stream_dispatches().inc()


def record_incident(transition: str, open_now: int = None) -> None:
    stream_incidents().inc(transition=transition)
    if open_now is not None:
        stream_open_incidents().set(float(open_now))


def record_dispatch_route(
    route: str, windows: int, overlap_seconds: float = 0.0
) -> None:
    """One router dispatch: route taken, windows it carried, staging
    seconds double-buffered behind it."""
    dispatch_routes().inc(route=route)
    dispatch_windows().observe(float(windows), route=route)
    if overlap_seconds > 0:
        dispatch_overlap_seconds().inc(float(overlap_seconds))


def record_build_route(route: str) -> None:
    """One window graph build: which build lane produced it."""
    build_routes().inc(route=route)


def record_compile_cache(event: str, n: int = 1) -> None:
    if n > 0:
        compile_cache_events().inc(float(n), event=event)


def record_sched_dispatch(lane: str, tenant: str, windows: int) -> None:
    sched_dispatches().inc(float(windows), lane=lane, tenant=tenant)


def record_sched_parked(lane: str, depth: int) -> None:
    sched_parked().set(float(depth), lane=lane)


def record_sched_expired(n: int = 1) -> None:
    if n > 0:
        sched_expired().inc(float(n))


def record_sched_throttled(tenant: str) -> None:
    sched_throttled().inc(tenant=tenant)


def record_sched_wait(lane: str, seconds: float) -> None:
    sched_wait_seconds().observe(float(seconds), lane=lane)


def record_warm_shape(outcome: str) -> None:
    warm_shapes().inc(outcome=outcome)


def record_build_pool(
    inflight: int = None, build_seconds: float = None
) -> None:
    if inflight is not None:
        build_pool_inflight().set(float(inflight))
    if build_seconds is not None:
        build_pool_builds().inc()
        stage_seconds().observe(float(build_seconds), stage="build_pool")


def record_flight_dump(reason: str) -> None:
    flight_dumps().inc(reason=reason)


def record_profile_session(trigger: str) -> None:
    profile_sessions().inc(trigger=trigger)


def record_explain(trigger: str) -> None:
    explain_bundles().inc(trigger=trigger)


def record_mrsan_check(seam: str) -> None:
    mrsan_checks().inc(seam=seam)


def record_mrsan_violation(kind: str, n: int = 1) -> None:
    mrsan_violations().inc(float(n), kind=kind)


def record_mrsan_collective(op: str, n: int = 1) -> None:
    mrsan_collectives().inc(float(n), op=op)


def record_mrsan_lockset_check(obj: str) -> None:
    mrsan_lockset_checks().inc(object=obj)


def record_jit_cache_miss(
    program: str,
    kernel: str = None,
    occupancy: int = None,
    key=None,
    predicted: bool = True,
) -> None:
    """One first-seen compile key at a dispatch seam (compile witness).

    Increments the per-program miss counter and journals the full key
    on the registered run journal, so a post-mortem can replay exactly
    which shapes compiled and whether the static model called them.
    """
    jit_cache_misses().inc(program=program)
    from .journal import emit_current

    emit_current(
        "jit_cache_miss",
        program=program,
        kernel=kernel,
        occupancy=occupancy,
        key=key,
        predicted=bool(predicted),
    )


def record_retry(seam: str) -> None:
    retry_attempts().inc(seam=seam)


def record_retry_exhausted(seam: str) -> None:
    retry_exhausted().inc(seam=seam)


def record_breaker_state(seam: str, state: float) -> None:
    breaker_state().set(float(state), seam=seam)


def record_fault_injection(seam: str, kind: str) -> None:
    fault_injections().inc(seam=seam, kind=kind)


def record_webhook_dropped(n: int = 1) -> None:
    webhook_dropped().inc(float(n))


def record_checkpoint(event: str) -> None:
    checkpoint_events().inc(event=event)


def record_policy_event(outcome: str, lane: str) -> None:
    policy_events().inc(lane=lane, outcome=outcome)


def record_fleet_heartbeat(host: str) -> None:
    fleet_heartbeats().inc(host=host)


def record_fleet_report(status: str) -> None:
    fleet_reports().inc(status=status)


def record_fleet_workers(alive: int = 0, dead: int = 0, done: int = 0,
                         **extra) -> None:
    g = fleet_workers_gauge()
    g.set(float(alive), state="alive")
    g.set(float(dead), state="dead")
    g.set(float(done), state="done")


def record_fleet_reassignment(n: int = 1) -> None:
    fleet_reassignments().inc(float(n))


def record_fleet_sealed(outcome: str) -> None:
    fleet_sealed_windows().inc(outcome=outcome)


def record_fleet_host_rate(host: str, spans_per_second: float) -> None:
    fleet_host_spans_rate().set(float(spans_per_second), host=host)


def record_fleet_host_lag(host: str, lag_seconds: float) -> None:
    fleet_host_watermark_lag().set(max(0.0, float(lag_seconds)), host=host)


def record_fleet_host_queue(host: str, depth: float) -> None:
    fleet_host_queue_depth().set(max(0.0, float(depth)), host=host)


def record_fleet_host_stage(host: str, stage: str, ms: float) -> None:
    fleet_host_stage_ms().set(max(0.0, float(ms)), host=host, stage=stage)


def record_fleet_delta(status: str) -> None:
    fleet_metric_deltas().inc(status=status)


def record_fleet_series_dropped(n: int = 1) -> None:
    fleet_series_dropped().inc(float(n))


def record_watchdog_eval() -> None:
    watchdog_evals().inc()


def record_watchdog_breach(signal: str) -> None:
    watchdog_breaches().inc(signal=signal)


def record_watchdog_burn(signal: str, window: str, burn: float) -> None:
    watchdog_burn().set(float(burn), signal=signal, window=window)


def record_ingest_rejected(reason: str, n: int = 1) -> None:
    ingest_rejected().inc(float(n), reason=reason)


def record_ingest_admitted(n: int) -> None:
    if n > 0:
        ingest_admitted().inc(float(n))


def record_ingest_clamped(kind: str, n: int = 1) -> None:
    if n > 0:
        ingest_clamped().inc(float(n), kind=kind)


def record_quarantine_dropped(n: int = 1) -> None:
    ingest_quarantine_dropped().inc(float(n))


def record_window_ops(n: int) -> None:
    ingest_window_ops().set(float(n))


def record_warehouse_seal(
    tier: str, windows: int, spans: int, nbytes: int
) -> None:
    warehouse_segments().inc(tier=tier)
    warehouse_windows().inc(float(windows), tier=tier)
    if tier == "warm":
        warehouse_spans().inc(float(spans))
    warehouse_bytes().inc(float(nbytes), tier=tier)


def record_warehouse_replay(verdict: str, n: int = 1) -> None:
    warehouse_replays().inc(float(n), verdict=verdict)


def record_kernel_ms_per_iter(kernel: str, ms: float) -> None:
    """Wire a trip-count-differencing profile (bench.py
    _profile_device_time) into the registry, so the measured per-iter
    device time of each kernel is scrapeable next to the counters."""
    kernel_ms_per_iter().set(float(ms), kernel=kernel)


def record_staging(
    path: str, n_bytes: int, n_transfers: int, pad_bytes: int = 0
) -> None:
    staged_bytes().inc(float(n_bytes), path=path)
    staging_transfers().inc(float(n_transfers), path=path)
    if pad_bytes > 0:
        staged_pad_bytes().inc(float(pad_bytes), path=path)


def graph_staging_stats(graph) -> Tuple[int, int]:
    """(total_bytes, est_pad_bytes) of a (possibly batched) WindowGraph.

    Padding waste is estimated from the dynamic extents each axis family
    carries (n_inc/n_ss/n_traces-or-n_cols/n_ops) against the padded
    shapes — entry/trace/op vectors scale by their live fraction; bitmap
    and indptr waste is folded in at the same last-axis ratio. An
    estimate, not an audit: it exists to make pad_policy overhead a
    counter instead of folklore.
    """
    total = 0
    pad = 0
    for part in (graph.normal, graph.abnormal):
        t_live = np.where(
            np.asarray(part.n_cols) >= 0, part.n_cols, part.n_traces
        ).astype(np.int64)
        n_inc = np.asarray(part.n_inc, dtype=np.int64)
        n_ss = np.asarray(part.n_ss, dtype=np.int64)
        n_ops = np.asarray(part.n_ops, dtype=np.int64)
        # field -> live extent along its LAST axis (bitmaps in bytes).
        live_of = {
            "inc_op": n_inc, "inc_trace": n_inc, "sr_val": n_inc,
            "rs_val": n_inc, "inc_trace_opmajor": n_inc,
            "sr_val_opmajor": n_inc,
            "ss_child": n_ss, "ss_parent": n_ss, "ss_val": n_ss,
            "inv_tracelen": t_live, "kind": t_live, "tracelen": t_live,
            "cov_bits": -(-t_live // 8), "ss_bits": -(-n_ops // 8),
            "inv_cov_dup": n_ops, "inv_outdeg": n_ops,
            "cov_unique": n_ops, "op_present": n_ops,
            "inc_indptr_op": n_ops, "inc_indptr_trace": t_live,
            "ss_indptr": n_ops,
        }
        pc_entry = {"pc_trace", "pc_sr_val", "pc_ell_op", "pc_ell_rs"}
        for f in part._fields:
            arr = np.asarray(getattr(part, f))
            total += arr.nbytes
            if f in pc_entry:
                # Binned tables / ELL slabs: n_inc live cells over the
                # whole 2-D table (each incidence entry appears once per
                # view); the rest is bin-skew padding.
                if arr.ndim >= 2 and arr.shape[-1] > 0:
                    cells = arr.shape[-2] * arr.shape[-1]
                    frac = float(
                        np.clip(1.0 - np.mean(n_inc) / cells, 0.0, 1.0)
                    )
                    pad += int(arr.nbytes * frac)
                continue
            live = live_of.get(f)
            if live is None or arr.ndim == 0 or arr.shape[-1] == 0:
                continue
            frac = float(
                np.clip(1.0 - np.mean(live) / arr.shape[-1], 0.0, 1.0)
            )
            pad += int(arr.nbytes * frac)
    return total, pad


def graph_staging_audit(graph) -> Tuple[int, int]:
    """(total_bytes, pad_bytes) of a (possibly batched) WindowGraph,
    AUDITED leaf by leaf against exact live extents — what the staging
    layer actually ships vs what the window actually needed.

    Unlike ``graph_staging_stats`` (the historical estimate, kept for
    comparison), no mean-live-fraction folding: each vector leaf's true
    size is the per-window sum of its clipped live extent, indptr leaves
    count their ``live+1`` offsets, and the 2-D bitmaps account BOTH
    axes (padded op rows beyond ``n_ops`` AND padded byte columns beyond
    ``ceil(live/8)`` — the row-axis waste the estimate never saw).
    Leaves ``device_subset`` stripped for the kernel have zero bytes and
    contribute nothing, so the counter reflects the staged reality.
    """
    scalars = {"n_ops", "n_traces", "n_inc", "n_ss", "n_cols"}
    total = 0
    pad = 0
    for part in (graph.normal, graph.abnormal):
        t_live = np.where(
            np.asarray(part.n_cols) >= 0, part.n_cols, part.n_traces
        )
        n_inc = np.atleast_1d(np.asarray(part.n_inc)).astype(np.int64)
        n_ss = np.atleast_1d(np.asarray(part.n_ss)).astype(np.int64)
        n_ops = np.atleast_1d(np.asarray(part.n_ops)).astype(np.int64)
        t_live = np.atleast_1d(np.asarray(t_live)).astype(np.int64)
        vec_live = {
            "inc_op": n_inc, "inc_trace": n_inc, "sr_val": n_inc,
            "rs_val": n_inc, "inc_trace_opmajor": n_inc,
            "sr_val_opmajor": n_inc,
            "ss_child": n_ss, "ss_parent": n_ss, "ss_val": n_ss,
            "inv_tracelen": t_live, "kind": t_live, "tracelen": t_live,
            "inv_cov_dup": n_ops, "inv_outdeg": n_ops,
            "cov_unique": n_ops, "op_present": n_ops,
            "inc_indptr_op": n_ops + 1,
            "inc_indptr_trace": t_live + 1,
            "ss_indptr": n_ops + 1,
        }
        bit_live = {
            "cov_bits": (n_ops, -(-t_live // 8)),
            "ss_bits": (n_ops, -(-n_ops // 8)),
        }
        pc_fields = {"pc_trace", "pc_sr_val", "pc_ell_op", "pc_ell_rs"}
        for f in part._fields:
            arr = np.asarray(getattr(part, f))
            total += arr.nbytes
            if f in scalars or arr.nbytes == 0:
                continue
            if f == "pc_blk_indptr":
                continue  # small dense offset table: all live
            if f in pc_fields:
                # Binned tables / ELL slabs: every live incidence entry
                # appears exactly once per view, so the live cell count
                # per window is n_inc; the rest is bin-skew padding.
                per_win = arr.shape[-2] * arr.shape[-1]
                b = arr.size // per_win
                if len(n_inc) in (1, b):
                    live_tot = int(
                        np.clip(
                            np.broadcast_to(n_inc, (b,)), 0, per_win
                        ).sum()
                    )
                    pad += arr.nbytes - live_tot * arr.itemsize
                continue
            if f in bit_live:
                rows_live, cols_live = bit_live[f]
                rows_pad, cols_pad = arr.shape[-2], arr.shape[-1]
                b = arr.size // (rows_pad * cols_pad)
                if len(rows_live) not in (1, b):
                    continue  # unrecognized stacking: skip, stay honest
                rl = np.broadcast_to(
                    np.clip(rows_live, 0, rows_pad), (b,)
                )
                cl = np.broadcast_to(
                    np.clip(cols_live, 0, cols_pad), (b,)
                )
                pad += arr.nbytes - int((rl * cl).sum()) * arr.itemsize
            else:
                live = vec_live.get(f)
                if live is None or arr.ndim == 0:
                    continue
                last = arr.shape[-1]
                rows = arr.size // last
                if len(live) not in (1, rows):
                    continue
                lv = np.broadcast_to(np.clip(live, 0, last), (rows,))
                pad += (rows * last - int(lv.sum())) * arr.itemsize
    return total, pad


_jit_cache_sizes: Dict[str, int] = {}


def record_retrace(program: str, jitted_fn) -> None:
    """Count jit cache growth for a module-level jitted entry point.

    Call AFTER a dispatch: if the wrapper's cache grew since the last
    observation, the call traced+compiled (or reloaded from the
    persistent cache) — either way, a new program shape. Counts the
    first compile too; a flat counter across a replay is the healthy
    signature, growth per window is the pad_policy="exact" storm.
    """
    counter = jit_retraces()  # register even when nothing grew — an
    # exposed zero IS the healthy signal
    size_fn = getattr(jitted_fn, "_cache_size", None)
    if size_fn is None:  # older jax without the introspection hook
        return
    try:
        size = int(size_fn())
    except Exception:
        return
    prev = _jit_cache_sizes.get(program, 0)
    if size > prev:
        counter.inc(float(size - prev), program=program)
    _jit_cache_sizes[program] = size


def snapshot_to_result_fields(registry=None) -> Dict[str, float]:
    """Small flat dict of headline telemetry (bench artifact embedding)."""
    reg = registry or get_registry()
    out: Dict[str, float] = {}
    retr = reg.get("microrank_jit_retraces_total")
    if retr is not None:
        out["jit_retraces"] = sum(
            s["value"] for s in retr.samples()
        )
    staged = reg.get("microrank_staged_bytes_total")
    if staged is not None:
        out["staged_bytes"] = sum(s["value"] for s in staged.samples())
    routes = reg.get("microrank_dispatch_route_total")
    if routes is not None:
        for s in routes.samples():
            out[f"route_{s['labels'].get('route', '?')}"] = s["value"]
    overlap = reg.get("microrank_dispatch_overlap_seconds_total")
    if overlap is not None:
        total = sum(s["value"] for s in overlap.samples())
        if total:
            out["overlap_ms"] = round(total * 1e3, 1)
    return out
