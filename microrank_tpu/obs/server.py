"""Optional HTTP metrics endpoint (``cli run --metrics-port N``).

A stdlib ``ThreadingHTTPServer`` on a daemon thread — no new
dependencies, nothing listening unless asked. Routes:

* ``/metrics``      — Prometheus text exposition (scrape target);
* ``/metrics.json`` — the JSON snapshot form;
* ``/healthz``      — liveness probe.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .registry import MetricsRegistry, get_registry

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _make_handler(registry: MetricsRegistry):
    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib API name)
            if self.path.split("?")[0] == "/metrics":
                body = registry.to_prometheus().encode()
                ctype = PROM_CONTENT_TYPE
            elif self.path.split("?")[0] == "/metrics.json":
                body = json.dumps(registry.to_json()).encode()
                ctype = "application/json"
            elif self.path.split("?")[0] == "/healthz":
                body = b"ok\n"
                ctype = "text/plain"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # scrapes are not log events
            pass

    return Handler


class MetricsServer:
    """Owns the listening socket + serving thread; ``close()`` to stop."""

    def __init__(self, port: int, registry: Optional[MetricsRegistry] = None,
                 host: str = "127.0.0.1"):
        if registry is None:
            from .metrics import ensure_catalog

            ensure_catalog()  # scrapes see the full catalog from poll 1
            registry = get_registry()
        self.httpd = ThreadingHTTPServer(
            (host, int(port)), _make_handler(registry)
        )
        self.port = self.httpd.server_address[1]  # resolved (port 0 = any)
        self._thread = threading.Thread(
            target=self.httpd.serve_forever,
            name="mr-metrics",
            daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


def start_metrics_server(
    port: int, registry: Optional[MetricsRegistry] = None
) -> MetricsServer:
    """Start serving the registry on ``port`` (0 picks a free port)."""
    return MetricsServer(port, registry)
