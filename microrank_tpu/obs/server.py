"""Optional HTTP metrics endpoint (``cli run --metrics-port N``).

A stdlib ``ThreadingHTTPServer`` on a daemon thread — no new
dependencies, nothing listening unless asked. Routes:

* ``/metrics``      — Prometheus text exposition (scrape target);
* ``/metrics.json`` — the JSON snapshot form;
* ``/healthz``      — liveness probe;
* ``/profilez``     — on-demand ``jax.profiler`` session
  (``?seconds=S``, default 1, capped at 30): captures a device profile
  under the server's profile directory and returns its path as JSON.
  One session at a time (409 while another runs); the capture blocks
  only the requesting handler thread, never the pipeline.
* ``/explainz``     — rank provenance (``?window=<start>``): the
  explain bundle of a recent window from the in-process store
  (``explain.store`` — pipelines publish bundles there on incident
  open / explain:true requests). Without ``window``, lists the stored
  window ids and returns the latest bundle.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .registry import MetricsRegistry, get_registry

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _make_handler(registry: MetricsRegistry, profile_dir=None):
    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib API name)
            route, _, query = self.path.partition("?")
            status = 200
            if route == "/metrics":
                body = registry.to_prometheus().encode()
                ctype = PROM_CONTENT_TYPE
            elif route == "/metrics.json":
                body = json.dumps(registry.to_json()).encode()
                ctype = "application/json"
            elif route == "/healthz":
                body = b"ok\n"
                ctype = "text/plain"
            elif route == "/profilez":
                status, body = self._profilez(query)
                ctype = "application/json"
            elif route == "/explainz":
                status, body = self._explainz(query)
                ctype = "application/json"
            else:
                self.send_error(404)
                return
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        @staticmethod
        def _explainz(query: str):
            from urllib.parse import parse_qs

            from ..explain.store import get_explain_store

            store = get_explain_store()
            window = parse_qs(query).get("window", [None])[0]
            if window is None:
                latest = store.latest()
                return 200, json.dumps(
                    {"windows": store.windows(), "latest": latest}
                ).encode()
            bundle = store.get(window)
            if bundle is None:
                return 404, json.dumps(
                    {
                        "error": f"no explain bundle for window "
                        f"{window!r}",
                        "windows": store.windows(),
                    }
                ).encode()
            return 200, json.dumps(bundle).encode()

        @staticmethod
        def _profilez(query: str):
            from urllib.parse import parse_qs

            from .profiler import capture_profile

            if profile_dir is None:
                return 404, json.dumps(
                    {"error": "no profile directory configured"}
                ).encode()
            try:
                seconds = float(
                    parse_qs(query).get("seconds", ["1.0"])[0]
                )
            except ValueError:
                return 400, b'{"error": "seconds must be a number"}'
            session = capture_profile(profile_dir, seconds)
            if session is None:
                return 409, json.dumps(
                    {"error": "another profile session is active "
                     "(or the profiler is unavailable)"}
                ).encode()
            return 200, json.dumps(
                {"session": session, "seconds": seconds}
            ).encode()

        def log_message(self, fmt, *args):  # scrapes are not log events
            pass

    return Handler


class MetricsServer:
    """Owns the listening socket + serving thread; ``close()`` to stop."""

    def __init__(self, port: int, registry: Optional[MetricsRegistry] = None,
                 host: str = "127.0.0.1", profile_dir=None):
        if registry is None:
            from .metrics import ensure_catalog

            ensure_catalog()  # scrapes see the full catalog from poll 1
            registry = get_registry()
        self.httpd = ThreadingHTTPServer(
            (host, int(port)), _make_handler(registry, profile_dir)
        )
        self.port = self.httpd.server_address[1]  # resolved (port 0 = any)
        self._thread = threading.Thread(
            target=self.httpd.serve_forever,
            name="mr-metrics",
            daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


def start_metrics_server(
    port: int,
    registry: Optional[MetricsRegistry] = None,
    profile_dir=None,
) -> MetricsServer:
    """Start serving the registry on ``port`` (0 picks a free port).
    ``profile_dir`` arms the ``/profilez`` on-demand device-profiler
    endpoint (sessions land under it)."""
    return MetricsServer(port, registry, profile_dir=profile_dir)
