"""Low-overhead, thread-safe metrics registry (SURVEY.md §5 observability).

The reference has zero self-instrumentation (its paper's Table 7
latencies were measured externally); before this module the pipeline's
only visibility was a per-window ``StageTimings`` dict and ad-hoc bench
prints. Here every subsystem records into process-global Counter/Gauge/
Histogram metrics, exposed two ways:

* Prometheus text exposition (``MetricsRegistry.to_prometheus``) — the
  format scrapers expect, served live by ``obs.server`` behind the CLI's
  ``--metrics-port`` and re-emitted offline by ``cli stats``;
* a JSON snapshot (``to_json``/``registry_from_json``) — written to the
  run's output directory so a finished run's metrics survive the
  process (``cli stats out_dir/`` round-trips it back to text form).

Design constraints (the pipeline pushes ~12-20M spans/s — telemetry must
cost <2% of replay throughput):

* one ``threading.Lock`` per metric, held only for a dict update — the
  async stage/fetch workers and the main thread record concurrently;
* label values are joined into a tuple key at call time; no string
  formatting happens until exposition;
* metric registration is idempotent (``registry.counter(name, ...)``
  returns the existing metric), so call sites just look up by name and
  hot paths can cache the handle.
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "get_registry",
    "set_registry",
    "registry_from_json",
    "diff_registries",
    "merge_registries",
]

# Latency-shaped default buckets (seconds): 100 us .. ~100 s.
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 100.0,
)

_LABEL_ESCAPES = {"\\": "\\\\", '"': '\\"', "\n": "\\n"}


def _escape_label(value: str) -> str:
    return "".join(_LABEL_ESCAPES.get(c, c) for c in str(value))


def _fmt(v: float) -> str:
    """Prometheus float formatting: integers bare, +Inf spelled out."""
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


class _Metric:
    """Shared label-keyed storage. Subclasses define the sample shape."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._values: Dict[Tuple[str, ...], object] = {}

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.labelnames}, "
                f"got {tuple(labels)}"
            )
        return tuple(str(labels[n]) for n in self.labelnames)

    def _label_str(self, key: Tuple[str, ...], extra: str = "") -> str:
        pairs = [
            f'{n}="{_escape_label(v)}"'
            for n, v in zip(self.labelnames, key)
        ]
        if extra:
            pairs.append(extra)
        return "{" + ",".join(pairs) + "}" if pairs else ""

    # -- serialization ---------------------------------------------------
    def samples(self) -> List[dict]:
        with self._lock:
            items = list(self._values.items())
        return [
            {"labels": dict(zip(self.labelnames, key)), "value": val}
            for key, val in items
        ]

    def to_json(self) -> dict:
        return {
            "type": self.kind,
            "help": self.help,
            "labelnames": list(self.labelnames),
            "samples": self.samples(),
        }

    def expose(self) -> List[str]:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.kind}",
        ]
        with self._lock:
            items = sorted(self._values.items())
        for key, val in items:
            lines.append(f"{self.name}{self._label_str(key)} {_fmt(val)}")
        return lines


class Counter(_Metric):
    """Monotonically increasing count (increments may be fractional)."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        with self._lock:
            return float(self._values.get(self._key(labels), 0.0))


class Gauge(_Metric):
    """A value that goes up and down (queue depth, load average)."""

    kind = "gauge"

    def set(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels: str) -> float:
        with self._lock:
            return float(self._values.get(self._key(labels), 0.0))


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics)."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ):
        super().__init__(name, help, labelnames)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = bs  # +Inf is implicit

    def observe(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        v = float(value)
        with self._lock:
            state = self._values.get(key)
            if state is None:
                state = self._values[key] = {
                    "counts": [0] * (len(self.buckets) + 1),
                    "sum": 0.0,
                    "count": 0,
                }
            # First bucket whose bound >= v (linear scan: bucket lists
            # are short and this stays allocation-free).
            i = len(self.buckets)
            for j, b in enumerate(self.buckets):
                if v <= b:
                    i = j
                    break
            state["counts"][i] += 1
            state["sum"] += v
            state["count"] += 1

    def snapshot(self, **labels: str) -> Optional[dict]:
        with self._lock:
            state = self._values.get(self._key(labels))
            if state is None:
                return None
            return {
                "counts": list(state["counts"]),
                "sum": state["sum"],
                "count": state["count"],
            }

    def samples(self) -> List[dict]:
        with self._lock:
            items = list(self._values.items())
        out = []
        for key, st in items:
            out.append(
                {
                    "labels": dict(zip(self.labelnames, key)),
                    "buckets": list(st["counts"]),
                    "sum": st["sum"],
                    "count": st["count"],
                }
            )
        return out

    def to_json(self) -> dict:
        d = super().to_json()
        d["bucket_bounds"] = list(self.buckets)
        return d

    def expose(self) -> List[str]:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} histogram",
        ]
        with self._lock:
            items = sorted(self._values.items())
        for key, st in items:
            cum = 0
            for bound, n in zip(
                list(self.buckets) + [math.inf], st["counts"]
            ):
                cum += n
                extra = f'le="{_fmt(bound)}"'
                lines.append(
                    f"{self.name}_bucket{self._label_str(key, extra)} {cum}"
                )
            lines.append(
                f"{self.name}_sum{self._label_str(key)} {_fmt(st['sum'])}"
            )
            lines.append(
                f"{self.name}_count{self._label_str(key)} {st['count']}"
            )
        return lines


class MetricsRegistry:
    """Process-global (or test-local) collection of named metrics."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _register(self, cls, name: str, help: str, labelnames, **kw):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls) or existing.labelnames != tuple(
                    labelnames
                ):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind} with labels {existing.labelnames}"
                    )
                return existing
            metric = cls(name, help, labelnames, **kw)
            self._metrics[name] = metric
            return metric

    def counter(
        self, name: str, help: str = "", labelnames: Sequence[str] = ()
    ) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(
        self, name: str, help: str = "", labelnames: Sequence[str] = ()
    ) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def histogram(
        self,
        name: str,
        help: str = "",
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._register(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return sorted(self._metrics.values(), key=lambda m: m.name)

    def clear(self) -> None:
        """Drop every metric (tests; a fresh run keeps its counters)."""
        with self._lock:
            self._metrics.clear()

    # -- exposition ------------------------------------------------------
    def to_prometheus(self) -> str:
        lines: List[str] = []
        for m in self.metrics():
            lines.extend(m.expose())
        return "\n".join(lines) + ("\n" if lines else "")

    def to_json(self) -> dict:
        return {
            "ts": time.time(),
            "metrics": {m.name: m.to_json() for m in self.metrics()},
        }

    def write_snapshot(self, out_dir) -> None:
        """Persist both exposition forms into a run's output directory
        (``metrics.json`` + ``metrics.prom``) for offline ``cli stats``.
        Atomic (tmp+fsync+rename): a SIGKILL mid-write must not leave a
        torn snapshot that poisons the next `cli stats`/warm start."""
        from pathlib import Path

        from ..utils.atomic import atomic_write_text

        out = Path(out_dir)
        out.mkdir(parents=True, exist_ok=True)
        atomic_write_text(
            out / "metrics.json", json.dumps(self.to_json(), indent=2)
        )
        atomic_write_text(out / "metrics.prom", self.to_prometheus())


def registry_from_json(data: dict) -> MetricsRegistry:
    """Rebuild a registry from a ``to_json`` snapshot (``cli stats``)."""
    reg = MetricsRegistry()
    for name, md in data.get("metrics", {}).items():
        labelnames = tuple(md.get("labelnames", ()))
        kind = md.get("type")
        if kind == "counter":
            c = reg.counter(name, md.get("help", ""), labelnames)
            for s in md.get("samples", ()):
                c.inc(float(s["value"]), **s.get("labels", {}))
        elif kind == "gauge":
            g = reg.gauge(name, md.get("help", ""), labelnames)
            for s in md.get("samples", ()):
                g.set(float(s["value"]), **s.get("labels", {}))
        elif kind == "histogram":
            h = reg.histogram(
                name,
                md.get("help", ""),
                labelnames,
                buckets=md.get("bucket_bounds", DEFAULT_BUCKETS),
            )
            for s in md.get("samples", ()):
                key = h._key(s.get("labels", {}))
                with h._lock:
                    h._values[key] = {
                        "counts": list(s["buckets"]),
                        "sum": float(s["sum"]),
                        "count": int(s["count"]),
                    }
        else:  # unknown kinds round-trip as gauges of their raw samples
            g = reg.gauge(name, md.get("help", ""), labelnames)
            for s in md.get("samples", ()):
                if isinstance(s.get("value"), (int, float)):
                    g.set(float(s["value"]), **s.get("labels", {}))
    return reg


def diff_registries(
    before: MetricsRegistry, after: MetricsRegistry
) -> MetricsRegistry:
    """``after - before`` as a new registry (``cli stats --diff``).

    Counters and histogram bucket counts/sums subtract per label set
    (clamped at zero — a counter that went DOWN means the process
    restarted between snapshots, and a negative "delta" would be
    noise, not information). Gauges are point-in-time readings, so the
    diff keeps the ``after`` value. Metrics present only in ``after``
    diff against zero; metrics that disappeared are dropped.
    """
    out = MetricsRegistry()
    for m in after.metrics():
        prev = before.get(m.name)
        prev_ok = prev is not None and type(prev) is type(m)
        if isinstance(m, Counter):
            c = out.counter(m.name, m.help, m.labelnames)
            for s in m.samples():
                base = prev.value(**s["labels"]) if prev_ok else 0.0
                c.inc(max(0.0, float(s["value"]) - base), **s["labels"])
        elif isinstance(m, Histogram):
            same_bounds = prev_ok and prev.buckets == m.buckets
            h = out.histogram(m.name, m.help, m.labelnames, m.buckets)
            for s in m.samples():
                p = prev.snapshot(**s["labels"]) if same_bounds else None
                if p is None:
                    p = {"counts": [0] * len(s["buckets"]), "sum": 0.0,
                         "count": 0}
                key = h._key(s["labels"])
                with h._lock:
                    h._values[key] = {
                        "counts": [
                            max(0, a - b)
                            for a, b in zip(s["buckets"], p["counts"])
                        ],
                        "sum": max(0.0, s["sum"] - p["sum"]),
                        "count": max(0, s["count"] - p["count"]),
                    }
        else:  # gauges (and unknown kinds): the after reading stands
            g = out.gauge(m.name, m.help, m.labelnames)
            for s in m.samples():
                if isinstance(s.get("value"), (int, float)):
                    g.set(float(s["value"]), **s["labels"])
    return out


def merge_registries(
    sources: Sequence[Tuple[str, MetricsRegistry]],
) -> MetricsRegistry:
    """Federate named per-host registries into one fleet registry
    (``diff_registries``'s sibling; the fleet plane's merge law, also
    ``cli stats --merge``).

    ``sources`` is an ordered ``(host_name, registry)`` sequence. The
    laws, chosen so merging K event-stream shards reproduces the
    single-registry run exactly:

    * **counters** sum per label set — increments are increments no
      matter which host recorded them;
    * **histograms** sum bucket-wise per label set (identical bucket
      bounds required — every host runs the same catalog; a source
      whose bounds differ is skipped rather than mis-binned);
    * **gauges** are point-in-time per-host readings that do NOT sum:
      each source's reading is kept under a prepended ``host`` label
      (last writer per (host, labels) wins in source order). A gauge
      already host-labeled (the coordinator's per-host breakdowns)
      keeps its shape, samples unioned.

    A source whose metric shape conflicts (same name, different kind or
    labelnames) is skipped for that metric: torn telemetry must never
    crash the merge.
    """
    out = MetricsRegistry()
    for host, reg in sources:
        for m in reg.metrics():
            try:
                if isinstance(m, Counter):
                    c = out.counter(m.name, m.help, m.labelnames)
                    for s in m.samples():
                        v = float(s["value"])
                        if v > 0:
                            c.inc(v, **s["labels"])
                elif isinstance(m, Histogram):
                    h = out.histogram(
                        m.name, m.help, m.labelnames, m.buckets
                    )
                    if h.buckets != m.buckets:
                        continue
                    for s in m.samples():
                        key = h._key(s["labels"])
                        with h._lock:
                            st = h._values.get(key)
                            if st is None:
                                st = h._values[key] = {
                                    "counts": [0] * len(s["buckets"]),
                                    "sum": 0.0,
                                    "count": 0,
                                }
                            st["counts"] = [
                                a + b
                                for a, b in zip(st["counts"], s["buckets"])
                            ]
                            st["sum"] += float(s["sum"])
                            st["count"] += int(s["count"])
                elif isinstance(m, Gauge):
                    if "host" in m.labelnames:
                        g = out.gauge(m.name, m.help, m.labelnames)
                        for s in m.samples():
                            g.set(float(s["value"]), **s["labels"])
                    else:
                        g = out.gauge(
                            m.name, m.help, ("host",) + m.labelnames
                        )
                        for s in m.samples():
                            g.set(
                                float(s["value"]),
                                host=host,
                                **s["labels"],
                            )
            except (ValueError, TypeError):
                # Shape conflict or torn sample: skip this source's
                # metric, keep merging the rest.
                continue
    return out


_default_lock = threading.Lock()
_default: Optional[MetricsRegistry] = None


def get_registry() -> MetricsRegistry:
    """The process-global registry every subsystem records into."""
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricsRegistry()
        return _default


def set_registry(registry: Optional[MetricsRegistry]) -> None:
    """Swap the process-global registry (tests install a fresh one)."""
    global _default
    with _default_lock:
        _default = registry
