"""The fleet telemetry plane: federated metrics, merged journal, one
cross-process trace.

PR 11's fleet tier was telemetry-blind: N workers each wrote their own
``metrics.prom`` / ``journal.jsonl`` / span ring and nothing aggregated
or correlated them. This module is the aggregation law plus the wire
protocol, in three pieces:

* **Delta protocol** (worker side: :class:`MetricsDeltaSender`): each
  heartbeat piggybacks a compact, versioned, CRC'd metrics delta —
  ``diff_registries`` of the live registry against the last
  coordinator-ACKED baseline, under a monotonic per-incarnation
  sequence number. The payload is IMMUTABLE until acked (a lost ack
  retransmits the same bytes), and the baseline advances by exactly
  what was sent, so increments that arrive between build and ack — or
  whole metrics dropped to fit the byte bound — ride the next delta.
  Exactly-once folding falls out: the coordinator applies seq == next,
  counts a retransmit of an applied seq as ``stale`` without folding,
  and answers an out-of-sync sender with a ``resync`` that restarts
  the exchange from a full snapshot (checkpoint rule: reject whole,
  never fold a suspect delta).

* **Fold + view** (coordinator side: :class:`FleetPlane`): per-host
  cumulative registries, folded under the cardinality cap
  (``expected_hosts + grace`` distinct hosts; overflow refused whole
  and counted), merged on demand with ``merge_registries`` into the
  fleet view served at ``GET /fleetz/metrics`` and snapshotted as the
  launcher's fleet ``metrics.{prom,json}``. At finalize each host's
  folded state is RECONCILED against its on-disk ``metrics.json``
  ledger — durable state wins, so the fleet totals equal the per-host
  ledger sums exactly.

* **Correlation** (:func:`write_fleet_journal`,
  :func:`write_fleet_trace`): per-host journals and flight-dump traces
  merge into ONE fleet journal / ONE Perfetto trace, per-host
  timestamps corrected by the heartbeat-RTT-estimated clock offset
  (``offset = worker_wall + rtt/2 - coordinator_recv``, EWMA'd and
  clamped — the ingest skew-repair math pointed at our own telemetry).
  Workers share the window trace id (``win-<start>``), so the merged
  trace shows worker build -> report -> coordinator seal -> merge ->
  incident as one causal chain across processes.
"""

from __future__ import annotations

import json
import time
import zlib
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils.logging import get_logger
from .registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    diff_registries,
    merge_registries,
    registry_from_json,
)

log = get_logger("microrank_tpu.obs.fleetplane")

DELTA_VERSION = 1
FLEET_JOURNAL_NAME = "fleet_journal.jsonl"
FLEET_TRACE_NAME = "fleet_trace.json"

__all__ = [
    "DELTA_VERSION",
    "FLEET_JOURNAL_NAME",
    "FLEET_TRACE_NAME",
    "FleetPlane",
    "MetricsDeltaSender",
    "fold_into",
    "histogram_quantile",
    "write_fleet_journal",
    "write_fleet_trace",
]


def _canonical(doc: dict) -> bytes:
    return json.dumps(doc, sort_keys=True, separators=(",", ":")).encode()


def delta_crc(metrics_doc: dict) -> int:
    """CRC32 over the canonical serialization of a delta's metrics doc
    (the torn-payload detector; JSON reordering is not a tear)."""
    return zlib.crc32(_canonical(metrics_doc)) & 0xFFFFFFFF


def fold_into(dst: MetricsRegistry, src: MetricsRegistry) -> None:
    """Accumulate ``src`` into ``dst`` in place: counters and histogram
    buckets ADD, gauges take ``src``'s reading per label set (a delta's
    gauge sample is the newest point-in-time reading). The worker-side
    baseline advance and the coordinator-side cum fold share this one
    law, which is what makes base + sent_delta == snapshot-at-build."""
    for m in src.metrics():
        try:
            if isinstance(m, Counter):
                c = dst.counter(m.name, m.help, m.labelnames)
                for s in m.samples():
                    v = float(s["value"])
                    if v > 0:
                        c.inc(v, **s["labels"])
            elif isinstance(m, Histogram):
                h = dst.histogram(m.name, m.help, m.labelnames, m.buckets)
                if h.buckets != m.buckets:
                    continue
                for s in m.samples():
                    key = h._key(s["labels"])
                    with h._lock:
                        st = h._values.get(key)
                        if st is None:
                            st = h._values[key] = {
                                "counts": [0] * len(s["buckets"]),
                                "sum": 0.0,
                                "count": 0,
                            }
                        st["counts"] = [
                            a + b
                            for a, b in zip(st["counts"], s["buckets"])
                        ]
                        st["sum"] += float(s["sum"])
                        st["count"] += int(s["count"])
            elif isinstance(m, Gauge):
                g = dst.gauge(m.name, m.help, m.labelnames)
                for s in m.samples():
                    g.set(float(s["value"]), **s["labels"])
        except (ValueError, TypeError):
            continue  # shape conflict: skip the metric, not the fold


def histogram_quantile(
    bounds: Sequence[float], counts: Sequence[int], q: float
) -> float:
    """Prometheus-style quantile estimate from per-bucket counts
    (NON-cumulative, overflow bucket last): linear interpolation inside
    the target bucket; the overflow bucket answers its lower bound (the
    largest claim the data supports). The merge property test uses this
    to check that federated histograms answer quantile queries within
    one bucket of the single-registry run."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    target = max(0.0, min(1.0, float(q))) * total
    cum = 0
    for i, n in enumerate(counts):
        if n <= 0:
            continue
        if cum + n >= target:
            if i >= len(bounds):  # overflow bucket
                return float(bounds[-1])
            lo = float(bounds[i - 1]) if i > 0 else 0.0
            hi = float(bounds[i])
            return lo + (hi - lo) * ((target - cum) / n)
        cum += n
    return float(bounds[-1])


# ---------------------------------------------------------------------------
# Worker side: the delta sender


def _prune_zero_deltas(doc: dict) -> None:
    """Drop zero-delta counter/histogram series (and then-empty
    metrics) from a delta document in place. Folding a zero is a
    no-op, so pruning changes nothing semantically — but it keeps the
    steady-state heartbeat small and, crucially, lets a truncated
    metric actually FIT the retry delta instead of riding alongside a
    payload-sized echo of unchanged series. Gauges are instantaneous
    readings and always ship."""
    metrics = doc.get("metrics", {})
    for name in list(metrics):
        m = metrics[name]
        kind = m.get("type")
        if kind == "counter":
            m["samples"] = [
                s for s in m.get("samples", ()) if float(s["value"]) != 0.0
            ]
        elif kind == "histogram":
            m["samples"] = [
                s for s in m.get("samples", ()) if int(s["count"]) != 0
            ]
        else:
            continue
        if not m["samples"]:
            del metrics[name]


class MetricsDeltaSender:
    """Builds the heartbeat's metrics-delta payload and advances the
    acked baseline. Single-threaded by design: only the heartbeat loop
    calls it (the registry it reads IS thread-safe)."""

    def __init__(self, host_id: str, max_bytes: int = 262144):
        self.host_id = host_id
        self.max_bytes = max(1024, int(max_bytes))
        # Per-incarnation epoch: a restarted worker starts a fresh
        # sequence space; the coordinator folds the new incarnation's
        # deltas on top of the old cum (counters keep growing across a
        # rejoin, exactly like the fleet's exactly-once window story).
        import os

        self.epoch = f"{os.getpid():x}-{int(time.time() * 1e3) & 0xFFFFFF:x}"
        self._base = MetricsRegistry()
        self._seq = 0
        self._pending: Optional[dict] = None
        self._sent: Optional[MetricsRegistry] = None
        self.truncated = 0

    def payload(self, registry: MetricsRegistry) -> dict:
        """The delta to piggyback on this heartbeat. While an earlier
        delta is unacked the SAME payload retransmits — never a
        recomputed one, so the coordinator's fold and our baseline
        advance agree on exactly which increments were delivered."""
        if self._pending is not None:
            return self._pending
        delta = diff_registries(self._base, registry)
        doc = delta.to_json()
        doc.pop("ts", None)
        _prune_zero_deltas(doc)
        dropped: List[str] = []
        body = _canonical(doc)
        while len(body) > self.max_bytes and doc["metrics"]:
            # Oversize: shed whole metrics, largest serialization
            # first. Their increments are NOT lost — the baseline only
            # advances by what this payload carries.
            name = max(
                doc["metrics"],
                key=lambda n: len(_canonical(doc["metrics"][n])),
            )
            dropped.append(name)
            del doc["metrics"][name]
            body = _canonical(doc)
        if dropped:
            self.truncated += len(dropped)
        self._sent = registry_from_json(doc)
        self._pending = {
            "v": DELTA_VERSION,
            "epoch": self.epoch,
            "seq": self._seq,
            "metrics": doc,
            "crc": delta_crc(doc),
            "truncated": len(dropped),
        }
        return self._pending

    def handle_ack(self, ack: Optional[dict]) -> None:
        if not isinstance(ack, dict):
            return
        if ack.get("resync"):
            # Coordinator lost our baseline: restart from a full
            # snapshot (empty base -> next delta carries the whole
            # cum; the coordinator REPLACES its cum when it lands).
            self._base = MetricsRegistry()
            self._seq = int(ack.get("ack", 0))
            self._pending = None
            self._sent = None
            return
        if self._pending is None:
            return
        if int(ack.get("ack", -1)) >= self._seq + 1:
            if self._sent is not None:
                fold_into(self._base, self._sent)
            self._seq += 1
            self._pending = None
            self._sent = None


# ---------------------------------------------------------------------------
# Coordinator side: the fold


class _HostPlane:
    __slots__ = (
        "epoch", "next_seq", "cum", "replace_next",
        "offset_s", "offset_init",
    )

    def __init__(self) -> None:
        self.epoch: Optional[str] = None
        self.next_seq = 0
        self.cum = MetricsRegistry()
        self.replace_next = False
        self.offset_s = 0.0
        self.offset_init = False


class FleetPlane:
    """Coordinator-side federated registry + clock-offset estimator."""

    def __init__(
        self,
        expected_hosts: int = 0,
        grace: int = 2,
        max_skew_seconds: float = 5.0,
    ):
        from ..utils.guards import TrackedLock, register_shared

        self.expected_hosts = max(0, int(expected_hosts))
        self.grace = max(0, int(grace))
        self.max_skew_seconds = max(0.0, float(max_skew_seconds))
        # HTTP handler threads (heartbeat/goodbye deltas) and the
        # finalize path funnel through one lock.
        self._lock = TrackedLock("fleet_plane")
        register_shared("fleet_plane", {"fleet_plane"})
        self._hosts: Dict[str, _HostPlane] = {}

    # ------------------------------------------------------------ deltas
    def _admit_locked(self, host: str) -> Optional[_HostPlane]:
        hp = self._hosts.get(host)
        if hp is None:
            cap = self.expected_hosts + self.grace
            if self.expected_hosts and len(self._hosts) >= cap:
                return None
            hp = self._hosts[host] = _HostPlane()
        return hp

    def ingest(self, host: str, payload: object) -> dict:
        """Fold one heartbeat delta; returns the ``metrics_ack`` dict
        for the heartbeat response. Rejections are WHOLE (a torn or
        out-of-order delta never half-poisons the fleet totals) and
        every disposition is counted."""
        from .metrics import (
            record_fleet_delta,
            record_fleet_host_stage,
            record_fleet_series_dropped,
        )
        from ..utils.guards import note_shared_access

        if not isinstance(payload, dict):
            record_fleet_delta("rejected")
            return {"ack": 0}
        with self._lock:
            note_shared_access("fleet_plane")
            hp = self._admit_locked(str(host))
            if hp is None:
                record_fleet_series_dropped()
                return {"ack": 0, "dropped": True}
            if int(payload.get("v", -1)) != DELTA_VERSION:
                record_fleet_delta("version")
                return {"ack": hp.next_seq}
            epoch = str(payload.get("epoch", ""))
            if hp.epoch != epoch:
                # New worker incarnation: fresh sequence space, same
                # cum (counters accumulate across a rejoin).
                hp.epoch = epoch
                hp.next_seq = 0
                hp.replace_next = False
            doc = payload.get("metrics")
            if not isinstance(doc, dict) or (
                delta_crc(doc) != int(payload.get("crc", -1))
            ):
                record_fleet_delta("torn")
                return {"ack": hp.next_seq}
            seq = int(payload.get("seq", -1))
            if seq < hp.next_seq:
                record_fleet_delta("stale")
                return {"ack": hp.next_seq}
            if seq > hp.next_seq:
                # We never acked what the sender thinks we did —
                # restart the exchange from a full snapshot.
                record_fleet_delta("ahead")
                hp.next_seq = 0
                hp.replace_next = True
                return {"ack": 0, "resync": True}
            delta = registry_from_json(doc)
            if hp.replace_next:
                hp.cum = MetricsRegistry()
                hp.replace_next = False
            fold_into(hp.cum, delta)
            hp.next_seq += 1
            record_fleet_delta("applied")
            if int(payload.get("truncated", 0)) > 0:
                record_fleet_delta("truncated")
            ack = {"ack": hp.next_seq}
        # Per-host recent stage cost, derived from the DELTA's
        # stage_seconds histogram (sum/count over just this beat's
        # observations — the cost signal ROADMAP item 3's placement
        # needs, not the run-diluted mean). Outside the plane lock:
        # plain registry writes.
        st = delta.get("microrank_stage_seconds")
        if isinstance(st, Histogram):
            for s in st.samples():
                if int(s["count"]) > 0:
                    record_fleet_host_stage(
                        str(host),
                        s["labels"].get("stage", ""),
                        1e3 * float(s["sum"]) / int(s["count"]),
                    )
        return ack

    # ------------------------------------------------------------- clocks
    def note_clock(
        self, host: str, wall: float, rtt: float, recv_wall: float
    ) -> None:
        """EWMA the host-clock offset estimate from one heartbeat:
        ``offset = worker_wall + rtt/2 - coordinator_recv`` (positive =
        the host's clock runs ahead of ours)."""
        from ..utils.guards import note_shared_access

        raw = float(wall) + float(rtt) / 2.0 - float(recv_wall)
        with self._lock:
            note_shared_access("fleet_plane")
            hp = self._admit_locked(str(host))
            if hp is None:
                return
            if not hp.offset_init:
                hp.offset_s, hp.offset_init = raw, True
            else:
                hp.offset_s += 0.3 * (raw - hp.offset_s)

    def offsets(self) -> Dict[str, float]:
        """Per-host clock offsets, clamped to the skew bound (the
        ingest skew-repair rule: correct what is plausibly skew, never
        chase an implausible clock)."""
        b = self.max_skew_seconds
        with self._lock:
            return {
                h: max(-b, min(b, hp.offset_s))
                for h, hp in self._hosts.items()
                if hp.offset_init
            }

    # -------------------------------------------------------------- views
    def fleet_view(
        self, extra: Sequence[Tuple[str, MetricsRegistry]] = ()
    ) -> MetricsRegistry:
        """The federated registry: coordinator-side sources first (its
        own process registry carries the fleet_* counters and per-host
        breakdown gauges), then each host's folded cum in name order."""
        with self._lock:
            hosts = sorted(self._hosts.items())
            sources = list(extra) + [(h, hp.cum) for h, hp in hosts]
        return merge_registries(sources)

    def reconcile(self, host: str, ledger: dict) -> None:
        """Replace a host's folded cum with its on-disk snapshot (the
        finalize path): the ledger a worker wrote at engine drain is
        the durable truth, and live deltas that raced the exit must
        not make the fleet totals disagree with the per-host sums."""
        from ..utils.guards import note_shared_access

        reg = registry_from_json(ledger)
        with self._lock:
            note_shared_access("fleet_plane")
            hp = self._admit_locked(str(host))
            if hp is not None:
                hp.cum = reg

    def host_names(self) -> List[str]:
        with self._lock:
            return sorted(self._hosts)


# ---------------------------------------------------------------------------
# Fleet journal + fleet trace (the finalize/incident correlation paths)


def _read_jsonl(path: Path) -> List[dict]:
    events = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except ValueError:
                    continue  # torn tail line: skip, keep the rest
    except OSError:
        return []
    return events


def write_fleet_journal(
    out_dir,
    host_dirs: Dict[str, Path],
    offsets: Dict[str, float],
) -> Optional[Path]:
    """Merge the coordinator journal and every host journal into one
    ``fleet_journal.jsonl`` ordered by clock-offset-corrected wall
    time. Each event gains a ``host`` field; corrected events carry
    the applied offset so the correction is auditable."""
    out = Path(out_dir)
    merged: List[dict] = []
    for e in _read_jsonl(out / "journal.jsonl"):
        merged.append({**e, "host": "coordinator"})
    for host, hdir in sorted(host_dirs.items()):
        off = float(offsets.get(host, 0.0))
        for e in _read_jsonl(Path(hdir) / "journal.jsonl"):
            ev = {**e, "host": host}
            if off and isinstance(e.get("ts"), (int, float)):
                ev["ts"] = float(e["ts"]) - off
                ev["clock_offset_s"] = round(off, 6)
            merged.append(ev)
    if not merged:
        return None
    merged.sort(key=lambda e: float(e.get("ts", 0.0)))
    path = out / FLEET_JOURNAL_NAME
    with open(path, "w") as f:
        for e in merged:
            f.write(json.dumps(e) + "\n")
    return path


def _latest_trace_dump(host_dir: Path) -> Optional[Path]:
    dumps = sorted((Path(host_dir) / "flight").glob("*/trace.json"))
    return dumps[-1] if dumps else None


def write_fleet_trace(
    out_dir,
    coordinator_spans,
    host_dirs: Dict[str, Path],
    offsets: Dict[str, float],
) -> Optional[Path]:
    """One Perfetto trace across processes: the coordinator's span ring
    as pid 1 plus each host's LATEST flight-dump trace re-pidded and
    clock-offset-corrected. Same-window spans share ``win-<start>``
    trace ids across hosts, so the merged dump shows worker
    build -> report -> seal -> merge -> incident as one causal chain."""
    from .flight import chrome_events

    events: List[dict] = chrome_events(
        list(coordinator_spans), pid=1, process_name="coordinator"
    )
    pid = 1
    for host, hdir in sorted(host_dirs.items()):
        trace_path = _latest_trace_dump(Path(hdir))
        if trace_path is None:
            continue
        try:
            doc = json.loads(trace_path.read_text())
        except (OSError, ValueError):
            continue
        pid += 1
        shift = int(float(offsets.get(host, 0.0)) * 1e6)
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": host},
            }
        )
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = pid
            if ev.get("ph") == "X" and shift:
                ev["ts"] = int(ev.get("ts", 0)) - shift
            events.append(ev)
    if not events:
        return None
    path = Path(out_dir) / FLEET_TRACE_NAME
    path.write_text(
        json.dumps({"traceEvents": events, "displayTimeUnit": "ms"})
    )
    return path
