"""Device-profiler hooks: sampled ``jax.profiler`` sessions + HBM gauges.

Three probes, all opt-in or free:

* ``DeviceProfiler`` — wrap every N-th router dispatch in a
  ``jax.profiler.trace`` session (``ObsConfig.profile_every_n``), so a
  long-running serve/stream process periodically leaves a real XLA
  profile on disk without anyone attaching a debugger;
* ``capture_profile`` — the ``GET /profilez?seconds=S`` handler's
  worker (obs.server): one on-demand session, serialized by a module
  lock (jax supports one active trace per process);
* ``record_device_memory`` — per-dispatch HBM live/peak byte gauges
  from ``Device.memory_stats()`` (present on TPU; None on CPU — the
  gauges just stay unset there).

Everything imports jax lazily and swallows platform gaps: a CPU test
run must never fail because its backend has no memory stats.
"""

from __future__ import annotations

import threading
import time
from pathlib import Path
from typing import Optional

from ..utils.logging import get_logger

log = get_logger("microrank_tpu.obs.profiler")

_profile_lock = threading.Lock()


def capture_profile(out_dir, seconds: float = 1.0) -> Optional[str]:
    """One on-demand ``jax.profiler`` session of ``seconds`` wall-clock,
    written under ``out_dir``. Returns the session directory, or None
    when another session is active or the profiler is unavailable."""
    from .metrics import record_profile_session

    if not _profile_lock.acquire(blocking=False):
        return None
    try:
        import jax

        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
        session = Path(out_dir) / f"profilez-{stamp}"
        session.mkdir(parents=True, exist_ok=True)
        jax.profiler.start_trace(str(session))
        try:
            time.sleep(max(0.05, min(float(seconds), 30.0)))
        finally:
            jax.profiler.stop_trace()
        record_profile_session("endpoint")
        log.info("profilez: %.2fs session -> %s", seconds, session)
        return str(session)
    except Exception as exc:  # noqa: BLE001 - a broken profiler must
        # never take down the metrics server answering the request.
        log.warning("profilez capture failed: %s", exc)
        return None
    finally:
        _profile_lock.release()


class DeviceProfiler:
    """Every-N-dispatches sampling: the router asks ``session()`` around
    each dispatch; every ``every_n``-th call wraps the dispatch in a
    ``jax.profiler.trace`` session under ``out_dir``."""

    def __init__(self, every_n: int, out_dir):
        self.every_n = max(0, int(every_n))
        self.out_dir = Path(out_dir)
        self._count = 0
        self.sessions = 0

    def session(self):
        """Context manager for one dispatch (no-op unless sampled)."""
        import contextlib

        self._count += 1
        if not self.every_n or self._count % self.every_n:
            return contextlib.nullcontext()
        return self._traced_session()

    def _traced_session(self):
        import contextlib

        profiler = self

        @contextlib.contextmanager
        def _cm():
            from .metrics import record_profile_session

            if not _profile_lock.acquire(blocking=False):
                yield  # a /profilez session is running; skip this sample
                return
            started = False
            try:
                import jax

                session = profiler.out_dir / f"dispatch-{profiler._count}"
                session.mkdir(parents=True, exist_ok=True)
                jax.profiler.start_trace(str(session))
                started = True
                profiler.sessions += 1
                record_profile_session("every_n")
                yield
            except Exception as exc:  # noqa: BLE001 - sampling must not
                # fail the dispatch it wraps.
                log.warning("dispatch profile session failed: %s", exc)
                if not started:
                    yield
            finally:
                if started:
                    try:
                        import jax

                        jax.profiler.stop_trace()
                    except Exception:  # noqa: BLE001 - already logged
                        pass
                _profile_lock.release()

        return _cm()


def record_device_memory() -> None:
    """Sample HBM live/peak bytes into the registry gauges (first
    addressable device — the one every single-device dispatch uses).
    A backend without memory stats (CPU) leaves the gauges unset."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
    except Exception:  # noqa: BLE001 - platform probe, never fatal
        return
    if not stats:
        return
    from .metrics import device_hbm_bytes

    live = stats.get("bytes_in_use")
    peak = stats.get("peak_bytes_in_use")
    if live is not None:
        device_hbm_bytes().set(float(live), kind="live")
    if peak is not None:
        device_hbm_bytes().set(float(peak), kind="peak")
