"""Accuracy evaluation harness: R@k and Exam Score over chaos cases.

The reference's headline numbers are localization accuracy, not latency
(paper Tables 4-6; BASELINE.md): R@k = fraction of faults whose root cause
appears in the top k, Exam Score = mean normalized inspection depth (how
far down the ranked list an operator must read). The reference repo has no
evaluation code at all — the paper's experiments were manual. This module
makes the experiment reproducible: generate N synthetic chaos cases
(single- or multi-fault), run the full detect -> partition -> rank
pipeline on each, score the rankings.

Multi-fault scoring follows the paper's dataset-B convention: each
injected fault is scored independently (R@k over faults, not cases).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from .config import MicroRankConfig, SpectrumConfig
from .detect import compute_slo, detect_numpy, detect_partition
from .graph import build_detect_batch
from .rank_backends import get_backend
from .testing import SyntheticConfig, generate_case
from .utils.logging import get_logger

log = get_logger("microrank_tpu.evaluation")

# ---------------------------------------------------------------------------
# Shared tie-aware ranking metrics
#
# Every ranked-list score in the repo (this module's R@k/Exam harness,
# bench.py's fault-hit checks, the scenario matrix) goes through these
# helpers, and every tie rule is the ONE comparator
# ``utils.ranking_compare.scores_tied`` — two suspects whose scores
# agree within rounding share the MINIMUM rank of their tie group (the
# tie-expanded-top-k convention the incident fingerprints and the
# oracle parity gates already use).

#: Tie tolerance for device-produced score lists. Tighter than the
#: cross-path parity gates' 1e-3 (those compare DIFFERENT compute
#: paths); within one fetched ranking only genuine float ties should
#: collapse.
DEFAULT_TIE_RTOL = 1e-6


def tie_aware_ranks(
    names, scores, rtol: float = DEFAULT_TIE_RTOL
) -> Dict[str, int]:
    """1-based tie-aware rank per name over one DESCENDING ranked list:
    members of a tie group (scores tied to the group head within
    ``rtol`` — head-anchored, so chained near-ties cannot drift a group
    downhill) all take the group's first position."""
    from .utils.ranking_compare import scores_tied

    ranks: Dict[str, int] = {}
    head = None
    group_rank = 1
    for i, (name, score) in enumerate(zip(names, scores)):
        s = float(score)
        if head is None or not scores_tied(s, head, rtol):
            group_rank = i + 1
            head = s
        ranks.setdefault(str(name), group_rank)
    return ranks


def rank_of_culprit(
    names, scores, culprit: str, rtol: float = DEFAULT_TIE_RTOL
) -> Optional[int]:
    """Tie-aware 1-based rank of ``culprit`` (None when unranked)."""
    return tie_aware_ranks(names, scores, rtol).get(str(culprit))


def topk_exact(
    names, scores, truth, k: int, rtol: float = DEFAULT_TIE_RTOL
) -> bool:
    """True when EVERY true culprit sits inside the tie-expanded top-k
    (tie-aware rank <= k). The multi-fault generalization of "fault
    top-1": with 2 culprits, top-2 exact means both are there."""
    truth = [str(t) for t in truth]
    if not truth:
        return False
    ranks = tie_aware_ranks(names, scores, rtol)
    return all(t in ranks and ranks[t] <= k for t in truth)


def reciprocal_rank(
    names, scores, truth, rtol: float = DEFAULT_TIE_RTOL
) -> float:
    """1 / best tie-aware rank over the culprit set (0.0 = none ranked)."""
    ranks = tie_aware_ranks(names, scores, rtol)
    found = [ranks[str(t)] for t in truth if str(t) in ranks]
    return 1.0 / min(found) if found else 0.0


def average_precision(
    names, scores, truth, rtol: float = DEFAULT_TIE_RTOL
) -> float:
    """AP of one ranked list against the culprit set, tie-aware: the
    i-th found culprit (ascending tie-aware rank r_i) contributes
    precision i / r_i; unranked culprits contribute 0; the mean runs
    over ALL |truth| culprits."""
    truth = [str(t) for t in truth]
    if not truth:
        return float("nan")
    ranks = tie_aware_ranks(names, scores, rtol)
    found = sorted(ranks[t] for t in truth if t in ranks)
    total = sum((i + 1) / r for i, r in enumerate(found))
    return total / len(truth)


def ranking_metrics(
    names,
    scores,
    truth,
    ks: Tuple[int, ...] = (1, 3, 5),
    rtol: float = DEFAULT_TIE_RTOL,
) -> Dict[str, object]:
    """The full per-window scorecard of one ranked list vs the true
    culprit SET: AP, reciprocal rank, tie-aware rank per culprit, and
    tie-expanded top-k exactness per k."""
    truth = [str(t) for t in truth]
    ranks = tie_aware_ranks(names, scores, rtol)
    return {
        "ap": average_precision(names, scores, truth, rtol),
        "rr": reciprocal_rank(names, scores, truth, rtol),
        "ranks": {t: ranks.get(t) for t in truth},
        "topk_exact": {
            int(k): topk_exact(names, scores, truth, int(k), rtol)
            for k in ks
        },
    }


@dataclass(frozen=True)
class EvalConfig:
    n_cases: int = 20
    n_operations: int = 30
    n_traces: int = 200
    n_pods: int = 1
    n_kinds: int = 24
    child_keep_prob: float = 0.6
    n_faults: int = 1
    fault_latency_ms: float = 2000.0
    # Target root-path overlap between injected faults (multi-fault
    # hardness control — testing.synthetic.path_overlap). None = the
    # unconstrained historical choice.
    fault_path_overlap: Optional[float] = None
    seed0: int = 1000
    # R@k columns. 2 is in by default since round 5: the paper's
    # two-fault headline is R@2 = 66% (Table 5, dataset B — BASELINE.md),
    # so the two-fault table compares cell-for-cell.
    ks: Tuple[int, ...] = (1, 2, 3, 5)


@dataclass
class CaseResult:
    seed: int
    faults: List[str]
    ranks: List[Optional[int]]  # 1-based rank per fault, None = not ranked
    n_ranked_ops: int
    detected: bool


@dataclass
class EvalReport:
    cases: List[CaseResult] = field(default_factory=list)
    recall_at: Dict[int, float] = field(default_factory=dict)
    # Mean NORMALIZED inspection depth, (rank-1)/candidates — scale-free
    # across topology sizes (this harness's native metric).
    exam_score: float = float("nan")
    # The paper's Exam Score (Tables 4-6): mean UNNORMALIZED inspection
    # count, rank-1 — "how many candidates an operator examines before
    # the root cause" (paper dataset A, Ochiai/Dstar2: 0.42). Unranked
    # faults count a full candidate scan either way.
    exam_score_paper: float = float("nan")
    detection_rate: float = float("nan")

    def summary(self) -> str:
        r = " ".join(
            f"R@{k}={v:.2%}" for k, v in sorted(self.recall_at.items())
        )
        return (
            f"{len(self.cases)} cases, detection {self.detection_rate:.2%}, "
            f"{r}, ExamScore={self.exam_score:.4f} "
            f"(paper form {self.exam_score_paper:.2f})"
        )


def _widen_spectrum(
    config: MicroRankConfig, eval_cfg: EvalConfig
) -> MicroRankConfig:
    """Full-depth rankings (top_max covers every op) so Exam Score is
    exact."""
    return config.replace(
        spectrum=SpectrumConfig(
            method=config.spectrum.method,
            top_max=eval_cfg.n_operations * max(1, eval_cfg.n_pods),
            extra_rows=config.spectrum.extra_rows,
            eps=config.spectrum.eps,
        )
    )


def _case_config(eval_cfg: EvalConfig, seed: int) -> SyntheticConfig:
    return SyntheticConfig(
        n_operations=eval_cfg.n_operations,
        n_pods=eval_cfg.n_pods,
        n_kinds=eval_cfg.n_kinds,
        child_keep_prob=eval_cfg.child_keep_prob,
        n_traces=eval_cfg.n_traces,
        fault_latency_ms=eval_cfg.fault_latency_ms,
        n_faults=eval_cfg.n_faults,
        fault_path_overlap=eval_cfg.fault_path_overlap,
        seed=seed,
    )


def _detect_partition(case, config: MicroRankConfig):
    """Shared detection + partitioning front half of every eval case
    (the production seam — ``detect.detect_partition`` — so error-
    status faults classify here exactly as they do on the serve/stream
    paths).

    Returns (ok, nrm, abn) with the compat partition swap applied."""
    vocab, baseline = compute_slo(case.normal)
    flag, nrm, abn = detect_partition(
        config, vocab, baseline, case.abnormal
    )
    ok = bool(flag) and bool(nrm) and bool(abn)
    if ok and config.compat.partition_swap:
        nrm, abn = abn, nrm
    return ok, nrm, abn


def _finalize_report(
    report: EvalReport,
    all_ranks: List[Tuple[Optional[int], int]],
    detected: int,
    eval_cfg: EvalConfig,
) -> EvalReport:
    """Shared scoring: R@k over faults, Exam Score in both forms —
    normalized depth and the paper's raw inspection count (unranked
    faults count as a full candidate scan)."""
    n_faults = len(all_ranks)
    for k in eval_cfg.ks:
        report.recall_at[k] = (
            sum(1 for r, _ in all_ranks if r is not None and r <= k)
            / max(n_faults, 1)
        )
    depths = [
        ((r - 1) / max(n, 1)) if r is not None else 1.0
        for r, n in all_ranks
    ]
    # Unranked = a full candidate scan; undetected cases carry n=0, so
    # fall back to the workload's whole candidate space.
    full_scan = eval_cfg.n_operations * max(1, eval_cfg.n_pods)
    raw = [
        (r - 1) if r is not None else (n if n > 0 else full_scan)
        for r, n in all_ranks
    ]
    report.exam_score = float(np.mean(depths)) if depths else float("nan")
    report.exam_score_paper = (
        float(np.mean(raw)) if raw else float("nan")
    )
    report.detection_rate = detected / max(eval_cfg.n_cases, 1)
    return report


def _run_case(case, config: MicroRankConfig) -> CaseResult:
    faults = case.fault_pod_ops
    ok, nrm, abn = _detect_partition(case, config)
    if not ok:
        return CaseResult(
            seed=-1, faults=faults, ranks=[None] * len(faults),
            n_ranked_ops=0, detected=False,
        )
    top, _ = get_backend(config).rank_window(case.abnormal, nrm, abn)
    pos = {name: i + 1 for i, name in enumerate(top)}
    ranks = [pos.get(f) for f in faults]
    return CaseResult(
        seed=-1, faults=faults, ranks=ranks, n_ranked_ops=len(top),
        detected=True,
    )


def evaluate(
    config: MicroRankConfig = MicroRankConfig(),
    eval_cfg: EvalConfig = EvalConfig(),
) -> EvalReport:
    """Run the accuracy experiment; rankings are requested full-depth so
    Exam Score is exact (top_max is widened to cover every op)."""
    config = _widen_spectrum(config, eval_cfg)
    report = EvalReport()
    all_ranks: List[Tuple[Optional[int], int]] = []
    detected = 0
    for i in range(eval_cfg.n_cases):
        seed = eval_cfg.seed0 + i
        case = generate_case(_case_config(eval_cfg, seed))
        result = _run_case(case, config)
        result.seed = seed
        report.cases.append(result)
        detected += result.detected
        for r in result.ranks:
            all_ranks.append((r, result.n_ranked_ops))
        log.info(
            "case %d: detected=%s faults=%s ranks=%s",
            seed, result.detected, result.faults, result.ranks,
        )
    return _finalize_report(report, all_ranks, detected, eval_cfg)


@dataclass
class DetectionReport:
    """Per-window anomaly-detection quality (paper Fig. 9 methodology)."""

    tp: int = 0
    fp: int = 0
    fn: int = 0
    tn: int = 0

    @property
    def precision(self) -> float:
        return self.tp / max(self.tp + self.fp, 1)

    @property
    def recall(self) -> float:
        return self.tp / max(self.tp + self.fn, 1)

    @property
    def f1(self) -> float:
        p, r = self.precision, self.recall
        return 2 * p * r / max(p + r, 1e-12)

    def summary(self) -> str:
        return (
            f"windows tp={self.tp} fp={self.fp} fn={self.fn} tn={self.tn}: "
            f"precision={self.precision:.2%} recall={self.recall:.2%} "
            f"F1={self.f1:.2%}"
        )


def evaluate_detection(
    config: MicroRankConfig = MicroRankConfig(),
    eval_cfg: EvalConfig = EvalConfig(),
    n_windows: int = 10,
) -> DetectionReport:
    """Window-level detection precision/recall/F1 over synthetic
    timelines (the paper's Fig. 9 experiment; its testbed numbers are
    98/94/96% on dataset A — BASELINE.md).

    Each case is a continuous ``n_windows``-window stream with a random
    half of the windows faulted; every window is classified by
    ``system_anomaly_detect`` semantics (fixed stride — the driver loop's
    +skip shortcut is deliberately NOT applied, so every window is
    scored).
    """
    import pandas as pd

    from .io.loader import window_spans
    from .testing.synthetic import generate_timeline

    report = DetectionReport()
    for i in range(eval_cfg.n_cases):
        seed = eval_cfg.seed0 + i
        rng = np.random.default_rng(seed)
        faulted = sorted(
            rng.choice(n_windows, size=max(1, n_windows // 2), replace=False)
        )
        tl = generate_timeline(
            _case_config(eval_cfg, seed),
            n_windows,
            [int(f) for f in faulted],
        )
        vocab, baseline = compute_slo(tl.normal)
        for w in range(n_windows):
            w0 = tl.start + pd.Timedelta(minutes=w * tl.window_minutes)
            w1 = w0 + pd.Timedelta(minutes=tl.window_minutes)
            # The same get_span predicate the pipeline windows with.
            spans = window_spans(tl.timeline, w0, w1)
            flag = False
            if len(spans):
                batch, _ = build_detect_batch(spans, vocab)
                det = detect_numpy(batch, baseline, config.detector)
                flag = bool(det.flag)
            truth = tl.window_faulted[w]
            if flag and truth:
                report.tp += 1
            elif flag and not truth:
                report.fp += 1
            elif truth:
                report.fn += 1
            else:
                report.tn += 1
        log.info(
            "timeline %d: faulted=%s tp=%d fp=%d fn=%d tn=%d",
            seed, list(faulted), report.tp, report.fp, report.fn, report.tn,
        )
    return report


def evaluate_overlap_ablation(
    config: MicroRankConfig = MicroRankConfig(),
    eval_cfg: EvalConfig = EvalConfig(n_faults=2),
    overlaps: Tuple[float, ...] = (0.0, 0.25, 0.5, 0.75, 1.0),
) -> Dict[float, EvalReport]:
    """Two-fault accuracy vs fault-path separation (the hardness
    ablation behind EVALUATION.md's two-fault table).

    Runs ``evaluate`` once per target overlap with the fault placement
    constrained via ``SyntheticConfig.fault_path_overlap``: overlap 0
    puts the two faults on disjoint call paths (the separable regime the
    paper's dataset-B testbed approximates), overlap 1 makes one fault
    an ancestor of the other (its spectrum counters are masked by the
    propagated latency — irreducibly hard for any coverage-spectrum
    ranker). Returns {target_overlap: EvalReport}.
    """
    import dataclasses

    out: Dict[float, EvalReport] = {}
    for ov in overlaps:
        ecfg = dataclasses.replace(
            eval_cfg,
            n_faults=max(2, eval_cfg.n_faults),
            fault_path_overlap=float(ov),
        )
        out[float(ov)] = evaluate(config, ecfg)
        log.info("overlap %.2f: %s", ov, out[float(ov)].summary())
    return out


def evaluate_all_methods(
    config: MicroRankConfig = MicroRankConfig(),
    eval_cfg: EvalConfig = EvalConfig(),
) -> Dict[str, "EvalReport"]:
    """The paper's per-formula comparison (Tables 4-6 axis) in one sweep.

    Each case runs detection/partitioning once and, on the jax backend,
    ONE all-formulas device dispatch (power iterations and spectrum
    counters are method-independent); the numpy oracle falls back to one
    ranking per method. Returns {method: EvalReport}, same scoring as
    ``evaluate``.
    """
    from .spectrum.formulas import METHODS

    config = _widen_spectrum(config, eval_cfg)
    backend = get_backend(config)
    reports = {m: EvalReport() for m in METHODS}
    all_ranks: Dict[str, List[Tuple[Optional[int], int]]] = {
        m: [] for m in METHODS
    }
    detected = 0
    for i in range(eval_cfg.n_cases):
        seed = eval_cfg.seed0 + i
        case = generate_case(_case_config(eval_cfg, seed))
        faults = case.fault_pod_ops
        ok, nrm, abn = _detect_partition(case, config)
        detected += ok
        if not ok:
            per_method = {m: ([], []) for m in METHODS}
        elif hasattr(backend, "rank_window_all_methods"):
            per_method = backend.rank_window_all_methods(
                case.abnormal, nrm, abn
            )
        else:  # oracle backend: one ranking per method
            import dataclasses

            per_method = {}
            for m in METHODS:
                mconfig = config.replace(
                    spectrum=dataclasses.replace(config.spectrum, method=m)
                )
                per_method[m] = get_backend(mconfig).rank_window(
                    case.abnormal, nrm, abn
                )
        for m in METHODS:
            top, _ = per_method[m]
            pos = {name: r + 1 for r, name in enumerate(top)}
            ranks = [pos.get(f) for f in faults]
            reports[m].cases.append(
                CaseResult(
                    seed=seed, faults=faults, ranks=ranks,
                    n_ranked_ops=len(top), detected=ok,
                )
            )
            for r in ranks:
                all_ranks[m].append((r, len(top)))
        log.info("case %d: detected=%s faults=%s", seed, ok, faults)

    for m in METHODS:
        _finalize_report(reports[m], all_ranks[m], detected, eval_cfg)
    return reports
