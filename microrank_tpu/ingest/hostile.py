"""Deterministic span-data corruption: the hostile side of admission.

One seeded function per corruption class, applied to a canonical span
frame. Three consumers share it so the attack and the defense are
pinned against the same bytes:

* the chaos registry's ``source_data`` seam (``ReplaySource``/
  ``SyntheticSource`` corrupt a chunk when a fault spec fires — the
  fault plan's seed + event number make the corruption replayable);
* the ``hostile`` scenario family (``scenarios.generate`` corrupts the
  compiled timeline so the policy engine scores formulas under dirty
  data);
* the adversarial corpus fixtures under ``tests/data/hostile/``
  (``tests/data/hostile/make_fixtures.py`` renders one CSV per class).

Corruptions mirror the admission taxonomy (ingest.quarantine.REASONS):

* ``corrupt_row``       — unparseable timestamps + negative/NaN
  durations on a row sample (the classic torn/garbled export rows);
* ``dup_span``          — a row sample duplicated verbatim;
* ``orphan``            — a row sample's ``ParentSpanId`` repointed at
  span ids that do not exist;
* ``clock_skew``        — a row sample's timestamps shifted by a
  cross-host offset (half clampable, half hopeless);
* ``cardinality_bomb``  — one adversarial trace appended whose every
  span carries a UNIQUE operation name (vocab growth) on one long
  trace (pad-bucket growth) — the budget guard's target.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

CORRUPTION_KINDS = (
    "corrupt_row", "dup_span", "orphan", "clock_skew",
    "cardinality_bomb",
)


def _sample(rng: np.random.Generator, n: int, fraction: float) -> np.ndarray:
    k = max(1, int(round(n * fraction)))
    return rng.choice(n, size=min(k, n), replace=False)


def corrupt_frame(
    frame: pd.DataFrame,
    kind: str,
    seed: int = 0,
    fraction: float = 0.05,
    bomb_ops: int = 64,
) -> pd.DataFrame:
    """Return a corrupted COPY of ``frame`` (the input is never
    mutated). ``fraction`` sizes the row sample for the row-local
    kinds; ``bomb_ops`` sizes the cardinality bomb's unique-op count.
    Deterministic in (frame, kind, seed)."""
    rng = np.random.default_rng(
        np.uint64(seed) + np.uint64(len(frame)) * np.uint64(2654435761)
    )
    out = frame.copy()
    n = len(out)
    if n == 0:
        return out
    if kind == "corrupt_row":
        rows = _sample(rng, n, fraction)
        half = rows[: max(1, len(rows) // 2)]
        rest = rows[len(half):]
        # Timestamp garbage needs an object column; duration garbage
        # needs a float/object column — exactly the dirtiness a real
        # CSV row brings in.
        out["startTime"] = out["startTime"].astype(object)
        out.iloc[
            half, out.columns.get_loc("startTime")
        ] = "not-a-timestamp"
        if len(rest):
            out["duration"] = out["duration"].astype(object)
            neg = rest[: len(rest) // 2 + 1]
            nan = rest[len(neg):]
            out.iloc[neg, out.columns.get_loc("duration")] = -1
            if len(nan):
                out.iloc[
                    nan, out.columns.get_loc("duration")
                ] = "garbage"
        return out
    if kind == "dup_span":
        rows = _sample(rng, n, fraction)
        return pd.concat(
            [out, out.iloc[rows]], ignore_index=True
        )
    if kind == "orphan":
        rows = _sample(rng, n, fraction)
        ghosts = np.array(
            [f"ghost-{seed}-{i}" for i in range(len(rows))]
        )
        out.iloc[
            rows, out.columns.get_loc("ParentSpanId")
        ] = ghosts
        return out
    if kind == "clock_skew":
        rows = _sample(rng, n, fraction)
        # Half a clampable cross-host offset (minutes), half hopeless
        # (days) — exercising BOTH admission outcomes.
        near = rows[: max(1, len(rows) // 2)]
        far = rows[len(near):]
        # Coerce: classes compose (corrupt_timeline chains them), so a
        # frame may already carry unparseable cells — they stay bad
        # (NaT) and the shift applies to the parseable rest.
        start = pd.to_datetime(
            out["startTime"], format="mixed", errors="coerce"
        ).copy()
        end = pd.to_datetime(
            out["endTime"], format="mixed", errors="coerce"
        ).copy()
        near_off = pd.Timedelta(minutes=10)
        far_off = pd.Timedelta(days=3)
        start.iloc[near] = start.iloc[near] + near_off
        end.iloc[near] = end.iloc[near] + near_off
        if len(far):
            start.iloc[far] = start.iloc[far] - far_off
            end.iloc[far] = end.iloc[far] - far_off
        out["startTime"] = start
        out["endTime"] = end
        return out
    if kind == "cardinality_bomb":
        t0 = pd.to_datetime(
            out["startTime"], format="mixed", errors="coerce"
        ).min()
        trace = f"bomb-{seed}"
        k = int(bomb_ops)
        rows = {
            "traceID": [trace] * k,
            "spanID": [f"{trace}-s{i}" for i in range(k)],
            "ParentSpanId": [""]
            + [f"{trace}-s{i}" for i in range(k - 1)],
            "operationName": [
                f"op-bomb-{seed}-{i}" for i in range(k)
            ],
            "serviceName": [f"svc-bomb-{seed}"] * k,
            "podName": [f"svc-bomb-{seed}-0"] * k,
            "duration": np.full(k, 1000, dtype=np.int64),
            "startTime": [
                t0 + pd.Timedelta(microseconds=10 * i) for i in range(k)
            ],
            "endTime": [
                t0 + pd.Timedelta(microseconds=10 * i + 1000)
                for i in range(k)
            ],
        }
        bomb = pd.DataFrame(rows)
        for col in out.columns:
            if col not in bomb.columns:
                bomb[col] = 0
        return pd.concat(
            [out, bomb[list(out.columns)]], ignore_index=True
        )
    raise ValueError(
        f"unknown corruption kind {kind!r}; expected one of "
        f"{CORRUPTION_KINDS}"
    )


def corrupt_timeline(
    frame: pd.DataFrame,
    kinds,
    seed: int = 0,
    fraction: float = 0.05,
    bomb_ops: int = 64,
) -> pd.DataFrame:
    """Apply several corruption classes in sequence (the ``hostile``
    scenario family's mixed shape); each class draws from a distinct
    derived seed so the mix is reproducible from one integer."""
    out = frame
    for i, kind in enumerate(kinds):
        out = corrupt_frame(
            out, kind, seed=seed * 1009 + i, fraction=fraction,
            bomb_ops=bomb_ops,
        )
    return out
