"""Span admission + quarantine: the data-plane robustness layer.

PRs 10-12 made the *process* crash-only; this subsystem makes the
*data path* hostile-proof. Every lane (batch, serve, stream, fleet)
passes span frames through :func:`admit_frame` before detect/build:
per-row schema+value validation vectorized over the frame, rejected
rows routed to a bounded dead-letter store
(:class:`QuarantineStore`, ``quarantine.jsonl``) with a fixed reason
taxonomy — never a crash, never silent — and resource-budget guards
(op-vocab growth, trace length, duration overflow) that keep an
adversarial cardinality bomb from growing the pad buckets and the
staged-bytes footprint without bound. :mod:`ingest.hostile` is the
attack side: deterministic corruption generators the chaos registry's
``source_data`` seam and the ``hostile`` scenario family share.
"""

from .admission import (
    AdmissionResult,
    TraceClock,
    admit_frame,
    coercible_event_times,
    pre_admit_frame,
)
from .hostile import CORRUPTION_KINDS, corrupt_frame, corrupt_timeline
from .quarantine import (
    QUARANTINE_NAME,
    REASONS,
    QuarantineStore,
    configure_quarantine,
    get_quarantine,
)
from .table_admission import admit_table

__all__ = [
    "AdmissionResult",
    "TraceClock",
    "CORRUPTION_KINDS",
    "QUARANTINE_NAME",
    "QuarantineStore",
    "REASONS",
    "admit_frame",
    "admit_table",
    "coercible_event_times",
    "configure_quarantine",
    "corrupt_frame",
    "corrupt_timeline",
    "get_quarantine",
    "pre_admit_frame",
]
