"""Span admission: per-row schema+value validation, vectorized.

Every lane — batch ``TableRCA``/``OnlineRCA``, ``serve`` POST /rank,
the ``stream`` sources, ``fleet`` workers — passes span frames through
:func:`admit_frame` before detect/build. The checks run in a fixed
order (each step sees only rows the previous steps admitted), all of
them vectorized over the frame:

1. **identity** — null/empty ``traceID``/``spanID`` reject
   (``missing_id``);
2. **timestamps** — ``startTime``/``endTime`` coerce with
   ``errors="coerce"``; NaT rejects (``bad_timestamp``) — one malformed
   row never aborts the frame;
3. **durations** — non-numeric/negative reject (``bad_duration``),
   values past ``IngestConfig.max_duration_us`` reject
   (``duration_overflow``);
4. **duplicates** — repeated ``(traceID, spanID)`` keeps the FIRST
   occurrence, rejects the rest (``dup_span``);
5. **trace-length budget** — a trace's spans past
   ``max_spans_per_trace`` (event-time order) reject
   (``trace_too_long``): a single adversarial mega-trace cannot grow
   the pad buckets without bound;
6. **parent linkage** — a span naming a parent absent from its trace
   is an orphan: ``orphan_policy="stitch"`` clears the link (the span
   becomes a root, kept and counted), ``"drop"`` rejects (``orphan``);
7. **vocab budget** — distinct operations past ``max_ops_per_window``
   keep the highest-span-count ops and reject the tail
   (``vocab_budget``): the cardinality-bomb guard — bomb ops are
   many-and-thin by construction, so the real vocabulary survives;
8. **clock skew** — spans whose start sits outside the window bound by
   up to ``skew_reject_seconds`` CLAMP to the window-relative bound
   (``max_skew_seconds``, kept and counted — cross-host skew is
   normalized, not punished); further out rejects (``clock_skew``).

Rejected rows route to the dead-letter store (ingest.quarantine) with
exactly one reason each; per-reason counts land in
``microrank_ingest_rejected_total{reason}`` and the caller's journal.
Admission is idempotent: re-admitting the clean subset rejects nothing
and changes nothing (pinned by a property test).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

import numpy as np
import pandas as pd

from ..utils.logging import get_logger
from .quarantine import QuarantineStore

log = get_logger("microrank_tpu.ingest")


@dataclass
class AdmissionResult:
    """What admission decided about one frame."""

    frame: pd.DataFrame                 # the clean (admitted) subset
    n_input: int = 0
    n_admitted: int = 0
    rejected: Dict[str, int] = field(default_factory=dict)
    clamped_skew: int = 0               # kept rows whose times clamped
    stitched_orphans: int = 0           # kept rows whose parent cleared
    window_ops: int = 0                 # post-admission distinct op count

    @property
    def n_rejected(self) -> int:
        return sum(self.rejected.values())

    @property
    def admission_ratio(self) -> float:
        """Admitted fraction of the input (1.0 for an empty input —
        an empty frame is vacuously clean, not hostile)."""
        if self.n_input == 0:
            return 1.0
        return self.n_admitted / self.n_input

    @property
    def degraded(self) -> bool:
        """True when anything was rejected: downstream results are
        correct on the clean subset but partial on the window."""
        return self.n_rejected > 0

    def journal_fields(self) -> dict:
        """Compact per-window journal record of the admission."""
        return {
            "n_input": self.n_input,
            "n_admitted": self.n_admitted,
            "rejected": dict(self.rejected),
            "clamped_skew": self.clamped_skew,
            "stitched_orphans": self.stitched_orphans,
            "admission_ratio": round(self.admission_ratio, 4),
        }


def _empty_result(frame: pd.DataFrame) -> AdmissionResult:
    return AdmissionResult(
        frame=frame, n_input=len(frame), n_admitted=len(frame)
    )


def _coerce_datetime(col: pd.Series) -> pd.Series:
    """errors="coerce" datetime parse that accepts already-parsed
    columns unchanged (fast path: no re-parse of datetime64)."""
    if pd.api.types.is_datetime64_any_dtype(col):
        return col
    return pd.to_datetime(col, format="mixed", errors="coerce")


def coercible_event_times(
    frame: pd.DataFrame,
) -> Tuple[pd.Series, pd.Series, np.ndarray]:
    """(start, end, bad_mask) — coerced timestamps plus the rows whose
    event time cannot exist. Shared by :func:`admit_frame` and the
    stream engine's pre-windowing gate (the windower needs sane
    ``startTime`` before window assignment is even defined)."""
    start = _coerce_datetime(frame["startTime"])
    end = _coerce_datetime(frame["endTime"])
    bad = (start.isna() | end.isna()).to_numpy()
    return start, end, bad


def _skew_normalize(
    start: pd.Series,
    end: pd.Series,
    dur: pd.Series,
    alive: np.ndarray,
    cfg,
    window_bounds: Optional[Tuple],
) -> Tuple[pd.Series, pd.Series, int, np.ndarray]:
    """Clock-skew normalization, shared by the per-window ladder and
    the pre-windowing gate: spans outside the reference interval by up
    to ``skew_reject_seconds`` CLAMP to the ``max_skew_seconds`` bound
    (kept); further out is hopeless (rejected). The reference interval
    is the window bounds when given, else the ROBUST (10th..90th
    percentile) start-time spread of the frame itself — robust, because
    the skewed rows are in the frame and a min/max reference would
    follow them. The clamp is what protects the WATERMARK: a forward-
    skewed row that kept its claimed time would advance the event-time
    watermark by the full skew and close innocent windows early (their
    real spans then drop as late) — clamped to the bound, the damage
    is capped at ``max_skew_seconds``. Returns
    (start, end, n_clamped, hopeless_mask)."""
    n = len(start)
    hopeless = np.zeros(n, dtype=bool)
    skew_s = float(getattr(cfg, "max_skew_seconds", 0.0) or 0.0)
    reject_s = float(getattr(cfg, "skew_reject_seconds", 0.0) or 0.0)
    if skew_s <= 0 or not alive.any():
        return start, end, 0, hopeless
    start_ns = start.values.astype("int64")
    if window_bounds is not None:
        ref_lo = pd.Timestamp(window_bounds[0]).value
        ref_hi = pd.Timestamp(window_bounds[1]).value
        hop_lo, hop_hi = ref_lo, ref_hi
        fwd_s = skew_s
    else:
        ref_lo = int(np.quantile(start_ns[alive], 0.1))
        ref_hi = int(np.quantile(start_ns[alive], 0.9))
        # The HOPELESS bound anchors on the median, not the spread
        # quantiles: an event-time sort concentrates skewed rows at
        # the batch edges, where they'd capture q10/q90 and certify
        # themselves sane. The median survives any minority attack
        # (a majority-corrupt batch defeats it — the per-window
        # min_admission_ratio refusal is the backstop there).
        hop_lo = hop_hi = int(np.median(start_ns[alive]))
        # Pre-windowing: the forward bound is tight (watermark
        # protection), the backward bound loose (a past-claiming row
        # only risks being late itself).
        fwd_s = float(
            getattr(cfg, "forward_skew_seconds", skew_s) or skew_s
        )
    lo = ref_lo - int(skew_s * 1e9)
    hi = ref_hi + int(fwd_s * 1e9)
    off = ((start_ns < lo) | (start_ns > hi)) & alive
    if reject_s > skew_s:
        hopeless = (
            (start_ns < hop_lo - int(reject_s * 1e9))
            | (start_ns > hop_hi + int(reject_s * 1e9))
        ) & alive
    clamp = off & ~hopeless
    n_clamp = int(clamp.sum())
    if n_clamp:
        clamped = np.clip(start_ns, lo, hi)
        new_start_ns = np.where(clamp, clamped, start_ns)
        dur_ns = (
            dur.fillna(0).to_numpy(dtype="float64") * 1e3
        ).astype("int64")
        new_end_ns = np.where(
            clamp, new_start_ns + dur_ns, end.values.astype("int64")
        )
        start = pd.Series(
            pd.to_datetime(new_start_ns), index=start.index
        )
        end = pd.Series(pd.to_datetime(new_end_ns), index=end.index)
        from ..obs.metrics import record_ingest_clamped

        record_ingest_clamped("clock_skew", n_clamp)
    return start, end, n_clamp, hopeless


class TraceClock:
    """Bounded per-trace first-seen event-time registry: the trace-
    relative half of clock-skew normalization.

    Batch-relative bounds cannot see a skewed span once the stream is
    re-sorted — a row shifted ten minutes forward sits among rows that
    genuinely started then, perfectly sane relative to its neighbors.
    What betrays it is its own TRACE: spans of one trace start within
    the trace's real duration of each other, so a span claiming a time
    far from its trace's first-seen event time is skew-displaced (a
    torn trace's root span landing alone in the wrong window is what
    turns cross-host skew into spurious anomalies). ``normalize``
    clamps such spans to ``first_seen ± forward_skew_seconds`` (kept +
    counted — normalization, not punishment). The registry is a
    bounded LRU over trace ids, so an unbounded id stream cannot grow
    host memory.
    """

    def __init__(self, max_traces: int = 1 << 16):
        from collections import OrderedDict

        self.max_traces = int(max_traces)
        self._first: "OrderedDict[str, int]" = OrderedDict()

    def normalize(
        self, trace_ids: np.ndarray, start: pd.Series,
        end: Optional[pd.Series], alive: np.ndarray, cfg,
    ) -> Tuple[pd.Series, Optional[pd.Series], int]:
        bound_s = float(
            getattr(cfg, "forward_skew_seconds", 0.0) or 0.0
        )
        if bound_s <= 0 or not alive.any():
            return start, end, 0
        bound = int(bound_s * 1e9)
        start_ns = start.values.astype("int64").copy()
        idx = np.flatnonzero(alive)
        sub_tr = trace_ids[idx]
        sub_ns = start_ns[idx]
        # Per-trace batch minimum, joined (vectorized) with the
        # registry's earlier first-seen where one exists.
        codes, uniq = pd.factorize(sub_tr)
        bmin = np.full(len(uniq), np.iinfo(np.int64).max, np.int64)
        np.minimum.at(bmin, codes, sub_ns)
        seen = np.array(
            [self._first.get(t, -1) for t in uniq], dtype=np.int64
        )
        first = np.where(seen >= 0, np.minimum(seen, bmin), bmin)
        row_first = first[codes]
        off = (sub_ns < row_first - bound) | (
            sub_ns > row_first + bound
        )
        n_clamp = int(off.sum())
        delta = None
        if n_clamp:
            # Repair lands ON first_seen, not at the bound edge: a
            # displaced span rejoins its trace's window — clamping to
            # first_seen + bound would park boundary-adjacent spans
            # one window over, and a torn partial trace there reads as
            # an anomaly.
            clamped = np.where(off, row_first, sub_ns)
            delta = clamped - sub_ns
            sub_ns = clamped
            start_ns[idx] = sub_ns
        new_first = np.minimum(first, bmin)
        np.minimum.at(new_first, codes, sub_ns)
        for t, v in zip(uniq, new_first):
            self._first[t] = int(v)
            self._first.move_to_end(t)
        while len(self._first) > self.max_traces:
            self._first.popitem(last=False)
        if n_clamp:
            from ..obs.metrics import record_ingest_clamped

            record_ingest_clamped("clock_skew", n_clamp)
            start = pd.Series(
                pd.to_datetime(start_ns), index=start.index
            )
            if end is not None:
                # The span's whole time range shifts by the repair
                # delta — end must follow start or the batch window
                # predicate (start >= w0 AND end <= w1) would silently
                # exclude the repaired span from every window.
                end_ns = end.values.astype("int64").copy()
                end_ns[idx] = end_ns[idx] + delta
                end = pd.Series(
                    pd.to_datetime(end_ns), index=end.index
                )
        return start, end, n_clamp


def pre_admit_frame(
    frame: pd.DataFrame,
    ingest_config,
    quarantine: Optional[QuarantineStore] = None,
    source: str = "",
    trace_clock: Optional[TraceClock] = None,
) -> Tuple[pd.DataFrame, Dict[str, int]]:
    """The pre-windowing gate: reject rows that cannot be ASSIGNED to a
    window (missing ids, uncoercible timestamps, non-numeric durations,
    hopeless clock skew) and clamp salvageable skew to the batch's
    robust event-time spread — BEFORE the windower files spans by start
    time, so a skewed span neither poisons the watermark (closing
    innocent windows early, late-dropping their real spans) nor
    silently late-drops itself. Window-relative checks (duplicates,
    orphans, budgets) stay with :func:`admit_frame` on the CLOSED
    window. Returns (clean_frame, rejected_counts)."""
    if not getattr(ingest_config, "enabled", True) or len(frame) == 0:
        return frame, {}
    masks: Dict[str, np.ndarray] = {}
    missing = _missing_id_mask(frame)
    start, end, bad_ts = coercible_event_times(frame)
    dur = pd.to_numeric(frame["duration"], errors="coerce")
    bad_dur = (dur.isna() | (dur < 0)).to_numpy()
    masks["missing_id"] = missing
    masks["bad_timestamp"] = bad_ts & ~missing
    masks["bad_duration"] = bad_dur & ~missing & ~bad_ts
    alive = ~(missing | bad_ts | bad_dur)
    start, end, n_skew, hopeless = _skew_normalize(
        start, end, dur, alive, ingest_config, window_bounds=None
    )
    n_clock = 0
    if trace_clock is not None:
        # Trace-relative skew repair: a span claiming a time far from
        # its own trace's first-seen event time clamps back to it —
        # the only reference a re-sorted stream cannot fake.
        tr = frame["traceID"].astype(str).to_numpy()
        start, end, n_clock = trace_clock.normalize(
            tr, start, end, alive & ~hopeless, ingest_config
        )
    masks["clock_skew"] = hopeless
    rejected = _reject(frame, masks, quarantine, source)
    bad = ~alive | hopeless
    if (
        not bad.any()
        and n_skew == 0
        and n_clock == 0
        and pd.api.types.is_datetime64_any_dtype(frame["startTime"])
        and pd.api.types.is_datetime64_any_dtype(frame["endTime"])
        and pd.api.types.is_numeric_dtype(frame["duration"])
    ):
        # Clean batch, nothing coerced or clamped: the hot path pays
        # the vectorized checks and zero copies.
        return frame, rejected
    keep = np.flatnonzero(~bad)
    out = frame.iloc[keep].copy()
    out["startTime"] = start.iloc[keep]
    out["endTime"] = end.iloc[keep]
    out["duration"] = dur.iloc[keep]
    return out.reset_index(drop=True), rejected


def _missing_id_mask(frame: pd.DataFrame) -> np.ndarray:
    bad = np.zeros(len(frame), dtype=bool)
    for col in ("traceID", "spanID"):
        s = frame[col]
        bad |= s.isna().to_numpy()
        bad |= (s.astype(str).str.len() == 0).to_numpy()
    return bad


def _reject(
    frame: pd.DataFrame,
    masks: Dict[str, np.ndarray],
    quarantine: Optional[QuarantineStore],
    source: str,
) -> Dict[str, int]:
    """Record + quarantine per-reason reject masks; returns counts."""
    from ..obs.metrics import record_ingest_rejected
    from .quarantine import get_quarantine

    counts = {
        reason: int(np.asarray(m).sum())
        for reason, m in masks.items()
        if np.asarray(m).any()
    }
    if not counts:
        return counts
    for reason, n in counts.items():
        record_ingest_rejected(reason, n)
    store = quarantine if quarantine is not None else get_quarantine()
    store.put_frame(frame, masks, source=source)
    return counts


def admit_frame(
    frame: pd.DataFrame,
    ingest_config,
    quarantine: Optional[QuarantineStore] = None,
    source: str = "",
    window_bounds: Optional[Tuple] = None,
    known_ops=None,
) -> AdmissionResult:
    """Run the full admission ladder over one window frame (see module
    docstring for the step order). ``window_bounds=(start, end)``
    anchors the clock-skew bound to the window; without it the frame's
    own robust start-time spread anchors it (the serve shape, where the
    request IS the window). ``known_ops`` — the baseline's service-
    level operation set — arms the vocab-GROWTH guard: a window
    introducing more than ``max_new_ops_per_window`` never-seen
    operations is under cardinality attack and ALL its never-seen-op
    spans quarantine (a bomb of novel op names must not reach the
    detector, the baseline, or the pad buckets)."""
    cfg = ingest_config
    if not getattr(cfg, "enabled", True) or len(frame) == 0:
        return _empty_result(frame)

    n_input = len(frame)
    work = frame.reset_index(drop=True)
    masks: Dict[str, np.ndarray] = {}
    result = AdmissionResult(frame=work, n_input=n_input)

    # 1-3: identity, timestamps, durations (the pre-windowing trio).
    missing = _missing_id_mask(work)
    start, end, bad_ts = coercible_event_times(work)
    dur = pd.to_numeric(work["duration"], errors="coerce")
    bad_dur = (dur.isna() | (dur < 0)).to_numpy()
    max_dur = int(getattr(cfg, "max_duration_us", 0) or 0)
    over_dur = (
        (dur > max_dur).fillna(False).to_numpy()
        if max_dur > 0
        else np.zeros(n_input, dtype=bool)
    )
    masks["missing_id"] = missing
    masks["bad_timestamp"] = bad_ts & ~missing
    masks["bad_duration"] = bad_dur & ~missing & ~bad_ts
    masks["duration_overflow"] = (
        over_dur & ~missing & ~bad_ts & ~bad_dur
    )
    rejected = missing | bad_ts | bad_dur | over_dur

    # 4: duplicate (traceID, spanID) — first occurrence wins.
    alive = ~rejected
    dup = (
        work[["traceID", "spanID"]]
        .astype(str)
        .duplicated(keep="first")
        .to_numpy()
    )
    # A duplicate of a REJECTED first occurrence is still a duplicate
    # of data that existed; keeping taxonomy simple, any repeat of a
    # key already seen rejects as dup_span.
    masks["dup_span"] = dup & alive
    rejected |= dup

    # 5: trace-length budget (event-time order within each trace).
    max_trace = int(getattr(cfg, "max_spans_per_trace", 0) or 0)
    if max_trace > 0:
        alive = ~rejected
        # Rank of each alive row within its trace, in start order:
        # stable sort by (trace, start), then position minus the first
        # position of the trace run.
        tr = work["traceID"].astype(str).to_numpy()
        key_start = start.values.astype("int64")
        idx = np.flatnonzero(alive)
        if idx.size:
            sub_order = idx[
                np.lexsort((key_start[idx], tr[idx]))
            ]
            tr_sorted = tr[sub_order]
            run_start = np.flatnonzero(
                np.concatenate(
                    ([True], tr_sorted[1:] != tr_sorted[:-1])
                )
            )
            pos = np.arange(sub_order.size)
            rank = pos - np.repeat(
                run_start, np.diff(np.append(run_start, sub_order.size))
            )
            too_long = np.zeros(n_input, dtype=bool)
            too_long[sub_order[rank >= max_trace]] = True
            masks["trace_too_long"] = too_long
            rejected |= too_long

    # (Parent linkage runs LAST — steps 7/8 can reject a span whose
    # children survive, and the orphan pass must see the final set or
    # re-admission would find new orphans, breaking idempotence.)

    # 7: vocab budgets — the cardinality-bomb guards.
    max_ops = int(getattr(cfg, "max_ops_per_window", 0) or 0)
    max_new = int(getattr(cfg, "max_new_ops_per_window", 0) or 0)
    alive = ~rejected
    op_names = (
        work["podName"].astype(str)
        + "_"
        + work["operationName"].astype(str)
    ).to_numpy()
    if known_ops and max_new > 0 and alive.any():
        # 7a: GROWTH cap against the baseline's known vocabulary. A
        # never-seen op is fine (deployments happen); a window full of
        # them is an attack — past the cap, every never-seen-op span
        # rejects, so novel-name bombs cannot trigger the detector,
        # retrain the baseline, or escalate the pad buckets.
        from ..io.naming import operation_names

        svc_names = operation_names(work, "service").to_numpy()
        uniq_new = pd.unique(
            svc_names[alive & ~np.isin(svc_names, list(known_ops))]
        )
        if uniq_new.size > max_new:
            over = np.isin(svc_names, uniq_new) & alive
            masks["vocab_budget"] = over
            rejected |= over
            alive = ~rejected
            log.warning(
                "%s: vocab growth cap hit — window introduces %d "
                "never-seen ops > %d cap; rejected all %d of their "
                "spans (cardinality attack)",
                source or "ingest", uniq_new.size, max_new,
                int(over.sum()),
            )
    if max_ops > 0 and alive.any():
        uniq, inv, counts = np.unique(
            op_names[alive], return_inverse=True, return_counts=True
        )
        if uniq.size > max_ops:
            # Keep the max_ops highest-span-count ops (ties by name for
            # determinism); everything else is past the budget.
            order2 = np.lexsort((uniq, -counts))
            kept_ops = set(uniq[order2[:max_ops]])
            over = np.zeros(n_input, dtype=bool)
            over[np.flatnonzero(alive)] = np.array(
                [u not in kept_ops for u in uniq], dtype=bool
            )[inv]
            # 7a (growth cap) may have fired too: one reason, one mask.
            masks["vocab_budget"] = (
                masks.get("vocab_budget", np.zeros(n_input, bool)) | over
            )
            rejected |= over
            log.warning(
                "%s: vocab budget hit — %d distinct ops > %d cap; "
                "rejected %d spans of the %d thinnest ops",
                source or "ingest", uniq.size, max_ops,
                int(over.sum()), uniq.size - max_ops,
            )

    # 8: clock skew — clamp to the window-relative bound, reject the
    # hopeless.
    alive = ~rejected
    start, end, n_clamp, hopeless = _skew_normalize(
        start, end, dur, alive, cfg, window_bounds
    )
    result.clamped_skew = n_clamp
    if hopeless.any():
        masks["clock_skew"] = hopeless
        rejected |= hopeless

    # 6 (last): parent linkage over the FINAL survivor set — any
    # earlier rejection can orphan a surviving child. "stitch" clears
    # the link in one pass (the span becomes a trace root, kept and
    # counted); "drop" rejects and must iterate — dropping a parent
    # orphans its children, so the pass runs to a fixpoint (bounded by
    # trace depth) or re-admission would keep finding new orphans.
    if "ParentSpanId" in work.columns:
        drop_policy = getattr(cfg, "orphan_policy", "stitch") == "drop"
        orphan_total = np.zeros(n_input, dtype=bool)
        for _ in range(n_input):
            alive = ~rejected
            parent = work["ParentSpanId"].fillna("").astype(str)
            has_parent = (parent.str.len() > 0).to_numpy()
            tr_str = work["traceID"].astype(str)
            span_keys = (
                tr_str + "\x00" + work["spanID"].astype(str)
            ).to_numpy()[alive]
            parent_keys = (tr_str + "\x00" + parent).to_numpy()
            orphan = has_parent & alive & ~np.isin(
                parent_keys, span_keys
            )
            if not orphan.any():
                break
            if drop_policy:
                orphan_total |= orphan
                rejected |= orphan
                continue  # a dropped parent may orphan its children
            # Stitch: one pass suffices (no rows are removed).
            work = work.copy()
            work.loc[orphan, "ParentSpanId"] = ""
            result.stitched_orphans = int(orphan.sum())
            from ..obs.metrics import record_ingest_clamped

            record_ingest_clamped(
                "orphan_stitched", result.stitched_orphans
            )
            break
        if drop_policy and orphan_total.any():
            masks["orphan"] = orphan_total

    # Materialize: quarantine + count the rejects, emit the clean frame
    # with coerced dtypes (clean windows skip the copy entirely).
    result.rejected = _reject(work, masks, quarantine, source)
    if (
        not rejected.any()
        and result.clamped_skew == 0
        and pd.api.types.is_datetime64_any_dtype(work["startTime"])
        and pd.api.types.is_datetime64_any_dtype(work["endTime"])
        and pd.api.types.is_numeric_dtype(work["duration"])
    ):
        clean = work
    else:
        keep = np.flatnonzero(~rejected)
        clean = work.iloc[keep].copy()
        clean["startTime"] = start.iloc[keep]
        clean["endTime"] = end.iloc[keep]
        clean["duration"] = dur.iloc[keep]
        clean = clean.reset_index(drop=True)
    result.frame = clean
    result.n_admitted = len(clean)
    if len(clean):
        result.window_ops = int(
            pd.unique(op_names[np.flatnonzero(~rejected)]).size
        )
    from ..obs.metrics import record_ingest_admitted, record_window_ops

    record_ingest_admitted(result.n_admitted)
    record_window_ops(result.window_ops)
    if result.degraded:
        log.warning(
            "%s: admitted %d/%d spans (%s)",
            source or "ingest", result.n_admitted, result.n_input,
            ", ".join(
                f"{k}={v}" for k, v in sorted(result.rejected.items())
            ),
        )
    return result
