"""Span admission for the native table lane (``TableRCA``).

The native ingest (``native.load_span_table``) interns names and
resolves parent linkage at load time, so half the pandas ladder is
already settled by construction: unparseable rows never produce table
rows, and a missing parent is already ``parent_row = -1`` (the stitch
policy). What remains hostile at this level is VALUES — negative or
overflow durations, inverted/impossible time ranges — and the resource
budgets: a mega-trace that would blow the pad buckets, duration
overflows that poison the SLO statistics. :func:`admit_table` applies
those vectorized over the interned arrays and returns a filtered
``SpanTable`` plus the per-reason counts; rejected rows land in the
dead-letter store with their decoded names, and ``parent_row`` is
remapped so surviving spans whose parent was rejected become roots
(the stitch policy, consistent with the pandas lane).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from ..utils.logging import get_logger
from .quarantine import QuarantineStore

log = get_logger("microrank_tpu.ingest")


def _quarantine_rows(
    table, mask: np.ndarray, reason: str, store, source: str
) -> None:
    idx = np.flatnonzero(mask)
    for i in idx:
        store.put_raw(
            (
                f"trace={table.trace_names[int(table.trace_id[i])]} "
                f"op={table.pod_op_names[int(table.pod_op[i])]} "
                f"duration_us={int(table.duration_us[i])} "
                f"start_us={int(table.start_us[i])} "
                f"end_us={int(table.end_us[i])}"
            ),
            reason,
            source=source,
            offset=int(i),
        )


def admit_table(
    table,
    ingest_config,
    quarantine: Optional[QuarantineStore] = None,
    source: str = "table",
) -> Tuple[object, Dict[str, int]]:
    """Validate + budget one ``SpanTable``; returns
    ``(clean_table, rejected_counts)``. The input is never mutated."""
    from ..obs.metrics import record_ingest_admitted, record_ingest_rejected
    from .quarantine import get_quarantine

    cfg = ingest_config
    n = table.n_spans
    if not getattr(cfg, "enabled", True) or n == 0:
        return table, {}

    masks: Dict[str, np.ndarray] = {}
    dur = table.duration_us
    bad_dur = dur < 0
    masks["bad_duration"] = bad_dur
    max_dur = int(getattr(cfg, "max_duration_us", 0) or 0)
    if max_dur > 0:
        masks["duration_overflow"] = (dur > max_dur) & ~bad_dur
    # Impossible event times: a trace-level end before its start (the
    # loader parses both independently, so a garbled row can invert).
    bad_ts = table.end_us < table.start_us
    masks["bad_timestamp"] = bad_ts & ~bad_dur

    rejected = np.zeros(n, dtype=bool)
    for m in masks.values():
        rejected |= m

    # Trace-length budget: spans of a trace past the cap reject in row
    # (event-time) order — the table is time-sorted, so "first cap
    # spans" is well defined.
    max_trace = int(getattr(cfg, "max_spans_per_trace", 0) or 0)
    if max_trace > 0:
        alive = ~rejected
        tid = table.trace_id.astype(np.int64)
        idx = np.flatnonzero(alive)
        if idx.size:
            order = idx[np.argsort(tid[idx], kind="stable")]
            t_sorted = tid[order]
            run_start = np.flatnonzero(
                np.concatenate(([True], t_sorted[1:] != t_sorted[:-1]))
            )
            pos = np.arange(order.size)
            rank = pos - np.repeat(
                run_start, np.diff(np.append(run_start, order.size))
            )
            too_long = np.zeros(n, dtype=bool)
            too_long[order[rank >= max_trace]] = True
            if too_long.any():
                masks["trace_too_long"] = too_long
                rejected |= too_long

    counts = {
        reason: int(m.sum()) for reason, m in masks.items() if m.any()
    }
    if not counts:
        record_ingest_admitted(n)
        return table, {}

    store = quarantine if quarantine is not None else get_quarantine()
    for reason, m in masks.items():
        if not m.any():
            continue
        record_ingest_rejected(reason, int(m.sum()))
        _quarantine_rows(table, m, reason, store, source)

    keep = ~rejected
    # parent_row holds ABSOLUTE row indices; remap them onto the
    # filtered table, stitching spans whose parent was rejected into
    # roots (-1) — the same policy the pandas lane applies.
    new_pos = np.cumsum(keep) - 1
    parent = table.parent_row
    has_parent = parent >= 0
    parent_kept = np.zeros(n, dtype=bool)
    parent_kept[has_parent] = keep[parent[has_parent]]
    new_parent = np.where(
        has_parent & parent_kept,
        new_pos[np.clip(parent, 0, None)],
        -1,
    ).astype(parent.dtype)
    clean = table._replace(
        trace_id=table.trace_id[keep],
        svc_op=table.svc_op[keep],
        pod_op=table.pod_op[keep],
        duration_us=table.duration_us[keep],
        start_us=table.start_us[keep],
        end_us=table.end_us[keep],
        parent_row=new_parent[keep],
    )
    record_ingest_admitted(int(keep.sum()))
    log.warning(
        "%s: admitted %d/%d spans (%s)",
        source, clean.n_spans, n,
        ", ".join(f"{k}={v}" for k, v in sorted(counts.items())),
    )
    return clean, counts
