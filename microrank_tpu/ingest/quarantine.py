"""Dead-letter store for rejected span rows: bounded, never a crash.

Every row span admission (ingest.admission) refuses — and every raw
line the tail source gives up re-parsing — lands here as ONE JSONL
record carrying the row content, the rejection reason (the taxonomy
below), the lane it came from, and where in the source it sat (byte
offset for raw lines). The store is bounded: past
``IngestConfig.quarantine_max_bytes`` new records are dropped AND
counted (``microrank_ingest_quarantine_dropped_total``) — hostile data
must not convert into a disk-filling attack through the very mechanism
that contains it. With no path configured (no out_dir, library use)
records are counted but not written; rejection is never silent either
way, because the per-reason counter and the journal event fire at the
admission seam, not here.
"""

from __future__ import annotations

import json
import threading
from pathlib import Path
from typing import Dict, Optional

from ..utils.guards import published
from ..utils.logging import get_logger

log = get_logger("microrank_tpu.ingest")

QUARANTINE_NAME = "quarantine.jsonl"

#: The rejection-reason taxonomy. Every quarantined row names exactly
#: one of these; the per-reason counter and the DESIGN.md table use the
#: same strings.
REASONS = (
    "bad_timestamp",      # start/end would not coerce to a datetime
    "bad_duration",       # duration non-numeric or negative
    "duration_overflow",  # duration past IngestConfig.max_duration_us
    "missing_id",         # empty/null traceID or spanID
    "dup_span",           # duplicate (traceID, spanID) — first kept
    "orphan",             # parent span absent (orphan_policy="drop")
    "clock_skew",         # start beyond skew_reject_seconds of the window
    "trace_too_long",     # spans past max_spans_per_trace (truncated)
    "vocab_budget",       # op past max_ops_per_window (cardinality bomb)
    "unparseable_line",   # tail line that never parsed (byte offset kept)
    "low_admission",      # whole window below min_admission_ratio
)


class QuarantineStore:
    """Bounded JSONL dead-letter writer (thread-safe: sources, the
    engine thread and serve's build pool all reject rows)."""

    def __init__(self, path=None, max_bytes: int = 16 << 20):
        from ..utils.guards import TrackedLock, register_shared

        self.path = Path(path) if path is not None else None
        self.max_bytes = int(max_bytes)
        self._lock = TrackedLock("quarantine")
        register_shared("quarantine", {"quarantine"})
        self.records = 0
        self.dropped = 0
        self._bytes = 0
        if self.path is not None and self.path.exists():
            self._bytes = self.path.stat().st_size

    # -------------------------------------------------------------- intake
    def put_frame(
        self,
        frame,
        reasons: Dict[str, "object"],
        source: str = "",
    ) -> int:
        """Quarantine rejected rows of one frame. ``reasons`` maps a
        reason string to a boolean row mask (pandas/numpy); a row
        matching several masks records its FIRST reason in taxonomy
        order, so every rejected row appears exactly once."""
        import numpy as np

        taken = None
        lines = []
        for reason in REASONS:
            mask = reasons.get(reason)
            if mask is None:
                continue
            m = np.asarray(mask, dtype=bool)
            if taken is None:
                taken = np.zeros(m.shape, dtype=bool)
            m = m & ~taken
            taken |= m
            if not m.any():
                continue
            sub = frame.iloc[np.flatnonzero(m)]
            for rec in sub.to_dict(orient="records"):
                lines.append(self._record(rec, reason, source))
        return self._write(lines)

    def put_raw(
        self,
        payload,
        reason: str,
        source: str = "",
        offset: Optional[int] = None,
    ) -> int:
        """Quarantine one raw (unparseable) source line, with the byte
        offset it occupied so an operator can find it in the file."""
        if isinstance(payload, bytes):
            payload = payload.decode("utf-8", errors="replace")
        rec = self._record(
            {"raw": payload.rstrip("\n")}, reason, source
        )
        if offset is not None:
            rec["offset"] = int(offset)
        return self._write([rec])

    # ------------------------------------------------------------ plumbing
    @staticmethod
    def _record(row: dict, reason: str, source: str) -> dict:
        import time

        clean = {}
        for k, v in row.items():
            # JSONL must always serialize: timestamps/NaT/numpy scalars
            # render as strings, everything else passes through.
            try:
                json.dumps(v)
                clean[k] = v
            except (TypeError, ValueError):
                clean[k] = str(v)
        return {
            "reason": reason,
            "source": source,
            "ts": time.time(),
            "row": clean,
        }

    def _write(self, records) -> int:
        from ..utils.guards import note_shared_access

        if not records:
            return 0
        lines = [json.dumps(r, default=str) + "\n" for r in records]
        kept = []
        with self._lock:
            note_shared_access("quarantine")
            if self.path is None:
                # Unconfigured (library use): count only, no cap — the
                # records exist nowhere, so there is nothing to bound.
                self.records += len(lines)
                return len(lines)
            for line in lines:
                if self._bytes + len(line) > self.max_bytes:
                    self.dropped += 1
                    continue
                self._bytes += len(line)
                self.records += 1
                kept.append(line)
            dropped_now = len(lines) - len(kept)
        if self.path is not None and kept:
            try:
                self.path.parent.mkdir(parents=True, exist_ok=True)
                with open(self.path, "a") as f:
                    f.writelines(kept)
            except OSError as e:  # pragma: no cover - disk trouble must
                # not convert a data rejection into an engine crash.
                log.warning("quarantine write failed: %s", e)
        if dropped_now:
            from ..obs.metrics import record_quarantine_dropped

            record_quarantine_dropped(dropped_now)
            log.warning(
                "quarantine full (%d bytes cap): dropped %d record(s)",
                self.max_bytes, dropped_now,
            )
        return len(kept)


# -------------------------------------------------------- process store

_store: Optional[QuarantineStore] = None


def configure_quarantine(ingest_config, default_dir=None) -> QuarantineStore:
    """Install the process dead-letter store (one per run entry —
    stream engine, serve service, batch runners all call this with
    their out_dir). ``IngestConfig.quarantine_dir`` overrides the run
    dir; neither configured means a counting-only store. Installed at
    run entry before worker threads spin up; seam threads read the
    binding lock-free by design (mrlint R10's ``published`` seam)."""
    global _store
    qdir = getattr(ingest_config, "quarantine_dir", None) or default_dir
    path = Path(qdir) / QUARANTINE_NAME if qdir is not None else None
    max_bytes = getattr(ingest_config, "quarantine_max_bytes", 16 << 20)
    _store = published(QuarantineStore(path, max_bytes=max_bytes))
    return _store


def get_quarantine() -> QuarantineStore:
    """The process store; a counting-only fallback when none was
    configured (rejection must never crash OR silently vanish)."""
    global _store
    if _store is None:
        _store = published(QuarantineStore(None))
    return _store
