"""The batching scheduler: one thread owns the device.

Requests enter per-tenant FIFOs (the HTTP frontend's threads only
enqueue); a single scheduler thread pops them **fairly** (round-robin
across tenants, so one chatty tenant cannot starve the rest), hands the
host half (parse -> detect -> partition -> padded graph build) to the
build worker pool (stream.pool — the seam shared with the streaming
engine), parks rankable windows in the micro-batcher's shape buckets,
and dispatches full or aged batches. Host builds overlap device
dispatch under load; every DEVICE touch stays on the scheduler thread —
single-threaded device ownership is the program-order guarantee jax
dispatch needs, the serving twin of the offline runners' rule that
collectives are issued by one thread. ``build_pool=None``
(ServeConfig.build_workers=0) restores serial builds on the scheduler
thread.

Drain: ``stop(drain=True)`` (the SIGTERM path) processes everything
already admitted — queues empty, every bucket force-flushed, every
future resolved — before the thread exits; ``drain=False`` fails queued
requests fast with a shutdown error.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Callable, Optional

from ..sched import WeightedFairQueue
from .batcher import MicroBatcher
from .protocol import RankRequest

_IDLE_POLL_S = 0.2


class ShutdownError(RuntimeError):
    """Queued request abandoned by a non-draining shutdown."""

    status = 503


class BatchScheduler(threading.Thread):
    def __init__(
        self, service, journal=None, build_pool=None, router=None,
        flight=None, sched=None,
    ):
        super().__init__(name="mr-serve-sched", daemon=True)
        self.service = service
        # Co-deploy: ``sched`` is the unified DeviceScheduler sharing
        # the device with stream/backfill. Built windows then park into
        # ITS store (the batcher dispatches when called back from the
        # scheduler thread that owns the device); this thread keeps the
        # host half — fair dequeue and build-pool handoff — and never
        # touches the device. Solo (sched=None) it owns the device
        # exactly as before.
        self.sched = sched
        self.batcher = MicroBatcher(
            service.config, journal=journal, router=router, flight=flight,
            store=sched.store if sched is not None else None,
        )
        self.build_pool = build_pool
        self._cond = threading.Condition()
        # Weighted fair dequeue across tenant FIFOs (sched.store): with
        # the default all-equal weights the pop order is exactly the
        # old round-robin interleave; SchedConfig.tenant_weights skews
        # turns toward heavier tenants.
        sched_cfg = getattr(service.config, "sched", None)
        self._queue = WeightedFairQueue(
            dict(sched_cfg.tenant_weights) if sched_cfg else {},
            sched_cfg.default_weight if sched_cfg else 1.0,
        )
        self._builds = 0             # host builds in flight on the pool
        self._stopping = False
        self._draining = False

    # ------------------------------------------------------------ intake
    def submit(
        self,
        request: RankRequest,
        on_done: Optional[Callable] = None,
    ) -> Future:
        """Enqueue one admitted request; returns its response future.
        The request's trace root (trace_id = request_id) is minted here
        — at admission — so queue time is inside the ``request`` span.
        A caller ``traceparent`` header overrides the trace id: the
        request's spans then JOIN the caller's distributed trace (the
        root span additionally parent-links to the caller's span id,
        serve.server.build_pending)."""
        from ..obs.spans import get_tracer

        fut: Future = Future()
        tp = getattr(request, "traceparent", None)
        ctx = get_tracer().new_trace(
            tp[0] if tp else request.request_id
        )
        entry = (request, fut, time.monotonic(), on_done, ctx)
        with self._cond:
            if self._stopping:
                fut.set_exception(ShutdownError("service shutting down"))
                return fut
            self._queue.push(request.tenant, entry)
            self._cond.notify()
        return fut

    def queued(self) -> int:
        with self._cond:
            return len(self._queue)

    # ------------------------------------------------------- fair dequeue
    def _pop_fair(self, timeout: float):
        """Weighted-fair pop across tenant FIFOs (stride scheduling,
        sched.WeightedFairQueue): each turn serves the backlogged
        tenant with the least accumulated virtual time, so one chatty
        tenant cannot starve the rest — and configured tenant weights
        buy proportionally more turns. Equal weights reproduce the old
        round-robin interleave exactly."""
        with self._cond:
            if not self._queue:
                self._cond.wait(timeout=max(0.0, timeout))
            return self._queue.pop()

    # --------------------------------------------------------------- run
    def run(self) -> None:
        from ..utils.guards import claim_device_owner

        # The scheduler thread IS the device owner on the serve path
        # (mrlint R8 / mrsan): every staging/dispatch/fetch and the
        # degrade fallback happen here; the HTTP threads only enqueue
        # and the build pool only does host work. Co-deployed, the
        # unified DeviceScheduler owns the device instead — this thread
        # then only dequeues/builds and parks into the shared store.
        if self.sched is None:
            claim_device_owner("serve-scheduler")
        while True:
            deadline = self.batcher.next_deadline()
            timeout = (
                _IDLE_POLL_S
                if deadline is None
                else min(_IDLE_POLL_S, max(0.0, deadline - time.monotonic()))
            )
            entry = self._pop_fair(timeout)
            if entry is not None:
                self._process(entry)
            # In-flight (already built or still building) windows always
            # complete at shutdown — only queued-not-yet-built requests
            # are failed by a non-draining stop. One condition hold for
            # the whole read: _stopping is written by stop() on another
            # thread (mrlint R10 — the force decision must see a
            # consistent (stopping, queued, builds) triple).
            with self._cond:
                force = (
                    self._stopping
                    and not self._queue
                    and self._builds == 0
                )
            # All ready batches dispatch through the router pipelined:
            # batch i+1's staging (host pack + H2D) overlaps batch i's
            # device execution (dispatch router double-buffering).
            # Co-deployed, take_ready is empty (windows parked in the
            # shared store) and a drain instead force-kicks the
            # unified scheduler to flush the serve lane.
            self.batcher.dispatch_ready(
                self.batcher.take_ready(force=force)
            )
            if self.sched is not None and force:
                self.sched.kick(force=True)
            with self._cond:
                if (
                    self._stopping
                    and not self._queue
                    and self._builds == 0
                    and self.batcher.pending() == 0
                ):
                    return

    def builds_inflight(self) -> int:
        with self._cond:
            return self._builds

    def _expire_if_past_deadline(self, entry) -> bool:
        """Per-request ``deadline_ms``: a queued request whose caller
        deadline elapsed before its window staged is expired HERE (504
        + journal event) — a burst cannot dispatch device work nobody
        is waiting for. Returns True when the entry was expired."""
        request, fut, enqueued, on_done, _ctx = entry
        dl = getattr(request, "deadline_ms", None)
        if not dl:
            return False
        waited_ms = (time.monotonic() - enqueued) * 1e3
        if waited_ms <= float(dl):
            return False
        from .protocol import DeadlineExceeded

        err = DeadlineExceeded(
            f"request {request.request_id} expired in queue: waited "
            f"{waited_ms:.0f} ms of a {float(dl):.0f} ms deadline"
        )
        if not fut.done():
            fut.set_exception(err)
        if on_done is not None:
            on_done(None, err)
        journal = getattr(self.service, "journal", None)
        if journal is not None:
            journal.emit(
                "request_deadline_expired",
                request_id=request.request_id,
                tenant=request.tenant,
                deadline_ms=float(dl),
                waited_ms=round(waited_ms, 3),
                stage="queue",
            )
        return True

    def _process(self, entry) -> None:
        from ..obs.spans import get_tracer

        if self._expire_if_past_deadline(entry):
            return
        request, fut, enqueued, on_done, ctx = entry
        tracer = get_tracer()
        if self.build_pool is None:
            with tracer.attach(ctx):
                pw = self.service.build_pending(
                    request, fut, enqueued, on_done
                )
            if pw is not None:
                self.batcher.submit(pw)
            return
        # Host half off-thread: the pool builds while THIS thread keeps
        # dispatching ready batches; the completion callback parks the
        # built window (batcher.submit is thread-safe) and nudges the
        # scheduler, which alone touches the device.
        with self._cond:
            self._builds += 1

        def _done(f):
            pw = None
            try:
                pw = f.result()
            except Exception as e:  # noqa: BLE001 - build_pending
                # resolves its own failures; this catches only wrapper
                # faults, which must still answer the request.
                if not fut.done():
                    fut.set_exception(e)
                    if on_done is not None:
                        on_done(None, e)
            if pw is not None:
                self.batcher.submit(pw)
            with self._cond:
                self._builds -= 1
                self._cond.notify()

        # attach: the pool captures the scheduler thread's ambient
        # context at submit, carrying the request trace onto the worker.
        with tracer.attach(ctx):
            self.build_pool.submit(
                self.service.build_pending,
                request, fut, enqueued, on_done,
                on_done=_done,
            )

    # -------------------------------------------------------------- stop
    def stop(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop the thread; ``drain`` answers everything admitted first."""
        with self._cond:
            self._stopping = True
            self._draining = drain
            if not drain:
                for request, fut, _, on_done, _ctx in (
                    self._queue.drain_items()
                ):
                    err = ShutdownError("service shutting down")
                    fut.set_exception(err)
                    if on_done is not None:
                        on_done(None, err)
            self._cond.notify_all()
        if self.is_alive():
            self.join(timeout=timeout)
