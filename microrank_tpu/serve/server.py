"""The online RCA service: asyncio HTTP frontend + service facade.

``cli serve`` wires this up: fit the SLO baseline from a normal-period
dump, optionally pre-stage named abnormal dumps, then answer
``POST /rank`` requests — each one a detection window — with ranked
suspects. Concurrent requests coalesce into padded micro-batches
(serve.batcher), admission control bounds the queue (serve.admission),
and SIGTERM drains in-flight work before exit.

Routes:

* ``POST /rank``     — rank one window (see serve.protocol for payloads);
* ``GET /healthz``   — liveness + drain state + queue depth (JSON);
* ``GET /metrics``   — Prometheus text exposition (same registry the
  offline pipelines record into);
* ``GET /metrics.json`` — the JSON snapshot form.

The frontend is stdlib-only asyncio (no aiohttp in the image): a
hand-rolled HTTP/1.1 parser over ``asyncio.start_server`` streams. The
event loop never blocks on device work — handlers await the scheduler's
response futures via ``asyncio.wrap_future``.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from pathlib import Path
from typing import Dict, Optional

from ..config import MicroRankConfig
from ..pipeline.results import WindowResult
from ..utils.logging import get_logger
from .admission import AdmissionController
from .protocol import (
    ProtocolError,
    RankRequest,
    error_body,
    parse_rank_request,
    response_body,
    spans_to_frame,
)
from .scheduler import BatchScheduler


class ServiceOverloaded(Exception):
    """Admission queue full — HTTP 429 + Retry-After."""

    status = 429


class ServiceDraining(Exception):
    """Shutdown in progress — HTTP 503 + Retry-After."""

    status = 503


class ServeService:
    """Service facade: baseline + datasets + admission + scheduler."""

    def __init__(self, config: MicroRankConfig, out_dir=None, sched=None):
        self.config = config
        self.serve = config.serve
        # Co-deploy: a sched.DeviceScheduler shared with stream/replay
        # lanes. The batch scheduler then parks built windows into its
        # store instead of owning the device itself.
        self.sched = sched
        self.log = get_logger("microrank_tpu.serve")
        self.admission = AdmissionController(
            self.serve.max_queue_depth, self.serve.retry_after_seconds
        )
        self.journal = None
        self.out_dir = Path(out_dir) if out_dir is not None else None
        if self.out_dir is not None and config.runtime.telemetry:
            from ..obs import JOURNAL_NAME, RunJournal, set_current_journal

            self.journal = RunJournal(self.out_dir / JOURNAL_NAME)
            set_current_journal(self.journal)
        self.build_pool = None
        if self.serve.build_workers > 0:
            from ..stream.pool import BuildWorkerPool

            self.build_pool = BuildWorkerPool(
                self.serve.build_workers, name="mr-serve-build"
            )
        # Persistent compile cache + the shared dispatch router (size-
        # aware sharded/vmapped routing, double-buffered staging). The
        # cache dir is wired before any jit so warmup compiles land on
        # disk and a restart reloads them.
        from ..dispatch import (
            CompileCacheProbe,
            DispatchRouter,
            configure_compile_cache,
        )

        self.cache_dir = configure_compile_cache(config.runtime)
        self.cache_probe = CompileCacheProbe(self.cache_dir)
        self.router = DispatchRouter(config)
        # Flight recorder: degraded dispatches and the SIGTERM drain
        # dump the span ring + journal + metrics to out_dir/flight/.
        self.flight = None
        if self.out_dir is not None:
            from ..obs import FlightRecorder

            self.flight = FlightRecorder(
                self.out_dir, config.obs, journal=self.journal
            )
        self.scheduler = BatchScheduler(
            self,
            journal=self.journal,
            build_pool=self.build_pool,
            router=self.router,
            flight=self.flight,
            sched=sched,
        )
        # Dynamic Retry-After: the batcher feeds measured per-window
        # dispatch cost into the admission EWMA; 429s then advertise
        # queue_depth x cost — actual drain time — not a constant.
        self.scheduler.batcher.cost_observer = (
            self.admission.observe_window_cost
        )
        # Shape-faithful warmup: the batcher records each production
        # (kernel, occupancy, leaf shapes) it dispatches into the
        # warmup manifest next to the compile cache.
        self.scheduler.batcher.cache_dir = self.cache_dir
        self.datasets: Dict[str, object] = {}
        self.slo_vocab = None
        self.baseline = None
        self.policy_resolution = None   # set by fit_baseline
        self.draining = False
        self._stopped = False

    # ------------------------------------------------------------- setup
    def fit_baseline(self, normal_df) -> None:
        from ..detect import compute_slo
        from ..scenarios.policy import apply_tuned_policy

        # Tuned-policy resolution (the shared lane seam): the normal
        # dump is the workload-profile witness. The router and the
        # batcher captured the un-tuned config at construction; both
        # re-point here, BEFORE warmup traces any program.
        self.config, self.policy_resolution = apply_tuned_policy(
            self.config, lane="serve", profile_frame=normal_df
        )
        self.router.config = self.config
        self.scheduler.batcher.config = self.config
        self.slo_vocab, self.baseline = compute_slo(
            normal_df, stat=self.config.detector.slo_stat
        )
        self.log.info(
            "fitted SLO baseline: %d operations", len(self.slo_vocab)
        )

    def add_dataset(self, name: str, span_df) -> None:
        """Pre-stage an abnormal dump; requests address it by name."""
        self.datasets[name] = span_df
        self.log.info("staged dataset %r: %d spans", name, len(span_df))

    def start(self) -> None:
        from ..analysis.mrsan import configure_sanitizers
        from ..chaos import configure_chaos, set_chaos_journal
        from ..obs import configure_tracer
        from ..obs.metrics import ensure_catalog
        from ..utils.guards import claim_device_owner

        if self.baseline is None:
            raise RuntimeError("call fit_baseline() before start()")
        ensure_catalog()
        configure_tracer(self.config.obs)  # fresh span ring per service
        configure_sanitizers(self.config)  # mrsan arm/disarm + reset
        configure_chaos(self.config)       # fault plan arm/disarm
        set_chaos_journal(self.journal)    # fault_injected -> journal
        from ..ingest import configure_quarantine

        # Dead-letter store next to the service outputs: rows span
        # admission refuses (hostile payload fragments) land in
        # quarantine.jsonl; unsalvageable payloads answer 422.
        configure_quarantine(
            self.config.ingest, default_dir=self.out_dir
        )
        # Warmup dispatches run on THIS thread before the scheduler
        # exists; the scheduler thread re-claims when it starts.
        # Co-deployed, the unified DeviceScheduler already owns the
        # device — warmup routes through it instead (below), and
        # claiming here would steal ownership from its thread.
        if self.sched is None:
            claim_device_owner("serve-warmup")
        if self.journal is not None:
            self.journal.run_start(
                pipeline="serve",
                kernel=self.config.runtime.kernel,
                pad_policy=self.config.runtime.pad_policy,
                max_batch_windows=self.serve.max_batch_windows,
                max_wait_ms=self.serve.max_wait_ms,
                max_queue_depth=self.serve.max_queue_depth,
            )
            if self.policy_resolution is not None:
                # Journal evidence of the tuned-policy consultation
                # (resolved at fit_baseline, after run_start on disk).
                self.journal.emit(
                    "policy", **self.policy_resolution.journal()
                )
        if self.serve.warmup:
            occs = self.serve.warmup_occupancies
            bad = [
                o
                for o in occs
                if int(o) < 1 or int(o) > self.serve.max_batch_windows
            ]
            if not occs or bad:
                raise ValueError(
                    f"warmup_occupancies {tuple(occs)} invalid: every "
                    f"entry must be in [1, max_batch_windows="
                    f"{self.serve.max_batch_windows}]"
                )
            if self.sched is not None:
                from ..sched import LANE_SERVE

                self.sched.run_on(LANE_SERVE, "serve", self.warmup)
            else:
                self.warmup()
        self.scheduler.start()

    def warmup(self) -> None:
        """Trace the batched rank program before traffic: one dispatch
        per configured occupancy (ServeConfig.warmup_occupancies) over
        a small synthetic window — a full batch at an uncompiled
        occupancy would otherwise pay a first-hit compile under
        traffic. The persistent compile cache (dispatch.cache) turns
        each compile into a disk reload on restart, and the warmup
        MANIFEST extends the set: occupancies a previous process warmed
        (or served) replay too, so a redeploy re-traces everything it
        will need while every compile hits the cache. Runs before the
        scheduler thread starts — exclusive device use; warmup
        dispatches don't pollute the occupancy/route metrics."""
        from ..dispatch import (
            manifest_occupancies,
            record_manifest_entry,
            warm_occupancies,
        )
        from ..obs.metrics import record_compile_cache

        t0 = time.monotonic()
        occupancies = sorted(
            {int(o) for o in self.serve.warmup_occupancies}
        )
        recorded = [
            o
            for o in manifest_occupancies(self.cache_dir, "serve")
            if 1 <= o <= self.serve.max_batch_windows
        ]
        if recorded:
            # Warm restart: a previous serve process left its program
            # manifest — replay it (compiles are cache reloads).
            record_compile_cache("warm_start")
            occupancies = sorted(set(occupancies) | set(recorded))
        kernel = warm_occupancies(
            self.router, self.config, occupancies, probe=self.cache_probe
        )
        if kernel is None:
            return
        record_manifest_entry(self.cache_dir, "serve", kernel, occupancies)
        # Shape-faithful pass: the manifest also carries the EXACT
        # (kernel, occupancy, padded leaf shapes) of production pad
        # buckets a previous process dispatched (batcher._record_shapes)
        # — replay them so the first real window after a restart hits
        # an already-traced program, not a same-occupancy-different-
        # shape approximation. p99 first-window latency ~ steady state.
        shaped = 0
        if self.config.sched.shape_warmup:
            from ..dispatch import warm_manifest_shapes

            shaped = warm_manifest_shapes(
                self.router, self.config, self.cache_dir, "serve",
                probe=self.cache_probe,
            )
        self.log.info(
            "warmup: batched rank program ready (occupancies %s, kernel "
            "%s, %d production shapes, compile cache %d hit / %d miss) "
            "in %.1fs",
            occupancies, kernel, shaped, self.cache_probe.hits,
            self.cache_probe.misses, time.monotonic() - t0,
        )

    # ----------------------------------------------------------- request
    def submit(self, request: RankRequest):
        """Admission-checked entry: returns the response future, or
        raises ServiceOverloaded/ServiceDraining."""
        from ..obs.metrics import record_serve_request

        if self.draining:
            record_serve_request("rejected")
            raise ServiceDraining("service is draining")
        if not self.admission.try_admit():
            record_serve_request("rejected")
            raise ServiceOverloaded("request queue is full")
        return self.scheduler.submit(request, on_done=self._on_done)

    def _on_done(self, pw, error) -> None:
        """Completion hook for every admitted request, on every path
        (ranked, clean, degraded, failed, shutdown): release the
        admission slot, record outcome + latency, journal the window."""
        from ..obs.metrics import record_serve_request

        self.admission.release()
        if pw is None:  # expired in queue, or abandoned by a
            # non-draining shutdown — no built window to journal.
            from .protocol import DeadlineExceeded

            record_serve_request(
                "expired"
                if isinstance(error, DeadlineExceeded)
                else "failed"
            )
            return
        result = pw.result
        total_s = time.monotonic() - pw.enqueued
        if error is not None:
            from .protocol import DeadlineExceeded

            if isinstance(error, ProtocolError):
                outcome = "invalid"
            elif isinstance(error, DeadlineExceeded):
                outcome = "expired"
            else:
                outcome = "failed"
        elif result.ranking:
            outcome = "ranked"
        elif result.skipped_reason:
            outcome = "skipped"
        else:
            outcome = "clean"
        record_serve_request(outcome, total_s)
        if self.journal is not None and error is None:
            self.journal.window(result)

    def build_pending(self, request, fut, enqueued, on_done):
        """Scheduler-thread host half: window frame -> detect ->
        partition -> padded graph. Returns a PendingWindow to coalesce,
        or None when the request resolved immediately (clean window,
        degenerate partition, bad payload)."""
        from ..obs.metrics import serve_stage_seconds
        from ..obs.spans import get_tracer
        from .batcher import PendingWindow

        tracer = get_tracer()
        queue_s = time.monotonic() - enqueued
        serve_stage_seconds().observe(queue_s, stage="queue")
        result = WindowResult(
            start="", end="", anomaly=False,
            request_id=request.request_id, tenant=request.tenant,
        )
        result.timings["queue_ms"] = round(queue_s * 1e3, 3)
        pw = PendingWindow(
            request=request, result=result, span_df=None,
            normal_ids=[], abnormal_ids=[], graph=None, op_names=[],
            kernel="", future=fut, enqueued=enqueued, on_done=on_done,
            # Root span bookkeeping: the ambient context is the request
            # trace the scheduler attached (queue time backdated into
            # the root span's start); a caller traceparent additionally
            # parent-links the root span to the caller's span.
            ctx=tracer.current_context(),
            t0_us=int((time.time() - queue_s) * 1e6),
            parent_span=(
                request.traceparent[1]
                if getattr(request, "traceparent", None)
                else None
            ),
        )
        t0 = time.monotonic()
        try:
            with tracer.span("parse", service="serve"):
                window_df = self._window_frame(request)
            parse_s = time.monotonic() - t0
            result.timings["parse_ms"] = round(parse_s * 1e3, 3)
            if self.config.ingest.enabled:
                # Span admission: the full per-row ladder (the request
                # IS the window). Unsalvageable payloads 422 with the
                # per-reason counts; salvageable ones rank degraded-
                # but-correct on the clean subset.
                from ..ingest import admit_frame

                t_adm = time.monotonic()
                with tracer.span("admit", service="serve"):
                    adm = admit_frame(
                        window_df,
                        self.config.ingest,
                        source=f"serve:{request.request_id}",
                        known_ops=(
                            frozenset(self.slo_vocab.names)
                            if self.slo_vocab is not None
                            else None
                        ),
                    )
                result.timings["admit_ms"] = round(
                    (time.monotonic() - t_adm) * 1e3, 3
                )
                result.ingest_rejected = adm.n_rejected
                result.degraded_input = adm.degraded
                if adm.degraded and self.journal is not None:
                    self.journal.emit(
                        "ingest",
                        stage="serve",
                        request_id=request.request_id,
                        tenant=request.tenant,
                        **adm.journal_fields(),
                    )
                if adm.n_admitted == 0:
                    from .protocol import AdmissionError

                    raise AdmissionError(adm.rejected)
                window_df = adm.frame
            result.start = str(window_df["startTime"].min())
            result.end = str(window_df["endTime"].max())
            t_det = time.monotonic()
            with tracer.span("detect", service="serve"):
                flag, nrm, abn = _detect_partition(
                    self.config, self.slo_vocab, self.baseline, window_df
                )
            result.timings["detect_ms"] = round(
                (time.monotonic() - t_det) * 1e3, 3
            )
            result.anomaly = bool(flag)
            result.n_normal, result.n_abnormal = len(nrm), len(abn)
            result.n_traces = len(nrm) + len(abn)
            if not flag:
                pw.finish()
                return None
            if not nrm or not abn:
                result.skipped_reason = "degenerate_partition"
                pw.finish()
                return None
            from ..rank_backends.jax_tpu import (
                prepare_window_graph,
                prepare_window_graph_explained,
            )

            if getattr(request, "explain", False):
                # explain:true — the build also retains the coverage-
                # column map the bundle joins attributions against.
                graph, names, kernel, pw.explain_ctx = (
                    prepare_window_graph_explained(
                        window_df, nrm, abn, self.config
                    )
                )
            else:
                graph, names, kernel = prepare_window_graph(
                    window_df, nrm, abn, self.config
                )
        except Exception as e:
            pw.finish(error=e)
            return None
        build_s = time.monotonic() - t0
        serve_stage_seconds().observe(build_s, stage="build")
        result.timings["build_ms"] = round(build_s * 1e3, 3)
        result.kernel = kernel
        pw.span_df = window_df
        pw.normal_ids, pw.abnormal_ids = nrm, abn
        pw.graph, pw.op_names, pw.kernel = graph, names, kernel
        pw.built = time.monotonic()
        return pw

    def _window_frame(self, request: RankRequest):
        if request.spans is not None:
            return spans_to_frame(request.spans)
        df = self.datasets.get(request.dataset)
        if df is None:
            raise ProtocolError(
                f"unknown dataset {request.dataset!r}; staged: "
                f"{sorted(self.datasets)}"
            )
        import pandas as pd

        from ..io.loader import window_spans

        start = (
            pd.Timestamp(request.start) if request.start else None
        )
        end = pd.Timestamp(request.end) if request.end else None
        out = window_spans(df, start, end)
        if len(out) == 0:
            raise ProtocolError(
                f"dataset {request.dataset!r} has no spans in "
                f"[{request.start}, {request.end}]"
            )
        return out

    # ---------------------------------------------------------- shutdown
    def begin_drain(self) -> None:
        """Stop admitting; everything admitted will still be answered."""
        self.draining = True
        self.admission.close()

    def shutdown(self, drain: bool = True, timeout=None) -> None:
        if self._stopped:
            return
        self._stopped = True
        self.begin_drain()
        if timeout is None:
            timeout = self.serve.drain_seconds
        if self.scheduler.is_alive() or self.scheduler.queued():
            self.scheduler.stop(drain=drain, timeout=timeout)
            if self.sched is not None and drain:
                # Parked serve windows flush on the unified scheduler's
                # thread; wait for the store to empty and the last
                # batch to resolve before journaling run_end.
                self.sched.kick(force=True)
                self.sched.wait_idle(timeout=timeout or 30.0)
        elif not self.scheduler.is_alive():
            # never started (direct-drive tests): flush parked work
            self.scheduler._stopping = True
            self.scheduler.batcher.dispatch_ready(
                self.scheduler.batcher.take_ready(force=True)
            )
            if self.sched is not None:
                self.sched.kick(force=True)
                self.sched.wait_idle(timeout=timeout or 30.0)
        if self.build_pool is not None:
            self.build_pool.shutdown()
        if self.journal is not None:
            self.journal.run_end(dispatches=self.scheduler.batcher.dispatches)
        if self.flight is not None:
            # SIGTERM drain: the last flight dump is the shutdown's
            # black box — ring + fsync'd journal + final metrics.
            self.flight.dump("sigterm")
        if self.out_dir is not None and self.config.runtime.telemetry:
            from ..obs import get_registry
            from ..obs.metrics import ensure_catalog

            ensure_catalog()
            get_registry().write_snapshot(self.out_dir)


def _case_slo(case):
    from ..detect import compute_slo

    return compute_slo(case.normal)


def _detect_partition(config, slo_vocab, baseline, window_df):
    """Detect + partition one window frame (shared with the streaming
    engine — detect.detect_partition)."""
    from ..detect import detect_partition

    return detect_partition(config, slo_vocab, baseline, window_df)


# ---------------------------------------------------------------- HTTP


class HttpFrontend:
    """Minimal asyncio HTTP/1.1 frontend over the service."""

    def __init__(self, service: ServeService, host="127.0.0.1", port=0):
        self.service = service
        self.host = host
        self.port = int(port)
        self._server: Optional[asyncio.AbstractServer] = None
        self._active = 0
        self._idle = asyncio.Event()
        self._idle.set()

    async def start(self) -> int:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def drain_and_close(self, timeout: float) -> None:
        """Stop accepting, then wait (bounded) for in-flight handlers."""
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        try:
            await asyncio.wait_for(self._idle.wait(), timeout=timeout)
        except asyncio.TimeoutError:
            self.service.log.warning(
                "drain timeout: %d request(s) still in flight",
                self._active,
            )

    # ---------------------------------------------------------- handling
    async def _handle(self, reader, writer) -> None:
        self._active += 1
        self._idle.clear()
        try:
            req = await self._read_request(reader)
            if req is None:
                return
            method, path, body, headers = req
            out = await self._route(method, path, body, headers)
            status, ctype, payload = out[:3]
            extra = out[3] if len(out) > 3 else None
            await self._respond(writer, status, ctype, payload, extra)
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, Exception):
                pass
            self._active -= 1
            if self._active == 0:
                self._idle.set()

    @staticmethod
    async def _read_request(reader):
        line = await reader.readline()
        if not line:
            return None
        try:
            method, path, _ = line.decode("latin-1").split(None, 2)
        except ValueError:
            return None
        headers = {}
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            name, _, value = h.decode("latin-1").partition(":")
            headers[name.strip().lower()] = value.strip()
        n = int(headers.get("content-length") or 0)
        body = await reader.readexactly(n) if n else b""
        return method.upper(), path.split("?")[0], body, headers

    async def _route(self, method, path, body, headers=None):
        svc = self.service
        if method == "POST" and path == "/rank":
            return await self._rank(body, headers or {})
        if method == "GET" and path == "/healthz":
            payload = json.dumps(
                {
                    "status": "draining" if svc.draining else "ok",
                    "queue_depth": svc.admission.depth,
                    "dispatches": svc.scheduler.batcher.dispatches,
                }
            ).encode()
            return 200, "application/json", payload
        if method == "GET" and path == "/metrics":
            from ..obs import get_registry
            from ..obs.server import PROM_CONTENT_TYPE

            return 200, PROM_CONTENT_TYPE, get_registry().to_prometheus().encode()
        if method == "GET" and path == "/metrics.json":
            from ..obs import get_registry

            return (
                200,
                "application/json",
                json.dumps(get_registry().to_json()).encode(),
            )
        return 404, "application/json", error_body("no such route")

    async def _rank(self, body, headers):
        svc = self.service
        retry = {"retry_after": svc.admission.retry_after()}
        try:
            # W3C trace context: the request's self-tracing spans join
            # the CALLER's distributed trace (serve.protocol).
            request = parse_rank_request(
                body, traceparent=headers.get("traceparent")
            )
        except ProtocolError as e:
            return 400, "application/json", error_body(str(e))
        try:
            fut = svc.submit(request)
        except (ServiceOverloaded, ServiceDraining) as e:
            return e.status, "application/json", error_body(str(e), **retry)
        try:
            result = await asyncio.wait_for(
                asyncio.wrap_future(fut),
                timeout=svc.serve.request_timeout_seconds,
            )
        except asyncio.TimeoutError:
            return (
                504,
                "application/json",
                error_body(
                    "request timed out in the service; its batch will "
                    "still complete and be journaled",
                    request_id=request.request_id,
                ),
            )
        except ProtocolError as e:
            # AdmissionError (status 422) carries the per-reason
            # rejection counts so the caller learns what was hostile.
            extra = {"request_id": request.request_id}
            rejected = getattr(e, "rejected", None)
            if rejected:
                extra["rejected"] = rejected
            return (
                getattr(e, "status", 400),
                "application/json",
                error_body(str(e), **extra),
            )
        except Exception as e:
            from .protocol import DeadlineExceeded

            if isinstance(e, DeadlineExceeded):
                # The service expired the request at its caller-supplied
                # deadline_ms before staging it — same status as the
                # frontend's own wait timeout, but no work was wasted.
                return (
                    504,
                    "application/json",
                    error_body(str(e), request_id=request.request_id),
                )
            return (
                500,
                "application/json",
                error_body(str(e), request_id=request.request_id),
            )
        # Server-Timing: the request's own stage durations land in the
        # caller's tracing next to the traceparent-joined spans.
        from .protocol import server_timing_header

        timing = server_timing_header(result.timings)
        extra = {"Server-Timing": timing} if timing else None
        return 200, "application/json", response_body(result), extra

    async def _respond(
        self, writer, status, ctype, payload, extra_headers=None
    ) -> None:
        reason = {
            200: "OK", 400: "Bad Request", 404: "Not Found",
            422: "Unprocessable Entity", 429: "Too Many Requests",
            500: "Internal Server Error", 503: "Service Unavailable",
            504: "Gateway Timeout",
        }.get(status, "OK")
        head = [
            f"HTTP/1.1 {status} {reason}",
            f"Content-Type: {ctype}",
            f"Content-Length: {len(payload)}",
            "Connection: close",
        ]
        if status in (429, 503):
            # Dynamic backpressure: queue depth x measured per-window
            # cost (admission EWMA), floored at the configured constant.
            retry = max(
                1, int(round(self.service.admission.retry_after()))
            )
            head.append(f"Retry-After: {retry}")
        for name, value in (extra_headers or {}).items():
            head.append(f"{name}: {value}")
        writer.write(
            ("\r\n".join(head) + "\r\n\r\n").encode() + payload
        )
        await writer.drain()


class ServeHandle:
    """Run the HTTP frontend on a background thread (tests, embedding).

    ``cli serve`` uses ``run_serve`` (foreground loop + signal
    handlers) instead; this wrapper exists so a test can start a fully
    wired service, speak real HTTP to it, and stop it deterministically.
    """

    def __init__(self, service: ServeService, host="127.0.0.1", port=0):
        self.service = service
        self.frontend = HttpFrontend(service, host, port)
        self.port: Optional[int] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._stop_evt: Optional[asyncio.Event] = None

    def start(self) -> int:
        started = threading.Event()

        async def _main():
            self._loop = asyncio.get_running_loop()
            self._stop_evt = asyncio.Event()
            self.port = await self.frontend.start()
            started.set()
            await self._stop_evt.wait()
            await self.frontend.drain_and_close(
                self.service.serve.drain_seconds
            )

        self._thread = threading.Thread(
            target=lambda: asyncio.run(_main()),
            name="mr-serve-http",
            daemon=True,
        )
        self._thread.start()
        if not started.wait(timeout=30):
            raise RuntimeError("HTTP frontend failed to start")
        return self.port

    def stop(self, drain: bool = True) -> None:
        self.service.begin_drain()
        if self._loop is not None and self._stop_evt is not None:
            self._loop.call_soon_threadsafe(self._stop_evt.set)
        if self._thread is not None:
            self._thread.join(timeout=self.service.serve.drain_seconds + 30)
        self.service.shutdown(drain=drain)


def run_serve(service: ServeService, host: str, port: int) -> int:
    """Foreground serve loop (``cli serve``): start the frontend, block
    until SIGTERM/SIGINT, then drain — in-flight batches complete, the
    metrics snapshot and journal land in the output directory."""
    import signal

    log = service.log

    async def _amain():
        frontend = HttpFrontend(service, host, port)
        bound = await frontend.start()
        loop = asyncio.get_running_loop()
        stop = asyncio.Event()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, stop.set)
        log.info(
            "serving RCA on http://%s:%d (POST /rank; /healthz, "
            "/metrics); max_batch=%d max_wait=%.0fms queue<=%d",
            host, bound, service.serve.max_batch_windows,
            service.serve.max_wait_ms, service.serve.max_queue_depth,
        )
        await stop.wait()
        log.info("signal received: draining in-flight requests")
        service.begin_drain()
        await frontend.drain_and_close(service.serve.drain_seconds)

    asyncio.run(_amain())
    service.shutdown(drain=True)
    log.info(
        "drained; %d batch dispatches served",
        service.scheduler.batcher.dispatches,
    )
    return 0
