"""Micro-batching across concurrent requests, keyed by pad buckets.

The offline pipelines already stack same-shaped window graphs and rank
them in one vmapped program (``dispatch_batch_windows``,
``batch_windows``); serving turns that inward-facing trick into the
request path: concurrent requests whose padded graphs land in the same
pad-policy bucket (``RuntimeConfig.pad_policy`` — the same buckets that
keep the jit cache small offline) stack into ONE device dispatch, so a
busy service amortizes dispatch/staging RPC overhead across tenants
exactly like a batching inference server amortizes a forward pass. A
bucket flushes when it reaches ``max_batch_windows`` or when its oldest
request has waited ``max_wait_ms``.

Graceful degradation: a failed device dispatch is retried once as a
batch; if the retry fails too, every member is re-ranked individually on
the ``numpy_ref`` oracle (pure host path, no jit, same semantics) and
the responses carry ``degraded: true`` — the service answers slowly
rather than not at all. No request is dropped on a device fault.
"""

from __future__ import annotations

import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..config import MicroRankConfig
from ..pipeline.results import WindowResult
from .protocol import RankRequest


# The shape-bucket key now lives in the dispatch router (PR 5) — the
# stream engine's burst coalescing uses the same buckets; re-exported
# here for existing importers.
from ..dispatch import bucket_key  # noqa: E402,F401


@dataclass
class PendingWindow:
    """One admitted request, built and parked for coalescing."""

    request: RankRequest
    result: WindowResult
    span_df: object                  # kept for the numpy_ref fallback
    normal_ids: List[str]
    abnormal_ids: List[str]
    graph: object
    op_names: List[str]
    kernel: str
    future: Future
    enqueued: float                  # monotonic, at admission
    built: float = 0.0               # monotonic, graph build done
    on_done: Optional[Callable] = None
    # Self-tracing: the request's root span context (obs.spans) and the
    # epoch-µs the request entered build — finish() records the root
    # ``request`` span from these once the response resolves. A caller
    # traceparent's span id lands in ``parent_span`` so the root span
    # joins the caller's distributed trace.
    ctx: object = None
    t0_us: int = 0
    parent_span: Optional[str] = None
    # Rank provenance: the build's coverage-column retention context
    # (explain.bundle.ExplainContext) when the request asked for an
    # explain bundle.
    explain_ctx: object = None
    _finished: bool = field(default=False, repr=False)

    def finish(self, error: Optional[BaseException] = None) -> None:
        if self._finished:
            return
        self._finished = True
        # Record the root span BEFORE resolving the future: the HTTP
        # response goes out the moment the future resolves, and a
        # caller reading the tracer ring right after the response must
        # find its request's span there.
        if self.ctx is not None and self.t0_us:
            from ..obs.spans import get_tracer

            get_tracer().record_span(
                "request",
                ctx=self.ctx,
                start_us=self.t0_us,
                dur_us=int(time.time() * 1e6) - self.t0_us,
                service="serve",
                parent_id=self.parent_span,
                tenant=self.request.tenant,
                degraded=bool(self.result.degraded),
                error=type(error).__name__ if error else None,
            )
        if error is not None:
            self.future.set_exception(error)
        else:
            self.future.set_result(self.result)
        if self.on_done is not None:
            self.on_done(self, error)


def _conv_summary(residuals, n_iters) -> dict:
    """Host-side summary of one window's FETCHED convergence row."""
    res = np.asarray(
        residuals,
        dtype=np.float64,  # mrlint: disable=R2(host-side summary of an already-fetched trace; never re-enters a jnp expression)
    )
    n = int(n_iters)
    joint = res.max(axis=0)[:n]
    return {
        "iterations": n,
        "final_residual": float(joint[-1]) if n else None,
        "residuals": [float(x) for x in joint],
    }


class MicroBatcher:
    """Owns the shape buckets and the device dispatch of full batches.

    Single-threaded by design: only the batching scheduler calls in
    (the lock guards the cheap bucket bookkeeping so stats can be read
    from the HTTP thread). Dispatch itself is synchronous — the
    scheduler thread is the device's program-order guarantee.
    """

    def __init__(
        self, config: MicroRankConfig, journal=None, router=None,
        flight=None, store=None,
    ):
        from ..dispatch import DispatchRouter

        self.config = config
        self.serve = config.serve
        self.journal = journal
        # Flight recorder (obs.flight): a degraded batch dumps the span
        # ring — the causal record of the dispatch that just failed.
        self.flight = flight
        # The shared dispatch seam (PR 5): size-aware sharded/vmapped
        # routing + double-buffered staging live there, not here.
        self.router = (
            router if router is not None else DispatchRouter(config)
        )
        # Co-deploy mode: a sched.ParkedWindowStore shared with the
        # stream engine and backfill. Built windows then park THERE
        # (lane=serve, keyed by the same bucket key) and the unified
        # DeviceScheduler — not the serve scheduler thread — dequeues
        # and calls ``dispatch`` back. Solo serve (store=None) keeps the
        # private buckets below, byte-for-byte the old behavior.
        self.store = store
        from ..utils.guards import TrackedLock, register_shared

        # The scheduler thread parks/pops; HTTP threads read stats —
        # a registered mrsan shared object (R10's runtime twin).
        self._lock = TrackedLock("serve_buckets")
        register_shared("serve_buckets", {"serve_buckets"})
        # bucket key -> FIFO of PendingWindow (insertion order = age).
        self._buckets: Dict[Tuple, List[PendingWindow]] = {}
        self._inject_failures = int(self.serve.inject_dispatch_failures)
        self.dispatches = 0
        # Retry-After pricing: set by ServeService to the admission
        # controller's cost observer; called with measured per-window
        # seconds after each successful dispatch.
        self.cost_observer: Optional[Callable[[float], None]] = None
        # Shape-faithful warmup: set by ServeService to its compile
        # cache dir; each distinct (kernel, occupancy, leaf shapes)
        # this batcher dispatches is recorded into the warmup manifest
        # once, so a restart replays the exact production pad buckets.
        self.cache_dir: Optional[str] = None
        self._recorded_shapes: set = set()

    # ------------------------------------------------------------ intake
    def submit(self, pw: PendingWindow) -> None:
        from ..utils.guards import note_shared_access

        key = bucket_key(pw.graph, pw.kernel)
        if self.store is not None:
            self._park_shared(pw, key)
            return
        with self._lock:
            note_shared_access("serve_buckets")
            self._buckets.setdefault(key, []).append(pw)

    def _park_shared(self, pw: PendingWindow, key) -> None:
        """Co-deploy intake: park into the shared store's serve lane.
        The DeviceScheduler dequeues by lane/fair-share/quota policy
        and calls ``dispatch`` with the coalesced batch; a deadline
        that lapses while parked expires at dequeue (504, same journal
        event as the private-bucket path)."""
        from ..sched import LANE_SERVE, ParkedEntry

        dl = getattr(pw.request, "deadline_ms", None)
        deadline = pw.enqueued + float(dl) / 1e3 if dl else None
        self.store.park(ParkedEntry(
            LANE_SERVE, pw.request.tenant, key, pw,
            runner=self.dispatch,
            expire=self._expire_parked,
            deadline=deadline,
        ))

    def _expire_parked(self, pw: PendingWindow) -> None:
        from .protocol import DeadlineExceeded

        waited_ms = (time.monotonic() - pw.enqueued) * 1e3
        dl = float(getattr(pw.request, "deadline_ms", 0) or 0)
        pw.result.skipped_reason = "deadline_expired"
        if self.journal is not None:
            self.journal.emit(
                "request_deadline_expired",
                request_id=pw.request.request_id,
                tenant=pw.request.tenant,
                deadline_ms=dl,
                waited_ms=round(waited_ms, 3),
                stage="batch",
            )
        pw.finish(error=DeadlineExceeded(
            f"request {pw.request.request_id} expired before dispatch: "
            f"waited {waited_ms:.0f} ms of a {dl:.0f} ms deadline"
        ))

    def pending(self) -> int:
        from ..utils.guards import note_shared_access

        if self.store is not None:
            from ..sched import LANE_SERVE

            return self.store.pending(LANE_SERVE)
        with self._lock:
            note_shared_access("serve_buckets")
            return sum(len(v) for v in self._buckets.values())

    def next_deadline(self) -> Optional[float]:
        """Monotonic time the oldest parked request must flush by.
        Co-deployed, flush timing belongs to the DeviceScheduler."""
        if self.store is not None:
            return None
        wait_s = max(0.0, float(self.serve.max_wait_ms)) / 1e3
        with self._lock:
            oldest = min(
                (b[0].built for b in self._buckets.values() if b),
                default=None,
            )
        return None if oldest is None else oldest + wait_s

    def take_ready(self, force: bool = False) -> List[List[PendingWindow]]:
        """Pop every bucket that is full, past its max-wait deadline, or
        (``force``, drain mode) non-empty."""
        if self.store is not None:
            return []  # the DeviceScheduler drains the shared store
        now = time.monotonic()
        wait_s = max(0.0, float(self.serve.max_wait_ms)) / 1e3
        cap = max(1, int(self.serve.max_batch_windows))
        from ..utils.guards import note_shared_access

        out: List[List[PendingWindow]] = []
        with self._lock:
            note_shared_access("serve_buckets")
            for key in list(self._buckets):
                bucket = self._buckets[key]
                while len(bucket) >= cap:
                    out.append(bucket[:cap])
                    del bucket[:cap]
                if bucket and (
                    force or now - bucket[0].built >= wait_s
                ):
                    out.append(bucket[:])
                    bucket.clear()
                if not bucket:
                    del self._buckets[key]
        return out

    # ---------------------------------------------------------- dispatch
    def dispatch_ready(self, batches: List[List[PendingWindow]]) -> None:
        """Dispatch every ready batch, double-buffered: batch i+1's
        staging is handed to the router as ``next_batch`` so its H2D
        transfer overlaps batch i's device execution. Per-batch failure
        isolation is unchanged — a failed batch retries then degrades
        without touching its neighbors (the router drops a prestaged
        handle whose batch never dispatches)."""
        for i, batch in enumerate(batches):
            nxt = batches[i + 1] if i + 1 < len(batches) else None
            self.dispatch(batch, next_items=nxt)

    def _expire_deadlined(
        self, items: List[PendingWindow]
    ) -> List[PendingWindow]:
        """Drop batch members whose caller ``deadline_ms`` elapsed
        while they were parked — their answer is already abandoned, so
        staging them only burns device time (504 + journal event)."""
        from .protocol import DeadlineExceeded

        live: List[PendingWindow] = []
        now = time.monotonic()
        for pw in items:
            dl = getattr(pw.request, "deadline_ms", None)
            waited_ms = (now - pw.enqueued) * 1e3
            if not dl or waited_ms <= float(dl):
                live.append(pw)
                continue
            pw.result.skipped_reason = "deadline_expired"
            if self.journal is not None:
                self.journal.emit(
                    "request_deadline_expired",
                    request_id=pw.request.request_id,
                    tenant=pw.request.tenant,
                    deadline_ms=float(dl),
                    waited_ms=round(waited_ms, 3),
                    stage="batch",
                )
            pw.finish(
                error=DeadlineExceeded(
                    f"request {pw.request.request_id} expired before "
                    f"dispatch: waited {waited_ms:.0f} ms of a "
                    f"{float(dl):.0f} ms deadline"
                )
            )
        return live

    def dispatch(
        self,
        items: List[PendingWindow],
        warmup=False,
        next_items: Optional[List[PendingWindow]] = None,
    ) -> None:
        """Rank one coalesced batch; resolves every member's future.

        The historical bare one-shot retry now rides the unified
        policy (chaos.retry DISPATCH_POLICY: max_attempts=2 keeps the
        same shape, plus jittered backoff, breaker accounting and the
        shared ``microrank_retry_attempts_total{seam="serve_dispatch"}``
        counter); exhaustion degrades exactly as before."""
        from ..chaos import DISPATCH_POLICY, retry_call

        if not warmup:
            items = self._expire_deadlined(items)
            if not items:
                return
        t0 = time.monotonic()
        route_info = None
        try:
            outs, route_info = retry_call(
                "serve_dispatch",
                lambda: self._device_dispatch(items, next_items),
                policy=DISPATCH_POLICY,
                on_retry=lambda attempt, e, delay: self._log().warning(
                    "batch dispatch failed (%d windows): %s; retrying",
                    len(items), e,
                ),
            )
        except Exception as final:
            self._degrade(items, final, warmup=warmup)
            return
        batch_ms = (time.monotonic() - t0) * 1e3
        self._assign(items, outs, batch_ms, route_info)
        if not warmup:
            from ..obs.metrics import record_serve_batch

            record_serve_batch(len(items))
            if self.cost_observer is not None:
                # Measured per-window cost -> admission's Retry-After
                # EWMA: a 429's back-off then prices actual drain time.
                self.cost_observer(batch_ms / 1e3 / max(1, len(items)))
            self._record_shapes(items, route_info)
        self.dispatches += 1
        self._explain_requests(items)
        self._journal_batch(
            items, batch_ms, degraded=0, warmup=warmup,
            route_info=route_info,
        )
        for pw in items:
            pw.finish()

    def _record_shapes(self, items, route_info) -> None:
        """Write this batch's (kernel, occupancy, padded leaf shapes)
        into the warmup manifest, once per distinct signature — a
        restarted process replays the EXACT production pad buckets
        (dispatch.warmup.warm_manifest_shapes), so its first real
        window after warmup is a jit-cache hit."""
        sched_cfg = getattr(self.config, "sched", None)
        if (
            self.cache_dir is None
            or sched_cfg is None
            or not sched_cfg.shape_warmup
            or not self.config.dispatch.warmup_manifest
            or not items
            or items[0].graph is None
        ):
            return
        kernel = route_info.kernel if route_info else items[0].kernel
        leaves = bucket_key(items[0].graph, kernel)[1:]
        sig = (kernel, len(items), leaves)
        if sig in self._recorded_shapes:
            return
        self._recorded_shapes.add(sig)
        from ..dispatch import record_manifest_entry

        record_manifest_entry(
            self.cache_dir, "serve", kernel, [len(items)],
            shapes=[{
                "occupancy": len(items),
                "leaves": [list(s) for s in leaves],
            }],
            max_shapes=sched_cfg.max_shapes,
        )

    def _explain_requests(self, items: List[PendingWindow]) -> None:
        """Rank provenance for ``explain: true`` members: ONE extra
        explained single-window dispatch per asking request, after the
        batch resolved (the batched hot path never carries the explain
        epilogue — requests that didn't ask pay nothing). Runs on the
        scheduler thread like every device touch; a failed explain
        degrades to a response without the bundle, never a failed
        request."""
        need = [
            pw
            for pw in items
            if getattr(pw.request, "explain", False)
            and pw.graph is not None
        ]
        if not need:
            return
        import dataclasses

        import jax

        from ..explain import build_bundle, get_explain_store
        from ..obs.metrics import record_explain
        from ..obs.spans import get_tracer
        from ..rank_backends.blob import stage_rank_window

        ex = dataclasses.replace(self.config.explain, enabled=True)
        for pw in need:
            try:
                with get_tracer().span(
                    "explain", service="serve", ctx=pw.ctx,
                    kernel=pw.kernel,
                ):
                    outs = jax.device_get(
                        stage_rank_window(
                            pw.graph,
                            self.config.pagerank,
                            self.config.spectrum,
                            pw.kernel,
                            self.config.runtime.blob_staging,
                            explain=ex,
                        )
                    )
                bundle = build_bundle(
                    outs,
                    pw.op_names,
                    pw.explain_ctx,
                    method=self.config.spectrum.method,
                    kernel=pw.kernel,
                    window={
                        "start": pw.result.start,
                        "end": pw.result.end,
                        "request_id": pw.request.request_id,
                    },
                    trigger="request",
                )
                pw.result.explain = bundle.data
                record_explain("request")
                get_explain_store().publish(
                    str(pw.result.start), bundle.data
                )
            except Exception as e:  # noqa: BLE001 - provenance is
                # best-effort; the ranked answer already stands.
                self._log().warning(
                    "explain dispatch failed for %s: %s",
                    pw.request.request_id, e,
                )

    def _device_dispatch(
        self,
        items: List[PendingWindow],
        next_items: Optional[List[PendingWindow]] = None,
    ):
        # Chaos: the unified serve_dispatch seam, plus the legacy knob
        # (ServeConfig.inject_dispatch_failures) now ALIASED onto the
        # same recording surface — either way the injection lands in
        # microrank_fault_injections_total{seam="serve_dispatch"}.
        from ..chaos import maybe_inject, record_injection

        maybe_inject("serve_dispatch")
        if self._inject_failures > 0:
            self._inject_failures -= 1
            record_injection("serve_dispatch", "fail")
            raise RuntimeError(
                "injected device dispatch failure "
                "(ServeConfig.inject_dispatch_failures)"
            )
        from ..utils.guards import contract_checks

        rt = self.config.runtime
        kernel = items[0].kernel
        next_batch = None
        if next_items:
            next_batch = (
                [pw.graph for pw in next_items], next_items[0].kernel
            )
        from ..obs.spans import get_tracer

        # The router's staging/dispatch/fetch spans attribute to the
        # batch HEAD's request trace (one device program answers the
        # whole micro-batch); each member's span still records the
        # occupancy it rode in.
        with get_tracer().attach(items[0].ctx):
            with contract_checks(rt.validate_numerics):
                outs, info = self.router.rank_batch(
                    [pw.graph for pw in items],
                    kernel,
                    conv_trace=bool(rt.convergence_trace),
                    next_batch=next_batch,
                )
        return outs, info

    def _assign(self, items, outs, batch_ms: float, route_info=None) -> None:
        ti, ts, nv = outs[:3]
        per_window_ms = batch_ms / max(1, len(items))
        kernel = route_info.kernel if route_info else items[0].kernel
        for b, pw in enumerate(items):
            n = int(nv[b])
            names = [pw.op_names[int(i)] for i in ti[b][:n]]
            scores = [float(s) for s in ts[b][:n]]
            if self.config.runtime.validate_numerics:
                from ..utils.guards import assert_finite_scores

                assert_finite_scores(scores, "serve batch window")
            pw.result.ranking = list(zip(names, scores))
            pw.result.batch_windows = len(items)
            pw.result.timings["rank_ms"] = round(per_window_ms, 3)
            if route_info is not None:
                # The sharded route may have resolved a different
                # (shard-capable) kernel than the per-window choice.
                pw.result.kernel = kernel
                pw.result.route = route_info.route
            if len(outs) > 3:
                conv = _conv_summary(outs[3][b], outs[4][b])
                pw.result.apply_convergence(conv)
                from ..obs.metrics import record_convergence

                record_convergence(
                    kernel,
                    conv["iterations"],
                    conv["final_residual"]
                    if conv["final_residual"] is not None
                    else float("nan"),
                )

    # -------------------------------------------------------- degradation
    def _degrade(self, items, error, warmup=False) -> None:
        """Device path is down for this batch: answer from the numpy_ref
        oracle per request (``fallback``), or fail the batch. Either
        way the flight recorder dumps the span ring first — the causal
        record of the dispatch that just died is exactly what the
        post-mortem needs, and the ring is still hot."""
        from ..utils.guards import assert_device_owner

        # The per-member numpy_ref fallback re-runs detect+rank on THIS
        # thread and mutates each member's result/future; it must stay
        # on the scheduler (device-owner) thread like every other
        # dispatch outcome — previously unguarded (mrsan satellite).
        assert_device_owner("serve.degrade")
        if self.flight is not None:
            self.flight.dump("degraded")
        if not self.serve.fallback:
            for pw in items:
                pw.finish(error=error)
            return
        self._log().error(
            "batch dispatch failed twice (%s); degrading %d windows to "
            "numpy_ref", error, len(items),
        )
        from ..rank_backends import NumpyRefBackend

        backend = NumpyRefBackend(self.config)
        done = []  # (pw, error) — futures resolve only after the
        # batch's metrics/journal record, so a response never races its
        # own telemetry.
        degraded = 0
        for pw in items:
            t0 = time.monotonic()
            try:
                names, scores = backend.rank_window(
                    pw.span_df, pw.normal_ids, pw.abnormal_ids
                )
            except Exception as e:
                done.append((pw, e))
                continue
            pw.result.ranking = list(zip(names, scores))
            pw.result.degraded = True
            pw.result.kernel = "numpy_ref"
            pw.result.batch_windows = 1
            pw.result.timings["rank_ms"] = round(
                (time.monotonic() - t0) * 1e3, 3
            )
            pw.result.apply_convergence(backend.last_convergence)
            degraded += 1
            done.append((pw, None))
        if not warmup:
            from ..obs.metrics import record_serve_batch

            record_serve_batch(len(items), degraded=degraded)
        self._journal_batch(items, 0.0, degraded=degraded, warmup=warmup)
        for pw, err in done:
            pw.finish(error=err)

    # ------------------------------------------------------------- misc
    def _journal_batch(
        self, items, batch_ms, degraded, warmup, route_info=None
    ) -> None:
        if self.journal is None:
            return
        self.journal.emit(
            "serve_batch",
            occupancy=len(items),
            kernel=(
                route_info.kernel
                if route_info
                else (items[0].kernel if items else None)
            ),
            route=route_info.route if route_info else None,
            overlap_ms=route_info.overlap_ms if route_info else 0.0,
            dispatch_ms=round(batch_ms, 3),
            degraded=degraded,
            warmup=bool(warmup),
            requests=[pw.request.request_id for pw in items],
            tenants=sorted({pw.request.tenant for pw in items}),
        )

    @staticmethod
    def _log():
        from ..utils.logging import get_logger

        return get_logger("microrank_tpu.serve")
