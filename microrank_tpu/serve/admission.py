"""Admission control: a bounded count of requests in the service.

The queue between the HTTP frontend and the batching scheduler must not
grow without bound — a traffic spike would otherwise turn into unbounded
memory (parked span payloads) and unbounded tail latency (requests
serviced minutes after their window closed). One counter covers a
request's whole residency: admitted at the frontend, released when its
response future resolves. Past ``max_depth`` the frontend answers 429
with ``Retry-After`` — load sheds at the edge, the device keeps ranking
the admitted set. A draining service (SIGTERM received) admits nothing.
"""

from __future__ import annotations

from ..utils.guards import TrackedLock, note_shared_access, register_shared


class AdmissionController:
    def __init__(self, max_depth: int, retry_after_seconds: float = 1.0):
        self.max_depth = int(max_depth)
        self.retry_after_seconds = float(retry_after_seconds)
        # HTTP threads admit, the scheduler thread releases — a
        # registered mrsan shared object (R10's runtime twin).
        self._lock = TrackedLock("serve_admission")
        register_shared("serve_admission", {"serve_admission"})
        self._depth = 0
        self._closed = False
        # EWMA of measured per-window service cost (seconds), fed by
        # the batcher after each device dispatch. None until the first
        # window completes — Retry-After then falls back to the
        # configured constant.
        self._cost_ewma = None

    def observe_window_cost(self, seconds: float) -> None:
        """One completed window's measured service cost; smoothed into
        the EWMA that prices Retry-After."""
        s = max(0.0, float(seconds))
        with self._lock:
            note_shared_access("serve_admission")
            if self._cost_ewma is None:
                self._cost_ewma = s
            else:
                self._cost_ewma = 0.2 * s + 0.8 * self._cost_ewma

    def retry_after(self) -> float:
        """Seconds a 429/503 caller should back off: current queue
        depth × measured per-window cost — the queue's actual drain
        time — instead of the static configured constant (which remains
        the floor, and the answer until the first window has been
        measured)."""
        with self._lock:
            note_shared_access("serve_admission")
            if self._cost_ewma is None:
                return self.retry_after_seconds
            return max(
                self.retry_after_seconds, self._depth * self._cost_ewma
            )

    def try_admit(self) -> bool:
        """One admission slot, or False (429 / 503 at the caller)."""
        from ..obs.metrics import serve_queue_depth

        with self._lock:
            note_shared_access("serve_admission")
            if self._closed or self._depth >= self.max_depth:
                return False
            self._depth += 1
            depth = self._depth
        serve_queue_depth().set(float(depth))
        return True

    def release(self) -> None:
        from ..obs.metrics import serve_queue_depth

        with self._lock:
            note_shared_access("serve_admission")
            self._depth = max(0, self._depth - 1)
            depth = self._depth
        serve_queue_depth().set(float(depth))

    def close(self) -> None:
        """Stop admitting (drain mode); in-flight slots still release."""
        with self._lock:
            self._closed = True

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    @property
    def depth(self) -> int:
        with self._lock:
            return self._depth
