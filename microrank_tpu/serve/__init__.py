"""Online RCA service (``cli serve``): the request path the offline
runners never had — asyncio HTTP frontend (server), per-tenant fair
scheduling (scheduler), cross-request micro-batching keyed by pad
buckets (batcher), admission control (admission), and the wire protocol
(protocol). One device dispatch ranks many tenants' windows; device
faults degrade to the numpy_ref oracle instead of dropping requests.
"""

from .admission import AdmissionController
from .batcher import MicroBatcher, PendingWindow, bucket_key
from .protocol import (
    DeadlineExceeded,
    ProtocolError,
    RankRequest,
    parse_rank_request,
    response_body,
    spans_to_frame,
)
from .scheduler import BatchScheduler, ShutdownError
from .server import (
    HttpFrontend,
    ServeHandle,
    ServeService,
    ServiceDraining,
    ServiceOverloaded,
    run_serve,
)

__all__ = [
    "AdmissionController",
    "BatchScheduler",
    "DeadlineExceeded",
    "HttpFrontend",
    "MicroBatcher",
    "PendingWindow",
    "ProtocolError",
    "RankRequest",
    "ServeHandle",
    "ServeService",
    "ServiceDraining",
    "ServiceOverloaded",
    "ShutdownError",
    "bucket_key",
    "parse_rank_request",
    "response_body",
    "run_serve",
    "spans_to_frame",
]
