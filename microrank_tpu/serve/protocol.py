"""Wire protocol of the online RCA service (serve/).

One request = one detection window. Two payload forms:

* inline spans — ``{"spans": [{span record}, ...]}``: the caller ships
  the window's span rows (canonical schema or raw ClickHouse column
  names, same rename rule as CSV ingest);
* pre-staged dataset — ``{"dataset": "name", "start": ..., "end": ...}``:
  the server slices a dump it loaded at startup (``--dataset NAME=CSV``)
  to the requested time range.

Either form may carry ``tenant`` (fair-dequeue key, default "default"),
``request_id`` (echoed back; generated when absent) and
``explain: true`` (rank provenance: the response's ``explain`` field
carries the window's ExplainBundle — per-suspect counter decomposition,
per-formula terms, PPR mass split, top contributing traces — produced
by one extra explained dispatch after the batch; the batched hot path
is untouched). The response is the request-scoped ``WindowResult``
serialization (pipeline.results) plus batching telemetry — including
``degraded: true`` when the answer came from the numpy_ref fallback
path.

Tracing: a W3C ``traceparent`` request header joins the request's
self-tracing spans to the CALLER's distributed trace (the request root
adopts the caller's trace id and parent-links to the caller's span);
responses carry a ``Server-Timing`` header built from the request's
StageTimings (queue/parse/detect/build/rank).
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import re
from dataclasses import dataclass
from typing import List, Optional, Tuple

from ..pipeline.results import WindowResult

_req_counter = itertools.count(1)


class ProtocolError(ValueError):
    """Malformed request — maps to HTTP 400."""

    status = 400


class AdmissionError(ProtocolError):
    """The request parsed, but span admission (ingest/) rejected every
    row — an unsalvageable payload maps to HTTP 422 with the
    per-reason rejection counts in the body, so the caller learns WHY
    (bad timestamps vs duplicate ids vs a blown budget) instead of a
    blanket 400. Salvageable payloads never raise: they rank
    degraded-but-correct on the clean subset."""

    status = 422

    def __init__(self, rejected: dict):
        self.rejected = dict(rejected)
        detail = ", ".join(
            f"{k}={v}" for k, v in sorted(self.rejected.items())
        )
        super().__init__(
            f"no span rows survived admission ({detail}); see the "
            "dead-letter store (quarantine.jsonl) for the rows"
        )


class DeadlineExceeded(RuntimeError):
    """The request's ``deadline_ms`` elapsed before its window staged —
    the service expires it (504) instead of dispatching device work
    nobody is waiting for."""

    status = 504


@dataclass
class RankRequest:
    request_id: str
    tenant: str = "default"
    spans: Optional[List[dict]] = None
    dataset: Optional[str] = None
    start: Optional[str] = None
    end: Optional[str] = None
    # Rank provenance: build + return an ExplainBundle for this window.
    explain: bool = False
    # Caller's patience bound: once this many milliseconds pass from
    # admission, the request EXPIRES (504) at the next scheduling
    # point instead of staging device work whose answer is already
    # abandoned — a burst cannot convert into dead dispatches.
    deadline_ms: Optional[float] = None
    # W3C trace context of the caller, parsed from the ``traceparent``
    # header: (trace_id, parent_span_id) or None.
    traceparent: Optional[Tuple[str, str]] = None


_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


def parse_traceparent(value: Optional[str]) -> Optional[Tuple[str, str]]:
    """Parse a W3C ``traceparent`` header (version-traceid-spanid-flags)
    into (trace_id, parent_span_id); malformed or all-zero ids return
    None (the spec says ignore, never reject the request)."""
    if not value:
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if not m:
        return None
    trace_id, span_id = m.group(2), m.group(3)
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


def format_traceparent(trace_id: str, span_id: str) -> str:
    """Render native tracer ids as a W3C ``traceparent`` header value
    (the outbound half of parse_traceparent — fleet HTTP carries it on
    register/heartbeat/report/goodbye). Native ids are human-readable
    (``win-<start>`` / ``s0000002a``), so non-conforming ids map
    deterministically into the header's hex fields: trace ids hash
    (md5 — same string, same 32-hex id on every host, which is what
    keeps the header shared across processes), span ids keep their hex
    digits zero-padded. The native ids stay authoritative for span
    linking; the header is the standards-compliant wire form."""
    import hashlib

    t = str(trace_id).lower()
    if not re.fullmatch(r"[0-9a-f]{32}", t):
        t = hashlib.md5(str(trace_id).encode()).hexdigest()
    s = re.sub(r"[^0-9a-f]", "", str(span_id).lower())[-16:].rjust(16, "0")
    if s == "0" * 16:
        s = "0" * 15 + "1"
    return f"00-{t}-{s}-01"


def parse_rank_request(
    body: bytes, traceparent: Optional[str] = None
) -> RankRequest:
    """Parse + validate one POST /rank body (+ optional caller trace
    context from the ``traceparent`` header)."""
    try:
        data = json.loads(body or b"")
    except json.JSONDecodeError as e:
        raise ProtocolError(f"request body is not JSON: {e}") from None
    if not isinstance(data, dict):
        raise ProtocolError("request body must be a JSON object")
    spans = data.get("spans")
    dataset = data.get("dataset")
    if (spans is None) == (dataset is None):
        raise ProtocolError(
            'provide exactly one of "spans" (inline span records) or '
            '"dataset" (a pre-staged dump name)'
        )
    if spans is not None:
        if not isinstance(spans, list) or not spans:
            raise ProtocolError('"spans" must be a non-empty list')
        if not all(isinstance(s, dict) for s in spans):
            raise ProtocolError('"spans" entries must be objects')
    tenant = str(data.get("tenant") or "default")
    request_id = str(
        data.get("request_id") or f"req-{next(_req_counter)}"
    )
    deadline_ms = data.get("deadline_ms")
    if deadline_ms is not None:
        try:
            deadline_ms = float(deadline_ms)
        except (TypeError, ValueError):
            raise ProtocolError(
                f'"deadline_ms" must be a number, got {deadline_ms!r}'
            ) from None
        if deadline_ms <= 0:
            raise ProtocolError('"deadline_ms" must be > 0')
    return RankRequest(
        request_id=request_id,
        tenant=tenant,
        spans=spans,
        dataset=dataset,
        start=data.get("start"),
        end=data.get("end"),
        explain=bool(data.get("explain", False)),
        deadline_ms=deadline_ms,
        traceparent=parse_traceparent(traceparent),
    )


def spans_to_frame(spans: List[dict]):
    """Inline span records -> the canonical span DataFrame (same rename
    + column contract as CSV ingest, io.loader).

    Large POST payloads take the columnar fast path
    (io.loader.frame_from_records — one pass per column, vectorized
    ISO8601 timestamp parse); payload shapes the fast path declines
    (empty, heterogeneous rows) fall back to the legacy row-wise parse.
    """
    import pandas as pd

    from ..io.loader import frame_from_records
    from ..io.schema import CLICKHOUSE_RENAME, validate_columns

    df = frame_from_records(spans)
    if df is None:
        df = pd.DataFrame(spans).rename(columns=CLICKHOUSE_RENAME)
        # Timestamps coerce rather than raise: one malformed row must
        # not abort the request — the admission ladder (serve.server)
        # routes NaT rows to the dead-letter store and ranks the clean
        # subset (422 via AdmissionError only when NOTHING survives).
        if "startTime" in df.columns:
            df["startTime"] = pd.to_datetime(
                df["startTime"], format="mixed", errors="coerce"
            )
        if "endTime" in df.columns:
            df["endTime"] = pd.to_datetime(
                df["endTime"], format="mixed", errors="coerce"
            )
    try:
        validate_columns(df.columns)
    except ValueError as e:
        raise ProtocolError(str(e)) from None
    return df


def response_body(result: WindowResult) -> bytes:
    """One answered request -> the JSON response payload."""
    d = dataclasses.asdict(result)
    d["ranking"] = [[n, float(s)] for n, s in result.ranking]
    return json.dumps(d).encode()


def server_timing_header(timings: dict) -> Optional[str]:
    """Render a request's StageTimings ``*_ms`` entries as a
    ``Server-Timing`` response header value (RFC draft syntax:
    ``name;dur=millis``) — queue/parse/detect/build/rank land in the
    caller's devtools/tracing next to its own spans."""
    parts = [
        f"{key[:-3]};dur={float(val):.3f}"
        for key, val in timings.items()
        if key.endswith("_ms")
    ]
    return ", ".join(parts) or None


def error_body(message: str, **extra) -> bytes:
    return json.dumps({"error": message, **extra}).encode()
