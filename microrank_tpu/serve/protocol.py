"""Wire protocol of the online RCA service (serve/).

One request = one detection window. Two payload forms:

* inline spans — ``{"spans": [{span record}, ...]}``: the caller ships
  the window's span rows (canonical schema or raw ClickHouse column
  names, same rename rule as CSV ingest);
* pre-staged dataset — ``{"dataset": "name", "start": ..., "end": ...}``:
  the server slices a dump it loaded at startup (``--dataset NAME=CSV``)
  to the requested time range.

Either form may carry ``tenant`` (fair-dequeue key, default "default")
and ``request_id`` (echoed back; generated when absent). The response is
the request-scoped ``WindowResult`` serialization (pipeline.results)
plus batching telemetry — including ``degraded: true`` when the answer
came from the numpy_ref fallback path.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
from dataclasses import dataclass
from typing import List, Optional

from ..pipeline.results import WindowResult

_req_counter = itertools.count(1)


class ProtocolError(ValueError):
    """Malformed request — maps to HTTP 400."""

    status = 400


@dataclass
class RankRequest:
    request_id: str
    tenant: str = "default"
    spans: Optional[List[dict]] = None
    dataset: Optional[str] = None
    start: Optional[str] = None
    end: Optional[str] = None


def parse_rank_request(body: bytes) -> RankRequest:
    """Parse + validate one POST /rank body."""
    try:
        data = json.loads(body or b"")
    except json.JSONDecodeError as e:
        raise ProtocolError(f"request body is not JSON: {e}") from None
    if not isinstance(data, dict):
        raise ProtocolError("request body must be a JSON object")
    spans = data.get("spans")
    dataset = data.get("dataset")
    if (spans is None) == (dataset is None):
        raise ProtocolError(
            'provide exactly one of "spans" (inline span records) or '
            '"dataset" (a pre-staged dump name)'
        )
    if spans is not None:
        if not isinstance(spans, list) or not spans:
            raise ProtocolError('"spans" must be a non-empty list')
        if not all(isinstance(s, dict) for s in spans):
            raise ProtocolError('"spans" entries must be objects')
    tenant = str(data.get("tenant") or "default")
    request_id = str(
        data.get("request_id") or f"req-{next(_req_counter)}"
    )
    return RankRequest(
        request_id=request_id,
        tenant=tenant,
        spans=spans,
        dataset=dataset,
        start=data.get("start"),
        end=data.get("end"),
    )


def spans_to_frame(spans: List[dict]):
    """Inline span records -> the canonical span DataFrame (same rename
    + column contract as CSV ingest, io.loader)."""
    import pandas as pd

    from ..io.schema import CLICKHOUSE_RENAME, validate_columns

    df = pd.DataFrame(spans).rename(columns=CLICKHOUSE_RENAME)
    try:
        validate_columns(df.columns)
    except ValueError as e:
        raise ProtocolError(str(e)) from None
    try:
        df["startTime"] = pd.to_datetime(df["startTime"], format="mixed")
        df["endTime"] = pd.to_datetime(df["endTime"], format="mixed")
    except (ValueError, TypeError) as e:
        raise ProtocolError(f"unparseable span timestamps: {e}") from None
    return df


def response_body(result: WindowResult) -> bytes:
    """One answered request -> the JSON response payload."""
    d = dataclasses.asdict(result)
    d["ranking"] = [[n, float(s)] for n, s in result.ranking]
    return json.dumps(d).encode()


def error_body(message: str, **extra) -> bytes:
    return json.dumps({"error": message, **extra}).encode()
