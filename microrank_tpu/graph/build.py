"""Vectorized host-side graph build: spans -> padded COO arrays.

This replaces the reference's dict building plus its O(n^2) dense-matrix
fill (``list.index()`` per edge, pagerank.py:35-52 — hot spot #3) and its
O(T^2·O) all-pairs trace-kind dedup (pagerank.py:54-66 — hot spot #2) with
O(n log n) numpy. Every string column is interned exactly once per window
(``pd.factorize``); both partitions are then built from int32 arrays only —
``np.unique`` on packed (op, trace) keys, ``np.bincount`` degree
statistics, and an exact vectorized dedup over each trace's sorted
unique-op row.

Semantics are kept value-identical to the reference matrices:
* ``p_ss[child, parent] = 1/outdeg_with_dups(parent)`` — duplicate
  (child, parent) entries overwrite, so multiplicity only inflates the
  denominator (pagerank.py:35-39);
* ``p_sr[op, trace] = 1/len_with_dups(trace)`` (pagerank.py:42-45);
* ``p_rs[trace, op] = 1/cov_with_dups(op)`` (pagerank.py:48-52);
* trace kinds: two traces are one kind iff their p_sr columns are equal,
  i.e. same unique-op set AND same span count (pagerank.py:54-66);
* parent links resolve by ``ParentSpanId == spanID`` within the partition
  (preprocess_data.py:157-158). One deliberate deviation: a span with a
  duplicated spanID matches once (positional lookup), where the
  reference's pandas merge would produce a cartesian blow-up — span ids
  are unique in OTel data.
"""

from __future__ import annotations

from typing import FrozenSet, Iterable, List, NamedTuple, Optional, Tuple

import numpy as np
import pandas as pd

from ..analysis.contracts import contract
from ..io.interning import Vocab
from ..io.naming import operation_names
from ..io.schema import DEFAULT_STRIP_LAST_SEGMENT_SERVICES
from .structures import (
    DeltaBuildState,
    DetectBatch,
    PartitionGraph,
    SloBaseline,
    WindowGraph,
    pad1d,
    pad_to,
)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer (uint64, wrapping arithmetic)."""
    with np.errstate(over="ignore"):
        x = (x + np.uint64(0x9E3779B97F4A7C15)).astype(np.uint64)
        x = ((x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)).astype(
            np.uint64
        )
        x = ((x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)).astype(
            np.uint64
        )
        return x ^ (x >> np.uint64(31))


# Above this many matrix cells the exact padded-row dedup switches to
# 128-bit set hashing (collision odds ~T^2 / 2^128 — negligible on
# non-adversarial data, and the parity suite would catch one). The padded
# row matrix is sorted row-wise by np.unique, so keep it small.
_DENSE_KIND_BUDGET = 1_000_000


def _trace_kind_groups(
    u_trace: np.ndarray,
    u_op: np.ndarray,
    tracelen: np.ndarray,
    n_traces: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Group traces into dedup kinds from sorted unique (trace, op) pairs
    — fully vectorized (no per-trace Python loop), replacing the
    reference's O(T^2·O) all-pairs column comparison (pagerank.py:54-66).

    Two traces are one kind iff they cover the same unique-op set AND have
    the same span count (that is exactly p_sr-column float equality).
    ``u_trace`` must be non-decreasing with ops ascending within a trace
    (guaranteed by np.unique over trace*V+op keys).

    Small windows: exact np.unique over padded [T, max_ops+1] rows.
    Large windows: np.unique over (sum-of-splitmix64(op), two salts,
    n_unique, tracelen) — O(E) memory regardless of row length.

    Returns (inverse[n_traces] group id per trace, counts[G] per group).
    """
    if len(u_trace) == 0:
        return (
            np.zeros(n_traces, dtype=np.int64),
            np.array([n_traces] if n_traces else [], dtype=np.int64),
        )
    n_unique = np.bincount(u_trace, minlength=n_traces).astype(np.int64)
    max_ops = int(n_unique.max())
    starts = np.concatenate(([0], np.cumsum(n_unique)[:-1]))

    if n_traces * (max_ops + 1) <= _DENSE_KIND_BUDGET:
        pos = np.arange(len(u_trace), dtype=np.int64) - starts[u_trace]
        mat = np.full((n_traces, max_ops + 1), -1, dtype=np.int64)
        mat[u_trace, pos] = u_op
        mat[:, max_ops] = tracelen[:n_traces]
        _, inverse, counts = np.unique(
            mat, axis=0, return_inverse=True, return_counts=True
        )
        return inverse.reshape(-1).astype(np.int64), counts.astype(np.int64)

    # Large windows: two 64-bit set-hash sums per trace. The per-entry
    # hash is a GATHER from two splitmix64 tables over the op vocab (ops
    # are small interned ints) — memory-bound instead of 3 multiply/xor
    # rounds per entry, ~20x cheaper at the 1M-span scale; the summed
    # keys are identical in strength to hashing each entry directly.
    n_vocab = int(u_op.max()) + 1
    base = np.arange(n_vocab, dtype=np.uint64)
    tab1 = _splitmix64(base)
    tab2 = _splitmix64(base ^ np.uint64(0xD6E8FEB86659FD93))
    with np.errstate(over="ignore"):
        s1 = np.add.reduceat(tab1[u_op], starts)
        s2 = np.add.reduceat(tab2[u_op], starts)
    # Group-by via one lexsort over the four key columns + boundary scan
    # (np.unique(axis=0)'s void-view sort measures ~10x slower here).
    tl = tracelen[:n_traces].astype(np.uint64, copy=False)
    nu = n_unique.astype(np.uint64, copy=False)
    order = np.lexsort((tl, nu, s2, s1))
    ks1, ks2 = s1[order], s2[order]
    knu, ktl = nu[order], tl[order]
    new_group = np.empty(n_traces, dtype=bool)
    new_group[0] = True
    new_group[1:] = (
        (ks1[1:] != ks1[:-1])
        | (ks2[1:] != ks2[:-1])
        | (knu[1:] != knu[:-1])
        | (ktl[1:] != ktl[:-1])
    )
    group_sorted = np.cumsum(new_group) - 1
    inverse = np.empty(n_traces, dtype=np.int64)
    inverse[order] = group_sorted
    counts = np.bincount(group_sorted)
    return inverse, counts.astype(np.int64)


def _trace_kinds(
    u_trace: np.ndarray,
    u_op: np.ndarray,
    tracelen: np.ndarray,
    n_traces: int,
) -> np.ndarray:
    """Kind-size per trace (C10): counts[group] scattered back per trace."""
    kind = np.zeros(n_traces, dtype=np.int32)
    if n_traces == 0 or len(u_trace) == 0:
        return kind
    inverse, counts = _trace_kind_groups(u_trace, u_op, tracelen, n_traces)
    kind[:] = counts[inverse]
    return kind


# Source-partition width (traces per partition) of the partition-centric
# kernel's binned views (kernel="pcsr"). One module constant shared by the
# host binning below and the device kernel (rank_backends.jax_tpu imports
# it), so the two sides can never disagree about the slab tiling.
# 4096 f32 trace entries = 16 KB per contiguous rv slice — comfortably
# cache/VMEM-sized while keeping the partition count low (T/4096).
PCSR_PART_TRACES = 4096

# Entries per reduction block in the forward tables: every
# (partition, op) range pads to whole blocks, so per-op sums become
# block row-sums + a prefix over block sums differenced at the dense
# offset table — no scatter. Small, because the expected pad waste is
# ~B/2 entries per populated (partition, op) pair.
PCSR_BLOCK = 8


def pcsr_partitions(t_pad: int) -> int:
    """Number of source partitions the pcsr views bin a t_pad-trace axis
    into (ceil division; >= 1 even for empty partitions)."""
    return max(1, -(-int(t_pad) // PCSR_PART_TRACES))


def pcsr_auxiliary(
    inc_op: np.ndarray,
    inc_trace: np.ndarray,
    sr_val: np.ndarray,
    rs_val: np.ndarray,
    n_inc: int,
    v_pad: int,
    t_pad: int,
):
    """Partition-centric binning of the (trace, op)-sorted incidence
    entries (Partition-Centric PageRank, arxiv 1709.07122, adapted to
    the bipartite coverage SpMV pair). See the field comments in
    graph.structures.PartitionGraph for the device-side reading.

    Forward tables: entries re-sorted (stable int-key argsort — numpy
    radix, O(E)) to (trace-partition, op, trace) order, every
    (partition, op) run padded to whole PCSR_BLOCK-entry blocks;
    ``pc_blk_indptr[p, o]`` is the BLOCK offset of op ``o``'s run inside
    partition ``p`` — the per-partition dense offset ranges. Trace ids
    are stored partition-LOCAL (trace - p*PCSR_PART_TRACES). Backward
    slab: each trace's entries as a fixed-width [t_pad, W] row (W = max
    unique ops per trace, pow2-bucketed). All padding carries value 0 /
    index 0 and is inert.

    Returns (pc_trace[P, Epb], pc_sr_val[P, Epb],
    pc_blk_indptr[P, v_pad+1], pc_ell_op[t_pad, W],
    pc_ell_rs[t_pad, W]).
    """
    s = PCSR_PART_TRACES
    bsz = PCSR_BLOCK
    n_parts = pcsr_partitions(t_pad)
    tr = np.asarray(inc_trace[:n_inc]).astype(np.int64)
    op = np.asarray(inc_op[:n_inc]).astype(np.int64)

    # Backward ELL slab (trace-major storage order: per-trace runs are
    # contiguous already).
    cnt_t = np.bincount(tr, minlength=t_pad).astype(np.int64)
    w = pad_to(int(cnt_t.max()) if n_inc else 1, "pow2", 1)
    ell_op = np.zeros((t_pad, w), np.int32)
    ell_rs = np.zeros((t_pad, w), np.float32)
    if n_inc:
        starts_t = np.concatenate(([0], np.cumsum(cnt_t)[:-1]))
        pos_t = np.arange(n_inc, dtype=np.int64) - starts_t[tr]
        ell_op[tr, pos_t] = op
        ell_rs[tr, pos_t] = np.asarray(rs_val[:n_inc])

    # Forward block tables.
    part = tr // s
    pair = part * v_pad + op
    order = np.argsort(pair, kind="stable")  # radix; trace stays ascending
    pair_s = pair[order]
    cnt_pair = np.bincount(pair_s, minlength=n_parts * v_pad).astype(
        np.int64
    )
    blocks_pair = -(-cnt_pair // bsz)        # ceil; empty pairs -> 0
    blocks_2d = blocks_pair.reshape(n_parts, v_pad)
    blk_indptr = np.zeros((n_parts, v_pad + 1), np.int32)
    blk_indptr[:, 1:] = np.cumsum(blocks_2d, axis=1).astype(np.int32)
    blocks_per_part = blocks_2d.sum(axis=1)
    e_blk = pad_to(
        int(blocks_per_part.max()) * bsz if n_inc else bsz, "pow2", bsz
    )
    pc_trace = np.zeros((n_parts, e_blk), np.int32)
    pc_sr = np.zeros((n_parts, e_blk), np.float32)
    if n_inc:
        # Destination column: the pair's block offset * bsz + position
        # within the pair's (sorted, contiguous) run.
        starts_pair = np.zeros(n_parts * v_pad + 1, dtype=np.int64)
        np.cumsum(cnt_pair, out=starts_pair[1:])
        pos_in_pair = np.arange(n_inc, dtype=np.int64) - starts_pair[pair_s]
        dest = blk_indptr[:, :-1].reshape(-1)[pair_s].astype(np.int64) * bsz
        dest += pos_in_pair
        part_s = pair_s // v_pad
        pc_trace[part_s, dest] = (tr[order] - part_s * s).astype(np.int32)
        pc_sr[part_s, dest] = np.asarray(sr_val[:n_inc])[order]
    return pc_trace, pc_sr, blk_indptr, ell_op, ell_rs


def csr_auxiliary(
    inc_op: np.ndarray,
    inc_trace: np.ndarray,
    sr_val: np.ndarray,
    ss_child: np.ndarray,
    n_inc: int,
    n_ss: int,
    v_pad: int,
    t_pad: int,
):
    """CSR orderings + row offsets for the scatter-free device kernel.

    Requires the storage invariants both build lanes guarantee: incidence
    sorted by (trace, op), call edges sorted by (child, parent). The
    op-major permutation is a stable sort on the op column (numpy radix for
    int keys — O(E)), which keeps traces ascending within each op row.

    Returns (inc_trace_opmajor[E], sr_val_opmajor[E], inc_indptr_op[V+1],
    inc_indptr_trace[T+1], ss_indptr[V+1]); padding entries carry 0 and sit
    outside every indptr range.
    """
    e_pad = inc_op.shape[0]
    perm = np.argsort(inc_op[:n_inc], kind="stable")
    tr_om = np.zeros(e_pad, dtype=np.int32)
    tr_om[:n_inc] = inc_trace[:n_inc][perm]
    sr_om = np.zeros(e_pad, dtype=np.float32)
    sr_om[:n_inc] = sr_val[:n_inc][perm]

    def indptr(ids, n, size):
        out = np.zeros(size + 1, dtype=np.int64)
        np.cumsum(np.bincount(ids[:n], minlength=size), out=out[1:])
        return out.astype(np.int32)

    return (
        tr_om,
        sr_om,
        indptr(inc_op, n_inc, v_pad),
        indptr(inc_trace, n_inc, t_pad),
        indptr(ss_child, n_ss, v_pad),
    )


# Device budget for the packed kernel's unpacked f32 matrices, summed over
# both partitions: (V*T + V*V)*4 per partition. One constant, one policy —
# resolve_aux decides at build time which auxiliary view to construct, and
# choose_kernel then selects purely by presence, so build and kernel choice
# can never disagree. Matches RuntimeConfig.dense_budget_bytes's default.
DEFAULT_DENSE_BUDGET_BYTES = 2 << 30

# Measured window dedup factor (true traces / distinct kind columns,
# summed over both partitions) at which an auto-resolved collapsed build
# constructs the kind-compressed views instead of bitmaps, so
# choose_kernel selects kernel="kind". Below it the axis barely shrank
# and the packed family keeps the window; the
# microrank_kind_dedup_ratio gauge exists to tune this from real
# profiles (RuntimeConfig.kind_dedup_threshold overrides per run).
DEFAULT_KIND_DEDUP_THRESHOLD = 4.0

# Above this many cells, build bitmaps by direct bit-scatter instead of a
# dense bool temporary + packbits (the bool temp is 8x the bitmap bytes).
_BOOL_TEMP_CELL_BUDGET = 128 << 20


def packed_unpacked_bytes(v_pad: int, t_pads) -> int:
    """Resident f32 bytes of the UNBLOCKED packed kernel's unpacked
    matrices ([V, T] coverage + [V, V] call graph per partition) — the
    one footprint formula choose_kernel, bench, and the tests share."""
    return sum((v_pad * t + v_pad * v_pad) * 4 for t in t_pads)


def packed_bits_bytes(v_pad: int, t_pads) -> int:
    """Resident bytes of the PACKED bitmaps themselves (what must fit
    for any packed-family kernel, including packed_blocked)."""
    return sum(
        v_pad * ((t + 7) // 8) + v_pad * ((v_pad + 7) // 8) for t in t_pads
    )


def resolve_aux(
    aux: str,
    v_pad: int,
    t_pads,
    dense_budget_bytes: int = DEFAULT_DENSE_BUDGET_BYTES,
    dedup: float | None = None,
    kind_dedup_threshold: float = DEFAULT_KIND_DEDUP_THRESHOLD,
) -> str:
    """Window-level auxiliary-view policy (one decision for BOTH
    partitions, so a window can never mix bitmap and CSR partitions).

    "auto" -> "kind" when the caller measured a trace-kind dedup factor
    (``dedup`` — true traces / distinct kind columns; only the collapse
    post-pass knows it, so ``t_pads`` here are already the COLLAPSED
    axes) at or past ``kind_dedup_threshold`` AND the kind views fit
    the same quarter-budget the bitmaps would -> "packed" when both
    partitions' PACKED bitmaps fit a quarter of the budget (the
    unpacked-f32 budget itself is applied at kernel-choice time: within
    it the kernel is "packed", past it "packed_blocked" streams column
    blocks so only the bitmap must be resident) -> "pcsr" when even the
    bitmaps blow that (the partition-centric fallback — no per-trace
    bitmap needs to exist at any point, and the kernel never issues a
    T-range random gather).

    "auto_all" (the sharded path's mode) -> "all" inside the bitmap
    budget, "pcsr" past it: the mesh kernel choice depends on the
    PER-SHARD packed footprint, which this window-level policy can't
    anticipate, so every view family is built and
    resolve_shard_kernel picks — keeping the memory-bounded fallback
    available where the single-device "auto" would have built bitmaps
    only.

    Explicit modes ("packed" | "csr" | "pcsr" | "kind" | "all" |
    "none") pass through for forced-kernel runs.
    """
    if aux not in ("auto", "auto_all"):
        return aux
    bits_total = packed_bits_bytes(v_pad, t_pads)
    if bits_total > dense_budget_bytes // 4:
        return "pcsr"
    if (
        aux == "auto"
        and dedup is not None
        and dedup >= kind_dedup_threshold
        and kind_bytes(v_pad, t_pads) <= dense_budget_bytes // 4
    ):
        return "kind"
    return "all" if aux == "auto_all" else "packed"


def kind_bytes(v_pad: int, t_pads) -> int:
    """Resident bytes of the kind-compressed views: the int8 [V, K]
    coverage pattern per partition plus its staged bitmap twin (the
    kind aux mode keeps the bitmap so packed parity runs stay possible
    on the same build)."""
    return sum(v_pad * t + v_pad * ((t + 7) // 8) for t in t_pads)


def aux_for_kernel(kernel: str, sharded: bool = False) -> str:
    """The build aux mode a forced RuntimeConfig.kernel needs."""
    mode = {
        "auto": "auto",
        "csr": "csr",
        "pcsr": "pcsr",
        "packed": "packed",
        "packed_bf16": "packed",
        "packed_blocked": "packed",
        "kind": "kind",
    }.get(kernel, "none")
    if sharded and mode == "auto":
        # Mesh dispatch: build BOTH view families (inside the bitmap
        # budget) so the per-shard packed-footprint check at kernel
        # choice can fall back to csr — the window-level auto policy
        # cannot anticipate the shard count.
        return "auto_all"
    return mode


def _scatter_bits(rows, cols, v_pad: int, n_cols: int) -> np.ndarray:
    """Pack a 0/1 pattern [v_pad, n_cols] to uint8 bits (big-endian bit
    order, matching np.packbits). Uses a dense bool temporary + packbits
    when small (fast), direct in-place bit-scatter when the temporary
    would dwarf the bitmap."""
    if v_pad * n_cols <= _BOOL_TEMP_CELL_BUDGET:
        dense = np.zeros((v_pad, n_cols), dtype=bool)
        dense[rows, cols] = True
        return np.packbits(dense, axis=1)
    bits = np.zeros((v_pad, (n_cols + 7) // 8), dtype=np.uint8)
    np.bitwise_or.at(
        bits,
        (rows, cols >> 3),
        (np.uint8(128) >> (cols & 7).astype(np.uint8)),
    )
    return bits


def packed_aux(
    inc_op: np.ndarray,
    inc_trace: np.ndarray,
    sr_val: np.ndarray,
    rs_val: np.ndarray,
    ss_child: np.ndarray,
    ss_parent: np.ndarray,
    ss_val: np.ndarray,
    n_inc: int,
    n_ss: int,
    v_pad: int,
    t_pad: int,
    with_bitmaps: bool = True,
):
    """Bitmap patterns + inverse vectors for the packed dense kernel.

    The inverse vectors are scattered from the per-entry value arrays (one
    f32 copy per axis position), so they carry bit-identical values to the
    COO path. Returns (cov_bits, ss_bits, inv_tracelen, inv_cov_dup,
    inv_outdeg); the bitmaps are [x, 0] placeholders when not requested.
    """
    inv_len = np.zeros(t_pad, dtype=np.float32)
    inv_len[inc_trace[:n_inc]] = sr_val[:n_inc]
    inv_cov = np.zeros(v_pad, dtype=np.float32)
    inv_cov[inc_op[:n_inc]] = rs_val[:n_inc]
    inv_out = np.zeros(v_pad, dtype=np.float32)
    inv_out[ss_parent[:n_ss]] = ss_val[:n_ss]

    if not with_bitmaps:
        empty = np.zeros((v_pad, 0), dtype=np.uint8)
        return empty, empty, inv_len, inv_cov, inv_out

    return (
        _scatter_bits(inc_op[:n_inc], inc_trace[:n_inc], v_pad, t_pad),
        _scatter_bits(ss_child[:n_ss], ss_parent[:n_ss], v_pad, v_pad),
        inv_len,
        inv_cov,
        inv_out,
    )


def kind_aux(cov_bits: np.ndarray, ss_child: np.ndarray, n_ss: int,
             v_pad: int, t_pad: int):
    """Kind-compressed reduced-precision views from an already-built
    coverage bitmap: the int8 [V, K] pattern (np.unpackbits — 0/1 is
    exact in int8, so this is a representation change, not a rounding)
    plus the call-edge row offsets the kernel's O(C) scatter-free
    row-sum differences at (the same indptr csr_auxiliary builds; the
    big op-major incidence copies are NOT needed and stay unbuilt).

    Returns (cov_i8 int8[v_pad, t_pad], ss_indptr int32[v_pad + 1]).
    """
    cov_i8 = (
        np.unpackbits(cov_bits, axis=1)[:, :t_pad].astype(np.int8)
        if cov_bits.shape[1]
        else np.zeros((v_pad, t_pad), np.int8)
    )
    ss_indptr = np.zeros(v_pad + 1, dtype=np.int64)
    np.cumsum(
        np.bincount(ss_child[:n_ss], minlength=v_pad), out=ss_indptr[1:]
    )
    return cov_i8, ss_indptr.astype(np.int32)


def build_aux_views(
    inc_op: np.ndarray,
    inc_trace: np.ndarray,
    sr_val: np.ndarray,
    rs_val: np.ndarray,
    ss_child: np.ndarray,
    ss_parent: np.ndarray,
    ss_val: np.ndarray,
    n_inc: int,
    n_ss: int,
    v_pad: int,
    t_pad: int,
    mode: str,
):
    """The shared (numpy-lane + native-lane) auxiliary-view constructor.

    ``mode`` is a RESOLVED aux mode ("packed" | "csr" | "pcsr" | "kind"
    | "all" | "none" — run resolve_aux first; "auto" is rejected here so
    the two build lanes can't silently apply different policies).
    Unbuilt views are [0]-shaped ([x, 0] for bitmaps and partition
    tables) placeholders; the kernels raise loudly on them. "kind"
    builds the packed bitmaps PLUS the kind-compressed views (int8
    pattern + ss row offsets), so packed parity runs stay possible on a
    kind build.

    Returns the 16 PartitionGraph aux fields: (inc_trace_opmajor,
    sr_val_opmajor, inc_indptr_op, inc_indptr_trace, ss_indptr, cov_bits,
    ss_bits, inv_tracelen, inv_cov_dup, inv_outdeg, pc_trace, pc_sr_val,
    pc_blk_indptr, pc_ell_op, pc_ell_rs, cov_i8).
    """
    if mode not in ("packed", "csr", "pcsr", "kind", "all", "none"):
        raise ValueError(f"unresolved aux mode {mode!r}")
    if mode in ("csr", "all"):
        csr = csr_auxiliary(
            inc_op, inc_trace, sr_val, ss_child, n_inc, n_ss, v_pad, t_pad
        )
    else:
        csr = (
            np.zeros(0, np.int32),
            np.zeros(0, np.float32),
            np.zeros(0, np.int32),
            np.zeros(0, np.int32),
            np.zeros(0, np.int32),
        )
    packed = packed_aux(
        inc_op, inc_trace, sr_val, rs_val, ss_child, ss_parent, ss_val,
        n_inc, n_ss, v_pad, t_pad,
        with_bitmaps=mode in ("packed", "kind", "all"),
    )
    if mode in ("pcsr", "all"):
        pc = pcsr_auxiliary(
            inc_op, inc_trace, sr_val, rs_val, n_inc, v_pad, t_pad
        )
    else:
        pc = (
            np.zeros((1, 0), np.int32),
            np.zeros((1, 0), np.float32),
            np.zeros((1, 0), np.int32),
            np.zeros((1, 0), np.int32),
            np.zeros((1, 0), np.float32),
        )
    if mode == "kind":
        cov_i8, ss_indptr = kind_aux(
            packed[0], ss_child, n_ss, v_pad, t_pad
        )
        csr = csr[:4] + (ss_indptr,)
    else:
        cov_i8 = np.zeros((1, 0), np.int8)
    return csr + packed + pc + (cov_i8,)


def _build_partition(
    op_codes: np.ndarray,       # int64 window-vocab op id per partition span
    g_trace: np.ndarray,        # int64 window-global trace id per span
    child_op: np.ndarray,       # int64 call-edge child op (instances)
    parent_op: np.ndarray,      # int64 call-edge parent op (instances)
    vocab_size: int,
    v_pad: int,
    pad_policy: str,
    min_pad: int,
    aux: str = "auto",
    compute_kinds: bool = True,
) -> Tuple[PartitionGraph, np.ndarray]:
    """Build one partition's padded graph from pure int arrays.

    ``compute_kinds=False`` skips the kind-size pass (kind stays 0) —
    for collapse-bound builds, where collapse_window_graph regroups the
    traces itself and rewrites ``kind`` either way (running both would
    do the O(E) grouping twice per partition).

    Returns (graph, global_trace_ids) where ``global_trace_ids[i]`` is the
    window-global trace id of partition-local trace i.
    """
    # Local trace interning: np.unique gives sorted-by-global-id order
    # (order is irrelevant downstream — results key on names).
    local_uniques, t_codes = np.unique(g_trace, return_inverse=True)
    t_codes = t_codes.astype(np.int64)
    n_traces = len(local_uniques)
    tracelen = np.bincount(t_codes, minlength=n_traces).astype(np.int64)

    # Unique (trace, op) incidence with value arrays for p_sr / p_rs.
    key = t_codes * vocab_size + op_codes
    ukey = np.unique(key)
    u_trace = (ukey // vocab_size).astype(np.int32)
    u_op = (ukey % vocab_size).astype(np.int32)
    cov_dup = np.bincount(op_codes, minlength=vocab_size).astype(np.int64)
    sr_val = (1.0 / tracelen[u_trace]).astype(np.float32)
    rs_val = (1.0 / cov_dup[u_op]).astype(np.float32)
    cov_unique = np.bincount(u_op, minlength=vocab_size).astype(np.int32)
    op_present = cov_unique > 0
    n_ops = int(op_present.sum())

    # Call edges: duplicates kept for the outdegree, unique pairs stored.
    outdeg_dup = np.bincount(parent_op, minlength=vocab_size).astype(np.int64)
    if len(child_op):
        ekey = np.unique(child_op * vocab_size + parent_op)
        e_child = (ekey // vocab_size).astype(np.int32)
        e_parent = (ekey % vocab_size).astype(np.int32)
        ss_val = (1.0 / outdeg_dup[e_parent]).astype(np.float32)
    else:
        e_child = np.zeros(0, dtype=np.int32)
        e_parent = np.zeros(0, dtype=np.int32)
        ss_val = np.zeros(0, dtype=np.float32)

    kind = (
        _trace_kinds(u_trace, u_op, tracelen, n_traces)
        if compute_kinds
        else np.zeros(n_traces, dtype=np.int32)
    )
    graph = _finish_partition(
        u_op, u_trace, sr_val, rs_val, e_child, e_parent, ss_val,
        tracelen, kind, cov_unique, op_present, n_ops, n_traces,
        v_pad, pad_policy, min_pad, aux,
    )
    return graph, local_uniques


def _finish_partition(
    u_op, u_trace, sr_val, rs_val, e_child, e_parent, ss_val,
    tracelen, kind, cov_unique, op_present, n_ops, n_traces,
    v_pad, pad_policy, min_pad, aux,
) -> PartitionGraph:
    """Pad + aux-view tail shared by the cold and delta build lanes:
    identical unpadded stats in, identical PartitionGraph out — the one
    place the delta assembly cannot drift from the cold build."""
    e_pad = pad_to(len(u_op), pad_policy, min_pad)
    c_pad = pad_to(len(e_child), pad_policy, min_pad)
    t_pad = pad_to(n_traces, pad_policy, min_pad)

    p_inc_op = pad1d(u_op, e_pad)
    p_inc_trace = pad1d(u_trace, e_pad)
    p_sr_val = pad1d(sr_val, e_pad)
    p_rs_val = pad1d(rs_val, e_pad)
    p_ss_child = pad1d(e_child, c_pad)
    p_ss_parent = pad1d(e_parent, c_pad)
    p_ss_val = pad1d(ss_val, c_pad)
    # ``aux`` must be window-level-resolved by the caller (resolve_aux);
    # "auto" here falls back to a partition-local resolution for direct
    # callers/tests that build a single partition.
    mode = resolve_aux(aux, v_pad, (t_pad,))
    (
        tr_om, sr_om, indptr_op, indptr_trace, ss_indptr,
        cov_bits, ss_bits, inv_len, inv_cov, inv_out,
        pc_trace, pc_sr, pc_blk, pc_ell_op, pc_ell_rs, cov_i8,
    ) = build_aux_views(
        p_inc_op, p_inc_trace, p_sr_val, p_rs_val,
        p_ss_child, p_ss_parent, p_ss_val,
        len(u_op), len(e_child), v_pad, t_pad, mode,
    )
    graph = PartitionGraph(
        inc_op=p_inc_op,
        inc_trace=p_inc_trace,
        sr_val=p_sr_val,
        rs_val=p_rs_val,
        ss_child=p_ss_child,
        ss_parent=p_ss_parent,
        ss_val=p_ss_val,
        inc_trace_opmajor=tr_om,
        sr_val_opmajor=sr_om,
        inc_indptr_op=indptr_op,
        inc_indptr_trace=indptr_trace,
        ss_indptr=ss_indptr,
        cov_bits=cov_bits,
        ss_bits=ss_bits,
        inv_tracelen=inv_len,
        inv_cov_dup=inv_cov,
        inv_outdeg=inv_out,
        kind=pad1d(kind, t_pad, fill=1),
        tracelen=pad1d(tracelen.astype(np.int32), t_pad, fill=1),
        cov_unique=pad1d(cov_unique, v_pad),
        op_present=pad1d(op_present, v_pad, fill=False),
        n_ops=np.int32(n_ops),
        n_traces=np.int32(n_traces),
        n_inc=np.int32(len(u_op)),
        n_ss=np.int32(len(e_child)),
        pc_trace=pc_trace,
        pc_sr_val=pc_sr,
        pc_blk_indptr=pc_blk,
        pc_ell_op=pc_ell_op,
        pc_ell_rs=pc_ell_rs,
        cov_i8=cov_i8,
    )
    return graph


def _window_intern(span_df: pd.DataFrame, strip_services: FrozenSet[str]):
    """One window's string interning — the dominant cold-build cost,
    factored out so the delta lane's cold fallback can capture its
    per-trace caches from the SAME factorize products instead of paying
    the string work twice.

    Returns ``(op_codes, op_uniques, tr_codes, tr_uniques, parent_row)``
    where ``parent_row[i]`` is the window row index of span i's parent
    (-1 when the parent span is absent from the window).
    """
    names = operation_names(span_df, "pod", strip_services)
    # sort=True interns the vocab in name order: vocab index then doubles
    # as the deterministic tie key of the device ranking (ascending op
    # name — the same key the numpy oracle uses under tiebreak="name").
    op_codes, op_uniques = pd.factorize(names, sort=True, use_na_sentinel=False)
    op_codes = op_codes.astype(np.int64)

    tr_codes, tr_uniques = pd.factorize(
        span_df["traceID"], use_na_sentinel=False
    )
    tr_codes = tr_codes.astype(np.int64)

    # Span linkage, once for the window: factorize spanID and ParentSpanId
    # through one shared vocabulary, then positional parent lookup.
    n = len(span_df)
    combined = np.concatenate(
        [
            span_df["spanID"].to_numpy(dtype=object),
            span_df["ParentSpanId"].to_numpy(dtype=object),
        ]
    )
    link_codes, link_uniques = pd.factorize(combined, use_na_sentinel=False)
    sid = link_codes[:n].astype(np.int64)
    pid = link_codes[n:].astype(np.int64)
    pos = np.full(len(link_uniques), -1, dtype=np.int64)
    pos[sid] = np.arange(n)
    parent_row = pos[pid]  # -1 when the parent span is absent
    return op_codes, op_uniques, tr_codes, tr_uniques, parent_row


def build_window_graph(
    span_df: pd.DataFrame,
    normal_ids: Iterable,
    abnormal_ids: Iterable,
    strip_services: FrozenSet[str] = DEFAULT_STRIP_LAST_SEGMENT_SERVICES,
    pad_policy: str = "pow2q",
    min_pad: int = 8,
    aux: str = "auto",
    dense_budget_bytes: int = DEFAULT_DENSE_BUDGET_BYTES,
    collapse: str = "off",
    retain_columns: bool = False,
    kind_dedup_threshold: float = DEFAULT_KIND_DEDUP_THRESHOLD,
):
    """Build both partitions of a window over one shared op vocab.

    The shared vocab is what makes the downstream spectrum step a single
    vectorized ``[V]`` computation: ops absent from a partition have no
    incidence entries, stay at score 0 through the iteration, and are
    masked by ``op_present`` (SURVEY.md C14 plan).

    ``collapse`` ("off" | "auto" | "on"): kind-collapse the trace axes
    (collapse_window_graph) — the core build then skips the per-trace aux
    views and the post-pass constructs them on the collapsed shapes.

    Returns (graph, op_names, normal_trace_ids, abnormal_trace_ids).

    ``retain_columns`` (the explain subsystem's coverage-column
    retention map): append a 5th element ``(map_normal, map_abnormal)``
    — per partition, an int64 array mapping each COLLAPSED coverage
    column to the partition-local index of its representative trace
    (the lowest-index member of its kind group), or ``None`` for an
    identity mapping (uncollapsed build, or a declined auto-collapse).
    ``trace_ids[map[c]]`` then names the trace a device-side column
    attribution refers to.
    """
    intern = _window_intern(span_df, strip_services)
    graph, op_names, ids0, ids1, column_map = _build_from_intern(
        intern, normal_ids, abnormal_ids, pad_policy, min_pad, aux,
        dense_budget_bytes, collapse, kind_dedup_threshold,
    )
    if retain_columns:
        return graph, op_names, ids0, ids1, column_map
    return graph, op_names, ids0, ids1


def _build_from_intern(
    intern,
    normal_ids,
    abnormal_ids,
    pad_policy,
    min_pad,
    aux,
    dense_budget_bytes,
    collapse,
    kind_dedup_threshold,
):
    """The cold build's partition construction from interned arrays
    (everything in build_window_graph after the string work)."""
    op_codes, op_uniques, tr_codes, tr_uniques, parent_row = intern
    vocab_size = len(op_uniques)
    v_pad = pad_to(vocab_size, pad_policy, min_pad)
    tr_index = {t: i for i, t in enumerate(tr_uniques)}

    # Window-level aux resolution: one decision for both partitions, from
    # their padded trace counts (every id kept below maps to >=1 span, so
    # the local trace count equals the kept-id count).
    code_lists = [
        [tr_index[t] for t in ids if t in tr_index]
        for ids in (normal_ids, abnormal_ids)
    ]
    t_pads = [
        pad_to(max(len(set(c)), 1), pad_policy, min_pad) for c in code_lists
    ]
    # Collapsing: the aux views are built by the post-pass on the
    # collapsed shapes — skip them in the core build.
    mode = (
        "none"
        if collapse != "off"
        else resolve_aux(aux, v_pad, t_pads, dense_budget_bytes)
    )

    parts = []
    id_lists = []
    for codes in code_lists:
        flags = np.zeros(len(tr_uniques) + 1, dtype=bool)
        if codes:
            flags[np.asarray(codes, dtype=np.int64)] = True
        mask = flags[tr_codes]

        edge_rows = np.flatnonzero(
            mask & (parent_row >= 0) & flags[tr_codes[np.clip(parent_row, 0, None)]]
        )
        part, local_codes = _build_partition(
            op_codes[mask],
            tr_codes[mask],
            op_codes[edge_rows],
            op_codes[np.clip(parent_row[edge_rows], 0, None)],
            vocab_size,
            v_pad,
            pad_policy,
            min_pad,
            mode,
            compute_kinds=(collapse == "off"),
        )
        parts.append(part)
        id_lists.append([tr_uniques[c] for c in local_codes])

    graph = WindowGraph(normal=parts[0], abnormal=parts[1])
    column_map = (None, None)
    if collapse != "off":
        graph, column_map = collapse_window_graph(
            graph, aux, pad_policy, min_pad, dense_budget_bytes, collapse,
            return_column_map=True,
            kind_dedup_threshold=kind_dedup_threshold,
        )
    return graph, list(op_uniques), id_lists[0], id_lists[1], column_map


# --------------------------------------------------------------- delta build
#
# Sliding-window incremental rebuild (ISSUE 20 tentpole): on a
# 75%-overlap slide almost every trace is unchanged between consecutive
# windows, yet the cold build re-pays its dominant cost — pod-level
# operation naming plus three pd.factorize string passes over EVERY
# span — for all of them. The delta lane caches the window per trace in
# interned int form (DeltaBuildState) and rebuilds the next window by
# splicing only the boundary traces: string work is O(arriving rows),
# per-trace aggregation is O(changed traces' spans), and the final
# partition assembly is vectorized int gathers over the caches.
#
# Exactness stance: the delta graph must rank tie-aware-identical to
# the cold build. Everything value-carrying (sr/rs/ss denominators,
# coverage, call edges, kind grouping) is derived from the same integer
# statistics through the same _finish_partition / collapse_window_graph
# tail the cold lane uses. The lane's one modeling assumption — the new
# frame is exactly the previous frame minus the departing prefix plus
# the arriving suffix — is CHECKED per window via a row count plus a
# wrapping uint64 span-time checksum; any mismatch (late spans,
# eviction drift, replay duplicates) routes the window to the cold
# build. Parent links crossing traces (out of contract for OTel data;
# see the module docstring's duplicated-spanID stance) are detected at
# capture and on every splice and likewise force cold.

#: Fraction of the window's traces (boundary + new) past which the
#: delta route stops paying for itself and the window rebuilds cold.
DEFAULT_DELTA_MAX_CHANGED = 0.5


class DeltaBuildResult(NamedTuple):
    """What build_window_graph_delta hands back: the cold build's
    4-tuple plus the retention map, the carried state and the route
    actually taken ("delta" | "cold"; ``reason`` says why a cold window
    went cold — "init" for the first window of a run)."""

    graph: WindowGraph
    op_names: list
    normal_trace_ids: list
    abnormal_trace_ids: list
    column_map: tuple
    state: DeltaBuildState
    route: str
    reason: str


def _graph_shape_sig(graph: WindowGraph) -> tuple:
    """Leaf-shape signature of both partitions — the delta lane's
    no-recompile guard (same signature => same jit pad bucket)."""
    return tuple(
        tuple(np.shape(leaf) for leaf in part)
        for part in (graph.normal, graph.abnormal)
    )


def _gather_ranges(indptr: np.ndarray, members: np.ndarray) -> np.ndarray:
    """Concatenated ``arange(indptr[m], indptr[m+1])`` for every member
    (vectorized CSR-segment gather index)."""
    lens = (indptr[members + 1] - indptr[members]).astype(np.int64)
    total = int(lens.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64)
    rep_starts = np.repeat(indptr[members].astype(np.int64), lens)
    cs = np.concatenate(([0], np.cumsum(lens)[:-1]))
    return rep_starts + (np.arange(total, dtype=np.int64) - np.repeat(cs, lens))


def _trace_aggregates(
    op: np.ndarray,
    tr: np.ndarray,
    t_ns: np.ndarray,
    sid: np.ndarray,
    pid: np.ndarray,
    n_traces: int,
    vocab_size: int,
    parent_row: Optional[np.ndarray] = None,
):
    """Per-trace CSR aggregates over the given span rows.

    ``tr`` must already be the target trace numbering (state-local ids
    at capture, compact sub ids on a splice). ``parent_row`` may be
    precomputed (the cold capture reuses the window intern's resolution
    so the cached edges mirror the cold build exactly); otherwise span
    linkage is resolved here over the given rows only.

    Returns ``(agg dict, ok, reason)`` — ``ok=False`` marks data the
    delta lane does not serve (cross-trace parent links, packed-key
    overflow); the caller then builds cold / marks the state ineligible.
    """
    n = len(op)
    if parent_row is None:
        combined = np.concatenate([sid, pid])
        link_codes, link_uniques = pd.factorize(
            combined, use_na_sentinel=False
        )
        s = link_codes[:n].astype(np.int64)
        p = link_codes[n:].astype(np.int64)
        pos = np.full(len(link_uniques), -1, dtype=np.int64)
        pos[s] = np.arange(n)
        parent_row = pos[p]

    # Intra-trace guard: every resolved parent must sit in the child's
    # own trace, else partition edges could span traces the splice
    # cannot see (the capture-time check covers the cold mirror, this
    # check covers every splice).
    valid = parent_row >= 0
    pr = np.clip(parent_row, 0, None)
    cross = valid & (tr[pr] != tr)
    if cross.any():
        return None, False, "cross_trace"
    if n_traces and float(n_traces) * vocab_size * vocab_size >= 2.0**62:
        return None, False, "key_overflow"

    order = np.argsort(tr, kind="stable")
    tracelen = np.bincount(tr, minlength=n_traces).astype(np.int64)
    span_indptr = np.zeros(n_traces + 1, dtype=np.int64)
    np.cumsum(tracelen, out=span_indptr[1:])
    span_t = t_ns[order]
    cs = np.zeros(n + 1, dtype=np.uint64)
    np.cumsum(span_t.astype(np.uint64), out=cs[1:])
    t_checksum = cs[span_indptr[1:]] - cs[span_indptr[:-1]]

    # Unique (trace, op) counts, op ascending within each trace.
    key = tr * vocab_size + op
    ukey, ucnt = np.unique(key, return_counts=True)
    u_tr = ukey // max(vocab_size, 1)
    uop_indptr = np.zeros(n_traces + 1, dtype=np.int64)
    np.cumsum(np.bincount(u_tr, minlength=n_traces), out=uop_indptr[1:])

    # Unique intra-trace call edges with instance multiplicities,
    # (child, parent) ascending within each trace.
    rows = np.flatnonzero(valid)
    etr = tr[rows]
    ekey = (etr * vocab_size + op[rows]) * vocab_size + op[pr[rows]]
    uek, ecnt = np.unique(ekey, return_counts=True)
    vv = max(vocab_size * vocab_size, 1)
    ue_tr = uek // vv
    rem = uek - ue_tr * vv
    uedge_indptr = np.zeros(n_traces + 1, dtype=np.int64)
    np.cumsum(np.bincount(ue_tr, minlength=n_traces), out=uedge_indptr[1:])

    agg = {
        "span_indptr": span_indptr,
        "span_op": op[order],
        "span_t_ns": span_t,
        "span_sid": sid[order],
        "span_pid": pid[order],
        "uop_indptr": uop_indptr,
        "uop_op": (ukey - u_tr * max(vocab_size, 1)).astype(np.int64),
        "uop_cnt": ucnt.astype(np.int64),
        "uedge_indptr": uedge_indptr,
        "uedge_child": (rem // max(vocab_size, 1)).astype(np.int64),
        "uedge_parent": (rem % max(vocab_size, 1)).astype(np.int64),
        "uedge_cnt": ecnt.astype(np.int64),
        "tracelen": tracelen,
        "t_checksum": t_checksum,
    }
    return agg, True, ""


def _capture_delta_state(
    span_df: pd.DataFrame,
    intern,
    params: tuple,
    start_us: Optional[int],
    end_us: Optional[int],
    shape_sig: tuple,
) -> DeltaBuildState:
    """Capture the per-trace caches from a cold build's intern products
    (one extra O(n log n) int pass — no further string work)."""
    op_codes, op_uniques, tr_codes, tr_uniques, parent_row = intern
    trace_ids = np.asarray(tr_uniques, dtype=object)
    empty = np.zeros(0, dtype=np.int64)
    empty_obj = np.zeros(0, dtype=object)
    state = DeltaBuildState(
        start_us=int(start_us) if start_us is not None else 0,
        end_us=int(end_us) if end_us is not None else 0,
        params=params,
        op_uniques=list(op_uniques),
        op_index=pd.Index(np.asarray(op_uniques, dtype=object)),
        trace_ids=trace_ids,
        trace_index=pd.Index(trace_ids),
        span_indptr=np.zeros(1, dtype=np.int64),
        span_op=empty,
        span_t_ns=empty,
        span_sid=empty_obj,
        span_pid=empty_obj,
        uop_indptr=np.zeros(1, dtype=np.int64),
        uop_op=empty,
        uop_cnt=empty,
        uedge_indptr=np.zeros(1, dtype=np.int64),
        uedge_child=empty,
        uedge_parent=empty,
        uedge_cnt=empty,
        tracelen=empty,
        t_checksum=np.zeros(0, dtype=np.uint64),
        shape_sig=shape_sig,
    )
    if start_us is None or end_us is None:
        state.eligible = False
        state.reason = "bounds"
        return state
    st = span_df["startTime"]
    if not pd.api.types.is_datetime64_any_dtype(st.dtype):
        state.eligible = False
        state.reason = "timestamps"
        return state
    t_ns = st.to_numpy().view("int64")
    agg, ok, reason = _trace_aggregates(
        op_codes,
        tr_codes,
        t_ns,
        span_df["spanID"].to_numpy(dtype=object),
        span_df["ParentSpanId"].to_numpy(dtype=object),
        len(tr_uniques),
        len(op_uniques),
        parent_row=parent_row,
    )
    if not ok:
        state.eligible = False
        state.reason = reason
        return state
    for k, v in agg.items():
        setattr(state, k, v)
    return state


def _assemble_partition(
    state: DeltaBuildState,
    members: np.ndarray,
    vocab_size: int,
    v_pad: int,
    pad_policy: str,
    min_pad: int,
    aux: str,
    compute_kinds: bool,
) -> PartitionGraph:
    """One partition from the state's per-trace caches: the same
    unpadded statistics _build_partition derives from raw spans,
    reassembled as vectorized gathers over the per-trace aggregates,
    finished through the shared _finish_partition tail."""
    m = members
    n_traces = len(m)
    tracelen = state.tracelen[m] if n_traces else np.zeros(0, np.int64)

    u_idx = _gather_ranges(state.uop_indptr, m)
    u_op = state.uop_op[u_idx]
    u_cnt = state.uop_cnt[u_idx]
    u_lens = (state.uop_indptr[m + 1] - state.uop_indptr[m]) if n_traces else np.zeros(0, np.int64)
    u_trace = np.repeat(np.arange(n_traces, dtype=np.int64), u_lens)

    cov_dup = np.bincount(
        u_op, weights=u_cnt, minlength=vocab_size
    ).astype(np.int64)
    sr_val = (1.0 / tracelen[u_trace]).astype(np.float32)
    rs_val = (1.0 / cov_dup[u_op]).astype(np.float32)
    cov_unique = np.bincount(u_op, minlength=vocab_size).astype(np.int32)
    op_present = cov_unique > 0
    n_ops = int(op_present.sum())

    e_idx = _gather_ranges(state.uedge_indptr, m)
    ec = state.uedge_child[e_idx]
    ep = state.uedge_parent[e_idx]
    ecnt = state.uedge_cnt[e_idx]
    outdeg_dup = np.bincount(
        ep, weights=ecnt, minlength=vocab_size
    ).astype(np.int64)
    if len(ec):
        ekey = np.unique(ec * vocab_size + ep)
        e_child = (ekey // vocab_size).astype(np.int32)
        e_parent = (ekey % vocab_size).astype(np.int32)
        ss_val = (1.0 / outdeg_dup[e_parent]).astype(np.float32)
    else:
        e_child = np.zeros(0, dtype=np.int32)
        e_parent = np.zeros(0, dtype=np.int32)
        ss_val = np.zeros(0, dtype=np.float32)

    u_trace32 = u_trace.astype(np.int32)
    u_op32 = u_op.astype(np.int32)
    kind = (
        _trace_kinds(u_trace32, u_op32, tracelen, n_traces)
        if compute_kinds
        else np.zeros(n_traces, dtype=np.int32)
    )
    return _finish_partition(
        u_op32, u_trace32, sr_val, rs_val, e_child, e_parent, ss_val,
        tracelen, kind, cov_unique, op_present, n_ops, n_traces,
        v_pad, pad_policy, min_pad, aux,
    )


def _try_delta(
    span_df,
    normal_ids,
    abnormal_ids,
    state: DeltaBuildState,
    start_us: int,
    end_us: int,
    strip_services,
    pad_policy,
    min_pad,
    aux,
    dense_budget_bytes,
    collapse,
    kind_dedup_threshold,
    max_changed_fraction,
):
    """One delta attempt. Returns ``(result, None)`` on success or
    ``(None, reason)`` to route the window to the cold build."""
    st = span_df["startTime"]
    if not pd.api.types.is_datetime64_any_dtype(st.dtype):
        return None, "timestamps"
    t_ns = st.to_numpy().view("int64")
    ns0 = start_us * 1000
    prev_end_ns = state.end_us * 1000
    vocab_size = len(state.op_uniques)
    T = len(state.trace_ids)

    span_lens = np.diff(state.span_indptr)
    span_tr = np.repeat(np.arange(T, dtype=np.int64), span_lens)
    dep = state.span_t_ns < ns0
    changed = np.zeros(T, dtype=bool)
    changed[span_tr[dep]] = True

    arr_idx = np.flatnonzero(t_ns >= prev_end_ns)
    tids_all = span_df["traceID"].to_numpy(dtype=object)
    arr_tids = tids_all[arr_idx]
    loc = state.trace_index.get_indexer(arr_tids).astype(np.int64)
    existing = loc >= 0
    changed[loc[existing]] = True
    new_codes, new_uniques = pd.factorize(
        arr_tids[~existing], use_na_sentinel=False
    )
    n_new = len(new_uniques)

    n_changed = int(changed.sum())
    if (n_changed + n_new) / max(T + n_new, 1) > max_changed_fraction:
        return None, "churn"

    # Arriving rows through the FROZEN vocab: any unseen pod-level op
    # name means the vocab (and with it v_pad) would shift — cold.
    if len(arr_idx):
        arr_names = operation_names(
            span_df.iloc[arr_idx], "pod", strip_services
        )
        arr_op = state.op_index.get_indexer(
            np.asarray(arr_names, dtype=object)
        ).astype(np.int64)
        if (arr_op < 0).any():
            return None, "vocab"
    else:
        arr_op = np.zeros(0, dtype=np.int64)

    # Splice the changed traces: surviving cached spans + arriving rows,
    # renumbered compactly (changed state traces first, new traces after).
    keep = ~dep
    ch_span = changed[span_tr] & keep
    ch_ids = np.flatnonzero(changed)
    remap = np.full(T, -1, dtype=np.int64)
    remap[ch_ids] = np.arange(len(ch_ids), dtype=np.int64)
    arr_sub = np.empty(len(arr_idx), dtype=np.int64)
    arr_sub[existing] = remap[loc[existing]]
    arr_sub[~existing] = len(ch_ids) + new_codes.astype(np.int64)

    sub_op = np.concatenate([state.span_op[ch_span], arr_op])
    sub_tr = np.concatenate([remap[span_tr[ch_span]], arr_sub])
    sub_t = np.concatenate([state.span_t_ns[ch_span], t_ns[arr_idx]])
    sub_sid = np.concatenate(
        [
            state.span_sid[ch_span],
            span_df["spanID"].to_numpy(dtype=object)[arr_idx],
        ]
    )
    sub_pid = np.concatenate(
        [
            state.span_pid[ch_span],
            span_df["ParentSpanId"].to_numpy(dtype=object)[arr_idx],
        ]
    )
    n_sub = len(ch_ids) + n_new
    agg, ok, why = _trace_aggregates(
        sub_op, sub_tr, sub_t, sub_sid, sub_pid, n_sub, vocab_size
    )
    if not ok:
        return None, why

    # Integrity: the frame must be EXACTLY the cached unchanged spans
    # plus the splice — row count and wrapping span-time checksum.
    unchanged = ~changed
    pred_rows = int(state.tracelen[unchanged].sum()) + len(sub_op)
    if pred_rows != len(span_df):
        return None, "integrity"
    pred_sum = np.concatenate(
        [state.t_checksum[unchanged], agg["t_checksum"]]
    ).sum(dtype=np.uint64)
    frame_sum = t_ns.astype(np.uint64).sum(dtype=np.uint64)
    if pred_sum != frame_sum:
        return None, "integrity"

    # Merge: unchanged traces keep their cached segments; changed/new
    # traces take the recomputed ones (empty splices are dropped — the
    # trace left the window). O(n) memcpy, no string/hash work.
    sub_len = agg["tracelen"]
    alive = sub_len > 0
    u_lens_old = np.diff(state.uop_indptr)
    keep_u = unchanged[np.repeat(np.arange(T, dtype=np.int64), u_lens_old)]
    e_lens_old = np.diff(state.uedge_indptr)
    keep_e = unchanged[np.repeat(np.arange(T, dtype=np.int64), e_lens_old)]
    keep_span = unchanged[span_tr]

    sub_ids = np.concatenate(
        [state.trace_ids[ch_ids], np.asarray(new_uniques, dtype=object)]
    )
    new_ids = np.concatenate([state.trace_ids[unchanged], sub_ids[alive]])

    def indptr_of(lens):
        out = np.zeros(len(lens) + 1, dtype=np.int64)
        np.cumsum(lens, out=out[1:])
        return out

    new_span_lens = np.concatenate(
        [state.tracelen[unchanged], sub_len[alive]]
    )
    new_state = DeltaBuildState(
        start_us=start_us,
        end_us=end_us,
        params=state.params,
        op_uniques=state.op_uniques,
        op_index=state.op_index,
        trace_ids=new_ids,
        trace_index=pd.Index(new_ids),
        span_indptr=indptr_of(new_span_lens),
        span_op=np.concatenate([state.span_op[keep_span], agg["span_op"]]),
        span_t_ns=np.concatenate(
            [state.span_t_ns[keep_span], agg["span_t_ns"]]
        ),
        span_sid=np.concatenate(
            [state.span_sid[keep_span], agg["span_sid"]]
        ),
        span_pid=np.concatenate(
            [state.span_pid[keep_span], agg["span_pid"]]
        ),
        uop_indptr=indptr_of(
            np.concatenate(
                [u_lens_old[unchanged], np.diff(agg["uop_indptr"])[alive]]
            )
        ),
        uop_op=np.concatenate([state.uop_op[keep_u], agg["uop_op"]]),
        uop_cnt=np.concatenate([state.uop_cnt[keep_u], agg["uop_cnt"]]),
        uedge_indptr=indptr_of(
            np.concatenate(
                [e_lens_old[unchanged], np.diff(agg["uedge_indptr"])[alive]]
            )
        ),
        uedge_child=np.concatenate(
            [state.uedge_child[keep_e], agg["uedge_child"]]
        ),
        uedge_parent=np.concatenate(
            [state.uedge_parent[keep_e], agg["uedge_parent"]]
        ),
        uedge_cnt=np.concatenate(
            [state.uedge_cnt[keep_e], agg["uedge_cnt"]]
        ),
        tracelen=new_span_lens,
        t_checksum=np.concatenate(
            [state.t_checksum[unchanged], agg["t_checksum"][alive]]
        ),
        shape_sig=state.shape_sig,
    )

    # Partition assembly from the merged caches — same window-level aux
    # resolution and collapse tail as the cold build.
    v_pad = pad_to(vocab_size, pad_policy, min_pad)
    code_sets = []
    for ids in (normal_ids, abnormal_ids):
        ids_arr = np.asarray(list(ids), dtype=object)
        if len(ids_arr):
            loc2 = new_state.trace_index.get_indexer(ids_arr).astype(
                np.int64
            )
            mem = np.unique(loc2[loc2 >= 0])
        else:
            mem = np.zeros(0, dtype=np.int64)
        code_sets.append(mem)
    t_pads = [
        pad_to(max(len(mem), 1), pad_policy, min_pad) for mem in code_sets
    ]
    mode = (
        "none"
        if collapse != "off"
        else resolve_aux(aux, v_pad, t_pads, dense_budget_bytes)
    )
    parts = []
    id_lists = []
    for mem in code_sets:
        parts.append(
            _assemble_partition(
                new_state, mem, vocab_size, v_pad, pad_policy, min_pad,
                mode, compute_kinds=(collapse == "off"),
            )
        )
        id_lists.append([new_state.trace_ids[i] for i in mem])
    graph = WindowGraph(normal=parts[0], abnormal=parts[1])
    column_map = (None, None)
    if collapse != "off":
        graph, column_map = collapse_window_graph(
            graph, aux, pad_policy, min_pad, dense_budget_bytes, collapse,
            return_column_map=True,
            kind_dedup_threshold=kind_dedup_threshold,
        )

    sig = _graph_shape_sig(graph)
    if state.shape_sig and sig != state.shape_sig:
        # The pad bucket would shift — rebuild cold so the new bucket is
        # the cold build's own (no delta-only compile keys, ever).
        return None, "pad_shift"
    new_state.shape_sig = sig
    return (
        graph, list(state.op_uniques), id_lists[0], id_lists[1],
        column_map, new_state,
    ), None


def build_window_graph_delta(
    span_df: pd.DataFrame,
    normal_ids: Iterable,
    abnormal_ids: Iterable,
    *,
    state: Optional[DeltaBuildState] = None,
    start_us: Optional[int] = None,
    end_us: Optional[int] = None,
    strip_services: FrozenSet[str] = DEFAULT_STRIP_LAST_SEGMENT_SERVICES,
    pad_policy: str = "pow2q",
    min_pad: int = 8,
    aux: str = "auto",
    dense_budget_bytes: int = DEFAULT_DENSE_BUDGET_BYTES,
    collapse: str = "off",
    kind_dedup_threshold: float = DEFAULT_KIND_DEDUP_THRESHOLD,
    max_changed_fraction: float = DEFAULT_DELTA_MAX_CHANGED,
) -> DeltaBuildResult:
    """build_window_graph with a sliding-window incremental mode.

    Pass the previous window's returned ``state`` plus this window's
    bounds (microseconds, half-open). When the frame is a clean slide of
    the previous window — same build params, overlapping bounds, no
    unseen op names, changed-trace fraction under
    ``max_changed_fraction``, pad signature preserved, integrity
    checksum matching — the graph is assembled from the per-trace caches
    (route "delta"). Anything else falls back to the cold build and
    re-captures (route "cold" with a reason).

    The delta route returns the SAME op vocab as the previous window
    (superset semantics: departed ops keep zero coverage and are masked
    by ``op_present``), which is what pins v_pad and the jit pad bucket.
    """
    params = (
        frozenset(strip_services), pad_policy, int(min_pad), aux,
        int(dense_budget_bytes), collapse, float(kind_dedup_threshold),
    )
    reason = None
    if state is None:
        reason = "init"
    elif state.params != params:
        reason = "params"
    elif not state.eligible:
        reason = state.reason or "ineligible"
    elif start_us is None or end_us is None:
        reason = "bounds"
    elif not (state.start_us <= start_us <= state.end_us <= end_us):
        reason = "bounds"
    if reason is None:
        result, reason = _try_delta(
            span_df, normal_ids, abnormal_ids, state, int(start_us),
            int(end_us), strip_services, pad_policy, min_pad, aux,
            dense_budget_bytes, collapse, kind_dedup_threshold,
            max_changed_fraction,
        )
        if result is not None:
            graph, op_names, ids0, ids1, column_map, new_state = result
            return DeltaBuildResult(
                graph, op_names, ids0, ids1, column_map, new_state,
                "delta", "",
            )

    intern = _window_intern(span_df, strip_services)
    graph, op_names, ids0, ids1, column_map = _build_from_intern(
        intern, normal_ids, abnormal_ids, pad_policy, min_pad, aux,
        dense_budget_bytes, collapse, kind_dedup_threshold,
    )
    new_state = _capture_delta_state(
        span_df, intern, params, start_us, end_us, _graph_shape_sig(graph)
    )
    return DeltaBuildResult(
        graph, op_names, ids0, ids1, column_map, new_state, "cold", reason
    )


def _collapse_partition(
    part: PartitionGraph,
    mode: str,
    pad_policy: str,
    min_pad: int,
    groups: Tuple[np.ndarray, np.ndarray] | None = None,
) -> PartitionGraph:
    """Collapse one partition's trace axis to its distinct kind columns.

    Identical p_sr columns (same unique-op set AND same span count — the
    reference's kind definition, pagerank.py:54-66) are merged into one
    column whose multiplicity m folds into the forward values
    (sr_val = m/len, and inv_tracelen scattered from it): a dense matvec
    over duplicate columns sums m identical terms, which is exactly one
    term scaled by m. The backward direction and the preference vector
    assign equal values to equal columns, so keeping one is exact (the
    device adjusts its two preference normalization sums by the
    multiplicity — jax_tpu.preference_vector). Per-op statistics
    (cov_unique, rs_val, call edges, n_traces) keep their TRUE
    full-trace values: the spectrum and the iteration's initial value
    are collapse-invariant by construction.

    ``mode`` is the RESOLVED aux mode for the collapsed shapes.

    Returns ``(collapsed_part, rep_idx)`` where ``rep_idx[c]`` is the
    partition-local trace index of column ``c``'s representative (the
    coverage-column retention map the explain subsystem uses to name
    the trace behind a device-side column attribution).
    """
    n_inc = int(part.n_inc)
    n_traces = int(part.n_traces)
    u_op = np.asarray(part.inc_op[:n_inc])
    u_trace = np.asarray(part.inc_trace[:n_inc])
    tracelen = np.asarray(part.tracelen[:n_traces]).astype(np.int64)
    inverse, counts = groups if groups is not None else _trace_kind_groups(
        u_trace, u_op, tracelen, n_traces
    )
    n_kinds = len(counts)
    # Representative = the lowest-id trace of each group; groups are then
    # renumbered in representative order so the selected entries stay
    # sorted by (column, op) — the storage invariant csr_auxiliary needs.
    first_idx = np.full(n_kinds, n_traces, dtype=np.int64)
    np.minimum.at(first_idx, inverse, np.arange(n_traces, dtype=np.int64))
    order = np.argsort(first_idx, kind="stable")
    rank = np.empty(n_kinds, dtype=np.int64)
    rank[order] = np.arange(n_kinds, dtype=np.int64)
    is_rep = np.zeros(n_traces, dtype=bool)
    is_rep[first_idx] = True

    sel = is_rep[u_trace]
    c_op = u_op[sel]
    c_col = rank[inverse[u_trace[sel]]].astype(np.int32)
    mult = counts[order]                       # [G] multiplicity per column
    c_len = tracelen[first_idx[order]]         # [G] span count per column
    # Forward values fold the multiplicity: p_sr's column appears once but
    # stands for m traces (m/len in one f64 division, cast once).
    sr_val = (mult[c_col] / c_len[c_col]).astype(np.float32)
    rs_val = np.asarray(part.rs_val[:n_inc])[sel]  # per-op value: unchanged

    e_pad = pad_to(len(c_op), pad_policy, min_pad)
    t_pad = pad_to(n_kinds, pad_policy, min_pad)
    v_pad = int(part.cov_unique.shape[0])
    n_ss = int(part.n_ss)

    p_inc_op = pad1d(c_op.astype(np.int32), e_pad)
    p_inc_trace = pad1d(c_col, e_pad)
    p_sr_val = pad1d(sr_val, e_pad)
    p_rs_val = pad1d(rs_val, e_pad)
    (
        tr_om, sr_om, indptr_op, indptr_trace, ss_indptr,
        cov_bits, ss_bits, inv_len, inv_cov, inv_out,
        pc_trace, pc_sr, pc_blk, pc_ell_op, pc_ell_rs, cov_i8,
    ) = build_aux_views(
        p_inc_op, p_inc_trace, p_sr_val, p_rs_val,
        part.ss_child, part.ss_parent, part.ss_val,
        len(c_op), n_ss, v_pad, t_pad, mode,
    )
    collapsed = part._replace(
        inc_op=p_inc_op,
        inc_trace=p_inc_trace,
        sr_val=p_sr_val,
        rs_val=p_rs_val,
        inc_trace_opmajor=tr_om,
        sr_val_opmajor=sr_om,
        inc_indptr_op=indptr_op,
        inc_indptr_trace=indptr_trace,
        ss_indptr=ss_indptr,
        cov_bits=cov_bits,
        ss_bits=ss_bits,
        inv_tracelen=inv_len,
        inv_cov_dup=inv_cov,
        inv_outdeg=inv_out,
        kind=pad1d(mult.astype(np.int32), t_pad, fill=1),
        tracelen=pad1d(c_len.astype(np.int32), t_pad, fill=1),
        n_inc=np.int32(len(c_op)),
        n_cols=np.int32(n_kinds),
        pc_trace=pc_trace,
        pc_sr_val=pc_sr,
        pc_blk_indptr=pc_blk,
        pc_ell_op=pc_ell_op,
        pc_ell_rs=pc_ell_rs,
        cov_i8=cov_i8,
    )
    return collapsed, first_idx[order]


def _rebuild_aux(part: PartitionGraph, mode: str) -> PartitionGraph:
    """Construct the aux views a core ``aux="none"`` build skipped, on the
    partition's existing (uncollapsed) arrays — the no-collapse exit of
    collapse_window_graph."""
    v_pad = int(part.cov_unique.shape[0])
    t_pad = int(part.kind.shape[0])
    (
        tr_om, sr_om, indptr_op, indptr_trace, ss_indptr,
        cov_bits, ss_bits, inv_len, inv_cov, inv_out,
        pc_trace, pc_sr, pc_blk, pc_ell_op, pc_ell_rs, cov_i8,
    ) = build_aux_views(
        part.inc_op, part.inc_trace, part.sr_val, part.rs_val,
        part.ss_child, part.ss_parent, part.ss_val,
        int(part.n_inc), int(part.n_ss), v_pad, t_pad, mode,
    )
    return part._replace(
        inc_trace_opmajor=tr_om,
        sr_val_opmajor=sr_om,
        inc_indptr_op=indptr_op,
        inc_indptr_trace=indptr_trace,
        ss_indptr=ss_indptr,
        cov_bits=cov_bits,
        ss_bits=ss_bits,
        inv_tracelen=inv_len,
        inv_cov_dup=inv_cov,
        inv_outdeg=inv_out,
        pc_trace=pc_trace,
        pc_sr_val=pc_sr,
        pc_blk_indptr=pc_blk,
        pc_ell_op=pc_ell_op,
        pc_ell_rs=pc_ell_rs,
        cov_i8=cov_i8,
    )


def kind_dedup_ratio(graph: WindowGraph) -> float:
    """The window's measured trace-kind dedup factor: true traces /
    distinct kind columns, summed over both partitions (1.0 on an
    uncollapsed build). The observability satellite's one number — the
    ``microrank_kind_dedup_ratio`` gauge, the journal's per-window
    field and the bench artifact column all record this value, so the
    kind auto-select threshold is tunable from real profiles."""
    total_t = total_c = 0
    for p in (graph.normal, graph.abnormal):
        # [-1]-style int() reads so batched ([B]-leading) graphs work.
        n_tr = int(np.max(np.asarray(p.n_traces)))
        n_co = int(np.max(np.asarray(p.n_cols)))
        total_t += n_tr
        total_c += n_tr if n_co < 0 else n_co
    return float(total_t) / float(max(total_c, 1))


def collapse_window_graph(
    graph: WindowGraph,
    aux: str = "auto",
    pad_policy: str = "pow2q",
    min_pad: int = 8,
    dense_budget_bytes: int = DEFAULT_DENSE_BUDGET_BYTES,
    collapse: str = "auto",
    return_column_map: bool = False,
    kind_dedup_threshold: float = DEFAULT_KIND_DEDUP_THRESHOLD,
):
    """Kind-collapse both partitions' trace axes and (re)build aux views.

    The exact trace-axis compression the reference's own kind-dedup
    implies (pagerank.py:54-66): real systems exhibit few distinct trace
    shapes, so the [V, T] coverage pattern usually holds T' << T distinct
    columns — collapsing shrinks staged bytes, per-iteration HBM traffic
    and matvec width by T/T' with bit-identical ranking semantics (the
    parity suite and the bench's full-window float64 oracle check run
    device-on-collapsed against oracle-on-uncollapsed).

    The caller should run the CORE build with ``aux="none"`` (skip the
    big per-trace bitmaps) and pass the REQUESTED aux here; this resolves
    it against the collapsed shapes. ``collapse="auto"`` collapses only
    when it shrinks the trace axis (when it doesn't, the aux views are
    built on the original arrays instead — same result as a direct
    build); ``"on"`` always collapses.

    ``return_column_map``: also return ``(map_normal, map_abnormal)``
    per-partition representative-trace indices (int64[n_cols]; None =
    identity — the declined-collapse exit), the explain subsystem's
    coverage-column retention map.
    """
    if collapse not in ("auto", "on"):
        raise ValueError(f"unknown collapse mode {collapse!r}")
    parts = (graph.normal, graph.abnormal)
    groups = []
    for p in parts:
        n_inc = int(p.n_inc)
        n_tr = int(p.n_traces)
        groups.append(
            _trace_kind_groups(
                np.asarray(p.inc_trace[:n_inc]),
                np.asarray(p.inc_op[:n_inc]),
                np.asarray(p.tracelen[:n_tr]).astype(np.int64),
                n_tr,
            )
        )
    total_g = sum(len(counts) for _, counts in groups)
    total_t = sum(int(p.n_traces) for p in parts)
    if collapse == "auto" and total_g >= total_t:
        t_pads = tuple(int(p.kind.shape[0]) for p in parts)
        mode = resolve_aux(
            aux, int(parts[0].cov_unique.shape[0]), t_pads,
            dense_budget_bytes,
        )
        # Rewrite kind from the grouping just computed — collapse-bound
        # core builds skip their own kind pass (compute_kinds=False).
        declined = []
        for p, (inverse, counts) in zip(parts, groups):
            kind = (
                counts[inverse].astype(np.int32)
                if len(inverse)
                else np.zeros(0, np.int32)
            )
            declined.append(
                _rebuild_aux(
                    p._replace(
                        kind=pad1d(kind, int(p.kind.shape[0]), fill=1)
                    ),
                    mode,
                )
            )
        out = WindowGraph(normal=declined[0], abnormal=declined[1])
        return (out, (None, None)) if return_column_map else out
    t_pads = tuple(
        pad_to(max(len(counts), 1), pad_policy, min_pad)
        for _, counts in groups
    )
    mode = resolve_aux(
        aux, int(parts[0].cov_unique.shape[0]), t_pads, dense_budget_bytes,
        dedup=float(total_t) / float(max(total_g, 1)),
        kind_dedup_threshold=kind_dedup_threshold,
    )
    collapsed = [
        _collapse_partition(p, mode, pad_policy, min_pad, grp)
        for p, grp in zip(parts, groups)
    ]
    out = WindowGraph(normal=collapsed[0][0], abnormal=collapsed[1][0])
    if return_column_map:
        return out, (collapsed[0][1], collapsed[1][1])
    return out


@contract(returns=("detectbatch", "any"))
def build_detect_batch(
    span_df: pd.DataFrame,
    slo_vocab: Vocab,
    strip_services: FrozenSet[str] = DEFAULT_STRIP_LAST_SEGMENT_SERVICES,
    pad_policy: str = "pow2q",
    min_pad: int = 8,
) -> Tuple[DetectBatch, List]:
    """Intern one detection window's spans for the vectorized detector.

    Service-level naming (the detector/SLO vocab); ops unseen in the SLO
    baseline get id -1 and contribute 0 expected duration — the reference's
    bare-``except`` behavior (anormaly_detector.py:66-67).

    The ``detectbatch`` return contract (armed behind
    RuntimeConfig.validate_numerics like the rank seams) machine-checks
    the detector's input layout: int32 op/trace + float32 duration on a
    shared padded span axis, 0-d int32 extents.
    """
    names = operation_names(span_df, "service", strip_services)
    op = slo_vocab.encode_series(names)
    t_codes, t_uniques = pd.factorize(span_df["traceID"], use_na_sentinel=False)
    n_spans = len(op)
    n_traces = len(t_uniques)
    s_pad = pad_to(n_spans, pad_policy, min_pad)
    batch = DetectBatch(
        op=pad1d(op, s_pad, fill=-1),
        trace=pad1d(t_codes.astype(np.int32), s_pad),
        duration_us=pad1d(
            span_df["duration"].to_numpy(dtype=np.float32), s_pad
        ),
        n_spans=np.int32(n_spans),
        n_traces=np.int32(n_traces),
    )
    return batch, list(t_uniques)
