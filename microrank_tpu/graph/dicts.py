"""Faithful dict-form PageRank-graph build (reference component C8).

Reproduces ``get_pagerank_graph`` (/root/reference/preprocess_data.py:146-171)
semantics exactly — including its quirks — so the numpy oracle backend can be
driven by byte-identical inputs:

* call graph ``operation_operation[parent] = [child, child, ...]`` keeps one
  entry per call-edge *instance* (duplicates preserved); childless ops map to
  ``[]`` (preprocess_data.py:160-163);
* the parent-child merge joins on ``ParentSpanId == spanID`` globally (not
  per-trace) over the partition's spans (preprocess_data.py:157-158);
* ``operation_trace`` / ``pr_trace`` are content-identical groupbys
  (SURVEY.md §2.2 quirk #7);
* instance-level (podName) operation naming with the strip rule keyed on
  serviceName (preprocess_data.py:151-155).
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Iterable, List, Tuple

import pandas as pd

from ..io.naming import operation_names
from ..io.schema import DEFAULT_STRIP_LAST_SEGMENT_SERVICES

GraphDicts = Tuple[
    Dict[str, List[str]],  # operation_operation
    Dict[str, List[str]],  # operation_trace: traceID -> [op, ...] (with dups)
    Dict[str, List[str]],  # trace_operation: op -> [traceID, ...] (with dups)
    Dict[str, List[str]],  # pr_trace (== operation_trace)
]


def pagerank_graph_dicts(
    trace_ids: Iterable[str],
    span_df: pd.DataFrame,
    strip_services: FrozenSet[str] = DEFAULT_STRIP_LAST_SEGMENT_SERVICES,
) -> GraphDicts:
    filtered = span_df[span_df["traceID"].isin(set(trace_ids))]
    filtered = filtered.assign(
        operation_name=operation_names(filtered, "pod", strip_services)
    )

    parent_child = filtered[["traceID", "spanID", "ParentSpanId", "operation_name"]]
    merged = parent_child.merge(
        parent_child,
        left_on="ParentSpanId",
        right_on="spanID",
        suffixes=("_child", "_parent"),
    )
    operation_operation = (
        merged.groupby("operation_name_parent")["operation_name_child"]
        .apply(list)
        .to_dict()
    )
    for operation in filtered["operation_name"].unique():
        if operation not in operation_operation:
            operation_operation[operation] = []

    operation_trace = (
        filtered.groupby("traceID")["operation_name"].apply(list).to_dict()
    )
    trace_operation = (
        filtered.groupby("operation_name")["traceID"].apply(list).to_dict()
    )
    pr_trace = {k: list(v) for k, v in operation_trace.items()}

    return operation_operation, operation_trace, trace_operation, pr_trace
