from .build import build_detect_batch, build_window_graph
from .dicts import pagerank_graph_dicts
from .structures import (
    DetectBatch,
    PartitionGraph,
    SloBaseline,
    WindowGraph,
    pad1d,
    pad_to,
)

__all__ = [
    "build_detect_batch",
    "build_window_graph",
    "pagerank_graph_dicts",
    "DetectBatch",
    "PartitionGraph",
    "SloBaseline",
    "WindowGraph",
    "pad1d",
    "pad_to",
]
