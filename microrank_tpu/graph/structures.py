"""Array-form window/graph structures — the host<->device data contract.

The reference passes Python dicts of strings between stages
(preprocess_data.py:146-171 -> pagerank.py:15). Here each stage exchanges
flat, padded, int32/float32 arrays: NamedTuples so they are automatically
JAX pytrees, with dynamic extents carried as 0-d arrays (traced values) and
padded extents carried in the shapes (static under jit).

Sparsity layout: ``p_sr`` and ``p_rs`` (pagerank.py:42-52) share one unique
(op, trace) incidence pattern — only their values differ — so a partition
stores the pair list once with two value arrays.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import NamedTuple

import numpy as np


class PartitionGraph(NamedTuple):
    """One trace partition's PageRank graph, padded, in a shared window
    op-vocab of (padded) size ``V``.

    Extents: E = padded unique (op,trace) incidence entries, C = padded
    unique (child_op, parent_op) call edges, T = padded trace count.
    Padding rows carry value 0.0 / index 0 and are inert under segment-sum.
    """

    # Unique (op, trace) incidence entries (trace ids are partition-local),
    # sorted by (trace, op) — "trace-major". The CSR views below index into
    # this order (and its op-major twin) for the scatter-free kernel.
    inc_op: np.ndarray      # int32[E]
    inc_trace: np.ndarray   # int32[E]
    sr_val: np.ndarray      # float32[E]  = 1 / len_with_dups(trace)   (p_sr)
    rs_val: np.ndarray      # float32[E]  = 1 / cov_with_dups(op)      (p_rs)
    # Unique call-graph edges (child <- parent), sorted by (child, parent).
    ss_child: np.ndarray    # int32[C]
    ss_parent: np.ndarray   # int32[C]
    ss_val: np.ndarray      # float32[C]  = 1 / outdeg_with_dups(parent)
    # CSR views for the cumsum-difference SpMV kernel (kernel="csr"):
    # TPU scatters are expensive, so each SpMV becomes gather -> cumsum ->
    # gather-at-row-boundaries, which only needs each operand grouped by
    # its OUTPUT axis. Trace-major grouping is the storage order above;
    # op-major is this reordered copy. indptr[r]..indptr[r+1] brackets row
    # r's entries; padded rows have empty ranges.
    inc_trace_opmajor: np.ndarray  # int32[E]   trace ids, op-major order
    sr_val_opmajor: np.ndarray     # float32[E] sr_val, op-major order
    inc_indptr_op: np.ndarray      # int32[V+1] op-major row offsets
    inc_indptr_trace: np.ndarray   # int32[T+1] trace-major row offsets
    ss_indptr: np.ndarray          # int32[V+1] call-edge child row offsets
    # Packed-bitmap views for the dense MXU kernel (kernel="packed"):
    # every transition matrix is a 0/1 pattern scaled by a per-source-axis
    # value (p_sr[v,t] = cov[v,t]/len(t), p_rs[t,v] = cov[v,t]/cov_dup(v),
    # p_ss[c,p] = call[c,p]/outdeg(p)), so the device needs only the
    # pattern as a host-packed bitmap (np.packbits, bitorder="big") plus
    # the three inverse vectors — unpacked on device with shift/mask ops
    # (no scatter: TPU scatters cost ~75 ms each at this scale, the whole
    # point of this layout). Empty [x, 0] bitmaps mean "not built" (the
    # window exceeded the build's bitmap budget); choose_kernel then
    # avoids "packed".
    cov_bits: np.ndarray           # uint8[V, T/8] incidence pattern
    ss_bits: np.ndarray            # uint8[V, V/8] call-edge pattern
    inv_tracelen: np.ndarray       # float32[T] = 1/len_with_dups (= sr_val)
    inv_cov_dup: np.ndarray        # float32[V] = 1/cov_with_dups (= rs_val)
    inv_outdeg: np.ndarray         # float32[V] = 1/outdeg_with_dups (= ss_val)
    # Per-trace statistics (partition-local trace axis, padded to T).
    kind: np.ndarray        # int32[T]    size of the trace's dedup kind (C10)
    tracelen: np.ndarray    # int32[T]    # spans in trace (with dups)
    # Per-op statistics on the shared window vocab.
    cov_unique: np.ndarray  # int32[V]    # unique traces covering op (C13)
    op_present: np.ndarray  # bool[V]     op appears in this partition
    # Dynamic extents (0-d int32): actual counts before padding.
    n_ops: np.ndarray       # ops present in this partition (reference O)
    n_traces: np.ndarray    # traces in this partition      (reference T)
    n_inc: np.ndarray       # actual incidence entries
    n_ss: np.ndarray        # actual call edges
    # Kind-collapsed trace axis (graph.build.collapse_window_graph): -1
    # means the trace axis is per-trace (one column per trace, the
    # uncollapsed layout); >= 0 means identical p_sr columns were merged
    # (the reference's own kind-dedup insight, pagerank.py:54-66) and the
    # axis holds ``n_cols`` distinct kind columns. ``kind`` then carries
    # each column's multiplicity, ``sr_val``/``inv_tracelen`` fold it in
    # (m/len), and ``n_traces`` still counts TRUE traces (the spectrum
    # and the iteration's initial value need the real count).
    n_cols: np.ndarray = np.int32(-1)
    # Partition-centric views for the at-scale fallback (kernel="pcsr",
    # after Partition-Centric PageRank, arxiv 1709.07122): entries
    # binned by SOURCE-trace range into P partitions of
    # graph.build.PCSR_PART_TRACES traces each, so neither direction of
    # the coverage SpMV pair ever issues a T-range random gather OR a
    # scatter (the two ops that serialize at scale — scatter measured
    # ~30x a vectorized pass on the bench host, and the whole csr
    # gather story on TPU).
    #
    # Forward (op-output) direction: ``pc_trace``/``pc_sr_val`` hold the
    # entries op-major WITHIN each partition, every (partition, op)
    # range padded to whole PCSR_BLOCK-entry blocks, with
    # ``pc_blk_indptr`` the per-partition dense BLOCK-offset table. The
    # kernel reshapes rv into contiguous [P, S] slices (the streaming
    # load), gathers only LOCAL trace ids (bounded small range),
    # block-sums, prefix-scans the per-partition block sums, and
    # differences at the offset table — a bounded dense [P, V] slab
    # summed over partitions, no scatter anywhere.
    #
    # Backward (trace-output) direction: ``pc_ell_op``/``pc_ell_rs``
    # hold each trace's entries as a fixed-width slab ([T, W], W = max
    # unique ops per trace, zero padding inert) — the output axis is
    # DENSE, so y_rs is a gather from the small [V] vector plus a row
    # sum. [x, 0] placeholders mean "not built".
    pc_trace: np.ndarray = np.zeros((1, 0), np.int32)     # int32[P, Epb] local
    pc_sr_val: np.ndarray = np.zeros((1, 0), np.float32)  # float32[P, Epb]
    pc_blk_indptr: np.ndarray = np.zeros((1, 0), np.int32)  # int32[P, V+1]
    pc_ell_op: np.ndarray = np.zeros((1, 0), np.int32)    # int32[T, W]
    pc_ell_rs: np.ndarray = np.zeros((1, 0), np.float32)  # float32[T, W]
    # Kind-compressed reduced-precision view (kernel="kind", aux="kind"):
    # the coverage PATTERN materialized as int8 over the (collapsed) kind
    # column axis. 0/1 values are exact in every reduced dtype, so the
    # device streams this matrix directly — int8 as-is, or cast once
    # (loop-invariant) to bf16/f32 per PageRankConfig.kind_precision —
    # with NO per-iteration bit-unpack arithmetic. That trade is the
    # point: the packed kernel's roofline is shift/mask unpack compute,
    # not bandwidth, and at the kind-collapsed width (K = distinct trace
    # kinds << T) the 8x byte cost over the bitmap is noise while the
    # unpack disappears. [x, 0] means "not built" (choose_kernel then
    # avoids "kind"). The call-graph term never joins this matrix: the
    # kind kernel computes it as an O(C) scatter-free row-sum over the
    # ss edge list (ss_indptr), not a [V, V] matvec.
    cov_i8: np.ndarray = np.zeros((1, 0), np.int8)        # int8[V, K]


class WindowGraph(NamedTuple):
    """Both partitions of one detection window over a shared op vocab."""

    normal: PartitionGraph
    abnormal: PartitionGraph


@dataclass
class DeltaBuildState:
    """Host-side cache that makes a sliding-window rebuild O(Δ) in the
    expensive work (``graph.build.build_window_graph_delta``).

    The cold build's dominant cost is string-side: pod-level operation
    naming plus three ``pd.factorize`` passes over every span row. This
    state caches the window frame per trace in already-interned int
    form, so the next window pays string work only for the ARRIVING
    rows and replays everything else as vectorized int gathers:

    * per-trace span CSR (op codes + start times + raw span-id refs,
      trace-major) — the splice source when a boundary trace loses its
      departing prefix or gains arriving spans;
    * per-(trace, unique-op) counts and per-trace unique intra-trace
      call edges with multiplicities — the partition assembly inputs
      (coverage, call-graph and kind views all derive from these);
    * a wrapping uint64 per-trace sum of span start times — the
      integrity checksum that routes anything the slide model did not
      predict (late spans, eviction drift, replay duplicates) to the
      cold build instead of silently diverging.

    The op vocab is FROZEN across delta windows (departed names keep
    their codes with zero coverage, masked by ``op_present``; any
    unseen arriving name forces a cold rebuild), so ``v_pad`` — and
    with it the jit pad bucket — cannot shift on the delta route by
    construction. ``shape_sig`` pins the full leaf-shape signature of
    the previous window's graph; a delta assembly whose padded shapes
    differ is discarded in favor of a cold rebuild ("pad signature
    preserved or cold").

    All trace-level arrays are indexed by the state-local trace id
    (``trace_ids[i]`` names trace ``i``); CSR arrays are trace-major
    over that axis.
    """

    start_us: int                  # window bounds this state describes
    end_us: int
    params: tuple                  # build-parameter signature; mismatch -> cold
    op_uniques: list               # frozen window vocab, name-sorted
    op_index: object               # pd.Index over op_uniques (hash join)
    trace_ids: np.ndarray          # object[T]
    trace_index: object            # pd.Index over trace_ids
    span_indptr: np.ndarray        # int64[T+1] per-trace span CSR offsets
    span_op: np.ndarray            # int64[n]  vocab code per span
    span_t_ns: np.ndarray          # int64[n]  startTime, ns
    span_sid: np.ndarray           # object[n] spanID refs
    span_pid: np.ndarray           # object[n] ParentSpanId refs
    uop_indptr: np.ndarray         # int64[T+1] per-trace unique-op offsets
    uop_op: np.ndarray             # int64[sumU] op codes, ascending per trace
    uop_cnt: np.ndarray            # int64[sumU] span count per (trace, op)
    uedge_indptr: np.ndarray       # int64[T+1] per-trace unique-edge offsets
    uedge_child: np.ndarray        # int64[sumC] sorted by (child, parent)
    uedge_parent: np.ndarray       # int64[sumC]
    uedge_cnt: np.ndarray          # int64[sumC] instance multiplicity
    tracelen: np.ndarray           # int64[T] spans per trace (with dups)
    t_checksum: np.ndarray         # uint64[T] wrapping sum of span_t_ns
    shape_sig: tuple = ()          # previous graph's leaf-shape signature
    eligible: bool = True          # False: every next window builds cold
    reason: str = ""               # why (cross_trace / timestamps / ...)


class DetectBatch(NamedTuple):
    """Arrays for the vectorized anomaly detector (components C4+C5).

    Spans of one detection window, interned: ``op`` indexes the SLO
    baseline vocab (service-level naming; -1 = unseen in baseline),
    ``trace`` is window-local. Padding spans carry trace index 0 and
    weight 0 via op=-1/duration=0 and are masked by ``n_spans``.
    """

    op: np.ndarray        # int32[S] id into the SLO vocab, -1 if unknown
    trace: np.ndarray     # int32[S] window-local trace id
    duration_us: np.ndarray  # float32[S] span duration, microseconds
    n_spans: np.ndarray   # int32 0-d
    n_traces: np.ndarray  # int32 0-d


class SloBaseline(NamedTuple):
    """Per-operation SLO stats (component C3), ms, aligned to a Vocab."""

    mean_ms: np.ndarray   # float32[n_ops]
    std_ms: np.ndarray    # float32[n_ops]


def pad_to(n: int, policy: str = "pow2", min_pad: int = 8) -> int:
    """Bucketed padding size to avoid jit recompilation storms.

    "pow2": next power of two — max 2x waste. "pow2q": quarter-pow2
    buckets (1.25/1.5/1.75 x 2^k sub-steps once sizes reach 64) — max
    25% waste for at most 4x the compile-cache entries; every bucket
    stays a multiple of 8 (bitmap byte rows) and keeps a 2^(k-3) factor
    (the sharded stacker still re-pads to its explicit shard/trace
    multiples). At the 1M-span bench shape the padded bitmap shrinks
    ~35%, which is staged bytes AND per-iteration HBM traffic.
    "exact": no padding (recompiles per window)."""
    n = max(int(n), 1)
    if policy == "exact":
        return n
    p = max(min_pad, 1)
    while p < n:
        p <<= 1
    if policy == "pow2q" and p >= 64 and p > min_pad:
        q = p >> 1
        for f_num in (5, 6, 7):  # q*1.25, q*1.5, q*1.75
            cand = (q * f_num) >> 2
            if cand >= n:
                return cand
    return p


def pad1d(arr: np.ndarray, size: int, fill=0) -> np.ndarray:
    out = np.full((size,), fill, dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return out
