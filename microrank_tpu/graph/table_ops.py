"""Array-native window building from a SpanTable (the native-ingest lane).

The pandas lane interns strings per window (build.py); here the native
loader (microrank_tpu.native) already interned everything at load time, so
window slicing, detection batching, and graph building are pure integer
array ops — no strings anywhere past ingest. The PageRank op vocab is the
table's pod_op vocabulary, shared across every window of the table (which
also makes batched multi-window stacking vocab-stable).
"""

from __future__ import annotations

from typing import Iterable, List, Tuple

import numpy as np

from ..analysis.contracts import contract
from ..io.interning import Vocab
from .build import (
    DEFAULT_DENSE_BUDGET_BYTES,
    _build_partition,
    resolve_aux,
)
from .structures import (
    DetectBatch,
    PartitionGraph,
    SloBaseline,
    WindowGraph,
    pad1d,
    pad_to,
)


def compute_slo_from_table(table, stat: str = "mean") -> Tuple[Vocab, SloBaseline]:
    """SLO baseline from a (normal-period) SpanTable — one bincount pass.

    Same semantics as detect.compute_slo (population std, ms, 4 decimals;
    reference preprocess_data.py:50-78), incl. the ``stat="pNN"``
    percentile variants (linear-interpolated, matching np.percentile).
    """
    from ..detect.slo import slo_quantile

    n_ops = len(table.svc_op_names)
    dur = table.duration_us.astype(np.float64)
    counts = np.bincount(table.svc_op, minlength=n_ops).astype(np.float64)
    counts = np.maximum(counts, 1.0)
    s1 = np.bincount(table.svc_op, weights=dur, minlength=n_ops)
    mean = s1 / counts
    # Two-pass variance for numerical agreement with np.std.
    centered = dur - mean[table.svc_op]
    s2 = np.bincount(table.svc_op, weights=centered * centered, minlength=n_ops)
    std = np.sqrt(s2 / counts)
    if stat == "mean":
        center = mean
    else:
        q = slo_quantile(stat)
        order = np.lexsort((dur, table.svc_op))
        s_op = table.svc_op[order]
        s_dur = dur[order]
        ids = np.arange(n_ops)
        starts = np.searchsorted(s_op, ids)
        n = np.searchsorted(s_op, ids, side="right") - starts
        n = np.maximum(n, 1)
        pos = q * (n - 1)
        lo = np.floor(pos).astype(np.int64)
        hi = np.minimum(lo + 1, n - 1)
        frac = pos - lo
        center = s_dur[starts + lo] * (1 - frac) + s_dur[starts + hi] * frac
    baseline = SloBaseline(
        mean_ms=np.round(center / 1000.0, 4).astype(np.float32),
        std_ms=np.round(std / 1000.0, 4).astype(np.float32),
    )
    return Vocab(table.svc_op_names), baseline


def window_rows(table, start_us: int, end_us: int) -> np.ndarray:
    """Row mask for one detection window (get_span semantics:
    startTime >= start AND endTime <= end, preprocess_data.py:10-14)."""
    return (table.start_us >= start_us) & (table.end_us <= end_us)


def window_span_range(table, start_us: int, end_us: int):
    """Candidate row range [lo, hi) of one window on a TIME-SORTED table.

    Every qualifying row (start >= w0 AND end <= w1, with end >= start)
    has start in [w0, w1], which is contiguous under the sort — so the
    per-window predicates only need to run on this slice, making window
    work O(window) instead of O(table) on multi-window replays.
    """
    lo = int(np.searchsorted(table.start_us, start_us, "left"))
    hi = int(np.searchsorted(table.start_us, end_us, "right"))
    return lo, hi


def _slice_table(table, lo: int, hi: int):
    """Row-slice view of a SpanTable (cheap; parent_row values stay
    table-absolute — detection never reads them)."""
    return table._replace(
        trace_id=table.trace_id[lo:hi],
        svc_op=table.svc_op[lo:hi],
        pod_op=table.pod_op[lo:hi],
        duration_us=table.duration_us[lo:hi],
        start_us=table.start_us[lo:hi],
        end_us=table.end_us[lo:hi],
        parent_row=table.parent_row[lo:hi],
    )


@contract(returns=("detectbatch", "any"))
def detect_batch_from_table(
    table,
    mask: np.ndarray,
    slo_vocab: Vocab,
    pad_policy: str = "pow2q",
    min_pad: int = 8,
) -> Tuple[DetectBatch, np.ndarray]:
    """DetectBatch for the masked window rows.

    Returns (batch, trace_codes) where trace_codes[i] is the table-global
    trace id of window-local trace i. The table's svc-op ids are remapped
    into the SLO vocab (unseen -> -1, the reference's bare-except rule).
    The ``detectbatch`` contract (armed behind validate_numerics)
    machine-checks the layout, same as the pandas-lane builder.
    """
    rows = np.flatnonzero(mask)
    remap = slo_vocab.encode(table.svc_op_names)
    op = remap[table.svc_op[rows]]
    g_trace = table.trace_id[rows]
    # Window-local trace interning: trace ids are already table-interned
    # small ints, so a flag + prefix-rank scatter replaces the sort-based
    # np.unique (same ascending-id order, ~5x faster at the 1M-span
    # scale). The scatter costs O(total traces) though — for a SMALL
    # window over a huge table (the many-window runner loop), the
    # windowed np.unique stays cheaper, so pick per window.
    n_total = len(table.trace_names)
    if len(rows) * 4 < n_total:
        uniques, t_codes = np.unique(g_trace, return_inverse=True)
    else:
        flags = np.zeros(n_total, dtype=bool)
        flags[g_trace] = True
        uniques = np.flatnonzero(flags)
        # int32 rank: trace counts fit (trace_id is int32) and the
        # downstream DetectBatch stores int32 — half the bandwidth.
        rank = np.cumsum(flags, dtype=np.int32) - np.int32(1)
        t_codes = rank[g_trace]
    n_spans = len(rows)
    s_pad = pad_to(n_spans, pad_policy, min_pad)
    batch = DetectBatch(
        op=pad1d(op.astype(np.int32), s_pad, fill=-1),
        trace=pad1d(t_codes.astype(np.int32), s_pad),
        duration_us=pad1d(
            table.duration_us[rows].astype(np.float32), s_pad
        ),
        n_spans=np.int32(n_spans),
        n_traces=np.int32(len(uniques)),
    )
    return batch, uniques


def detect_window_partition(
    table,
    w0_us: int,
    w1_us: int,
    slo_vocab: Vocab,
    baseline,
    detector_cfg,
    remap: np.ndarray | None = None,
    thresh: np.ndarray | None = None,
    pad_policy: str = "pow2q",
    min_pad: int = 8,
    with_range: bool = False,
):
    """THE window-detection seam (used by TableRCA, bench single-window
    and bench batched modes alike): returns (mask, nrm_codes, abn_codes,
    n_window_spans) for one [w0, w1) window — the fused C++ scan
    (native.detect_window_native) when available, the numpy twin
    otherwise; both produce identical partitions (parity-tested).

    Time-sorted tables only scan the window's candidate row slice
    (window_span_range). ``with_range=True`` appends that (lo, hi) range
    to the return tuple AND returns the mask over the slice (length
    hi-lo — expanding it to table length costs an O(table) allocation
    per window, which the row-range consumers never need); without it
    the mask is full-length.

    ``remap``/``thresh`` may be passed precomputed (callers looping over
    many windows cache them); otherwise they are derived here.
    """
    from ..detect import detect_numpy
    from ..detect.detector import _thresholds
    from ..native import NativeUnavailable, native_available

    n_spans = table.n_spans
    if getattr(table, "time_sorted", False):
        lo, hi = window_span_range(table, w0_us, w1_us)
    else:
        lo, hi = 0, n_spans
    sub = table if (lo, hi) == (0, n_spans) else _slice_table(table, lo, hi)

    def ret(sub_mask, nrm, abn, n_window):
        if with_range:  # slice-local mask, paired with its range
            return sub_mask, nrm, abn, n_window, (lo, hi)
        if (lo, hi) == (0, n_spans):
            return sub_mask, nrm, abn, n_window
        mask = np.zeros(n_spans, dtype=sub_mask.dtype)
        mask[lo:hi] = sub_mask
        return mask, nrm, abn, n_window

    if native_available():
        from ..native import detect_window_native

        if remap is None:
            remap = np.ascontiguousarray(
                slo_vocab.encode(table.svc_op_names), dtype=np.int32
            )
        if thresh is None:
            thresh = _thresholds(baseline, detector_cfg)
        try:
            sub_mask, nrm, abn, n_window, _ = detect_window_native(
                sub, w0_us, w1_us, remap, thresh, detector_cfg.slack_ms
            )
            return ret(sub_mask, nrm, abn, n_window)
        except NativeUnavailable:
            pass  # fall through to numpy
    sub_mask = window_rows(sub, w0_us, w1_us)
    n_window = int(sub_mask.sum())
    if n_window == 0:
        return ret(sub_mask, None, None, 0)
    batch, trace_codes = detect_batch_from_table(
        sub, sub_mask, slo_vocab, pad_policy, min_pad
    )
    det = detect_numpy(batch, baseline, detector_cfg)
    t = len(trace_codes)
    abn = trace_codes[det.abnormal[:t]]
    nrm = trace_codes[det.valid[:t] & ~det.abnormal[:t]]
    return ret(sub_mask, nrm, abn, n_window)


def _graph_from_padded(p):
    """Wrap one native PaddedPartition (already padded) as PartitionGraph.

    All auxiliary kernel views were exported by the C++ side
    (mr_export_bitmaps / mr_export_csr) per the resolved aux mode — this
    is a pure field copy."""
    return PartitionGraph(
        inc_op=p.inc_op,
        inc_trace=p.inc_trace,
        sr_val=p.sr_val,
        rs_val=p.rs_val,
        ss_child=p.ss_child,
        ss_parent=p.ss_parent,
        ss_val=p.ss_val,
        inc_trace_opmajor=p.inc_trace_opmajor,
        sr_val_opmajor=p.sr_val_opmajor,
        inc_indptr_op=p.inc_indptr_op,
        inc_indptr_trace=p.inc_indptr_trace,
        ss_indptr=p.ss_indptr,
        cov_bits=p.cov_bits,
        ss_bits=p.ss_bits,
        inv_tracelen=p.inv_tracelen,
        inv_cov_dup=p.inv_cov_dup,
        inv_outdeg=p.inv_outdeg,
        kind=p.kind,
        tracelen=p.tracelen,
        cov_unique=p.cov_unique,
        op_present=p.op_present,
        n_ops=np.int32(p.n_ops),
        n_traces=np.int32(p.n_traces),
        n_inc=np.int32(p.n_inc),
        n_ss=np.int32(p.n_ss),
        n_cols=np.int32(p.n_cols),
        pc_trace=p.pc_trace,
        pc_sr_val=p.pc_sr_val,
        pc_blk_indptr=p.pc_blk_indptr,
        pc_ell_op=p.pc_ell_op,
        pc_ell_rs=p.pc_ell_rs,
        cov_i8=p.cov_i8,
    )


def build_window_graph_from_table(
    table,
    mask: np.ndarray,
    normal_trace_codes: Iterable[int],
    abnormal_trace_codes: Iterable[int],
    pad_policy: str = "pow2q",
    min_pad: int = 8,
    use_native: bool = True,
    aux: str = "auto",
    dense_budget_bytes: int = DEFAULT_DENSE_BUDGET_BYTES,
    collapse: str = "off",
    row_range: Tuple[int, int] | None = None,
    kind_dedup_threshold: float | None = None,
) -> Tuple[WindowGraph, List[str], np.ndarray, np.ndarray]:
    """Both partitions' graphs from table rows — ints end to end.

    The op vocab is the table's pod_op vocabulary (stable across windows).
    ``mask`` is a bool row filter (None = all rows). When the native
    library is available (and ``use_native``), both partitions build in
    C++ via fused single-scan counting sorts (graph_builder.cpp); the
    numpy fallback below is array-identical.

    ``collapse`` ("off" | "auto" | "on"): kind-collapse the trace axes
    (graph.build.collapse_window_graph) — the core build then skips the
    per-trace aux views and the post-pass constructs them on the
    collapsed shapes.

    ``row_range`` (lo, hi): every True row of ``mask`` lies inside this
    slice (detect_window_partition's with_range output on a time-sorted
    table) — the build then touches only the slice, O(window) instead of
    O(table) on multi-window replays. ``mask`` may be table-length or
    already slice-local (length hi-lo, as with_range returns it).

    ``kind_dedup_threshold``: the measured-dedup factor past which a
    collapsed auto build constructs the kind-compressed views
    (RuntimeConfig.kind_dedup_threshold; None = the build module's
    default).

    Returns (graph, op_names, normal_codes, abnormal_codes).
    """
    from .build import DEFAULT_KIND_DEDUP_THRESHOLD, collapse_window_graph

    if kind_dedup_threshold is None:
        kind_dedup_threshold = DEFAULT_KIND_DEDUP_THRESHOLD

    vocab_size = len(table.pod_op_names)
    v_pad = pad_to(vocab_size, pad_policy, min_pad)
    lo, hi = row_range if row_range is not None else (0, table.n_spans)
    # Normalize the mask to SLICE-LOCAL form (all uses below are).
    if mask is None:
        mask = np.ones(hi - lo, dtype=bool)
    elif len(mask) != hi - lo:
        if len(mask) != table.n_spans:
            raise ValueError(
                f"mask length {len(mask)} matches neither the row_range "
                f"({hi - lo}) nor the table ({table.n_spans})"
            )
        mask = mask[lo:hi]

    normal_trace_codes = list(normal_trace_codes)
    abnormal_trace_codes = list(abnormal_trace_codes)
    # Window-level aux resolution (one decision for both partitions; every
    # partition code comes from detection over these same rows, so the
    # local trace count equals the code count). Collapsing: the aux views
    # are built by the post-pass on the collapsed shapes instead.
    t_pads = [
        pad_to(max(len(set(c)), 1), pad_policy, min_pad)
        for c in (normal_trace_codes, abnormal_trace_codes)
    ]
    if collapse != "off":
        # The native lane collapses in C++ (mr_collapse_window) and
        # resolves aux against the collapsed shapes there; the numpy
        # fallback runs the core build with aux="none" and the python
        # post-pass below.
        mode = "none"
        native_mode = aux
    else:
        mode = native_mode = resolve_aux(
            aux, v_pad, t_pads, dense_budget_bytes
        )

    def _finish(graph):
        if collapse != "off":
            return collapse_window_graph(
                graph, aux, pad_policy, min_pad, dense_budget_bytes,
                collapse, kind_dedup_threshold=kind_dedup_threshold,
            )
        return graph

    if use_native:
        from ..native import (
            NativeUnavailable,
            build_window_padded,
            native_available,
        )

        if native_available():
            n_total = len(table.trace_names)
            nf = np.zeros(n_total, dtype=np.uint8)
            af = np.zeros(n_total, dtype=np.uint8)
            ncodes = np.asarray(list(normal_trace_codes), dtype=np.int64)
            acodes = np.asarray(list(abnormal_trace_codes), dtype=np.int64)
            if len(ncodes):
                nf[ncodes] = 1
            if len(acodes):
                af[acodes] = 1
            sub_mask = mask  # slice-local (normalized above)
            full = bool(np.all(sub_mask))
            try:
                # parent_row stays ABSOLUTE; the C++ scan subtracts
                # parent_base and bounds-checks — parents outside the
                # slice drop their edge (they cannot be window rows).
                raw_n, raw_a = build_window_padded(
                    table.pod_op[lo:hi],
                    table.trace_id[lo:hi],
                    table.parent_row[lo:hi],
                    None if full else sub_mask,
                    nf,
                    af,
                    vocab_size,
                    v_pad,
                    lambda n: pad_to(n, pad_policy, min_pad),
                    native_mode,
                    collapse=collapse,
                    dense_budget_bytes=dense_budget_bytes,
                    parent_base=lo,
                    kind_dedup_threshold=kind_dedup_threshold,
                )
            except NativeUnavailable:
                raw_n = raw_a = None  # fall through to the numpy lane
            if raw_n is not None:
                graph = WindowGraph(
                    normal=_graph_from_padded(raw_n),
                    abnormal=_graph_from_padded(raw_a),
                )
                # Collapse (when requested) already happened in C++.
                return (
                    graph,
                    list(table.pod_op_names),
                    raw_n.local_uniques.astype(np.int64),
                    raw_a.local_uniques.astype(np.int64),
                )
    rows = lo + np.flatnonzero(mask)
    op_codes = table.pod_op[rows].astype(np.int64)
    g_trace = table.trace_id[rows].astype(np.int64)

    # Parent linkage restricted to the window: map slice-row -> window-pos
    # (slice-local scatter — O(window) when a row_range is given).
    pos_in_window = np.full(hi - lo, -1, dtype=np.int64)
    pos_in_window[rows - lo] = np.arange(len(rows))
    parent = table.parent_row[rows]
    parent_local = np.where(
        (parent >= lo) & (parent < hi), parent - lo, np.int64(-1)
    )
    parent_pos = np.where(
        parent_local >= 0,
        pos_in_window[np.clip(parent_local, 0, None)],
        -1,
    )

    n_total_traces = len(table.trace_names)
    parts = []
    code_arrays = []
    for codes in (normal_trace_codes, abnormal_trace_codes):
        codes = np.asarray(list(codes), dtype=np.int64)
        flags = np.zeros(n_total_traces, dtype=bool)
        if len(codes):
            flags[codes] = True
        pmask = flags[g_trace]
        # Call edges: child in partition AND parent span in the window AND
        # parent's trace in the partition (preprocess_data.py:157-158).
        edge_child = np.flatnonzero(
            pmask
            & (parent_pos >= 0)
            & flags[g_trace[np.clip(parent_pos, 0, None)]]
        )
        part, local = _build_partition(
            op_codes[pmask],
            g_trace[pmask],
            op_codes[edge_child],
            op_codes[np.clip(parent_pos[edge_child], 0, None)],
            vocab_size,
            v_pad,
            pad_policy,
            min_pad,
            mode,
            compute_kinds=(collapse == "off"),
        )
        parts.append(part)
        code_arrays.append(local)

    graph = WindowGraph(normal=parts[0], abnormal=parts[1])
    return (
        _finish(graph),
        list(table.pod_op_names),
        code_arrays[0],
        code_arrays[1],
    )
