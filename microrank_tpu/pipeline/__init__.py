from .checkpoint import WindowCursor, load_slo, save_slo
from .results import ResultSink, WindowResult
from .runner import OnlineRCA, run_rca
from .table_runner import TableRCA, run_rca_native

__all__ = [
    "OnlineRCA",
    "run_rca",
    "TableRCA",
    "run_rca_native",
    "ResultSink",
    "WindowResult",
    "WindowCursor",
    "load_slo",
    "save_slo",
]
