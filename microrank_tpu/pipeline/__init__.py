from .checkpoint import WindowCursor, load_slo, save_slo
from .results import ResultSink, WindowResult
from .runner import OnlineRCA, run_rca

__all__ = [
    "OnlineRCA",
    "run_rca",
    "ResultSink",
    "WindowResult",
    "WindowCursor",
    "load_slo",
    "save_slo",
]
