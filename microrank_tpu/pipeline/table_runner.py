"""The native-ingest fast lane: OnlineRCA over a SpanTable.

Same orchestration semantics as runner.py (reference online_rca.py:155-216
window arithmetic, guards, compat flags) but strings never appear past
ingest: windowing is int64-µs comparisons, detection and graph build are
integer array ops (graph/table_ops.py), ranking is the jitted device
program. This is the path the benchmark and high-volume replays use.
"""

from __future__ import annotations

import time
from typing import List, Optional

import jax
import numpy as np

from ..config import MicroRankConfig
from ..detect import detect_numpy
from ..graph.build import kind_dedup_ratio
from ..graph.table_ops import (
    build_window_graph_from_table,
    compute_slo_from_table,
    detect_batch_from_table,
    window_rows,
)
from ..parallel.sharded_rank import SHARD_KERNELS
from ..rank_backends.jax_tpu import choose_kernel
from ..utils.logging import get_logger
from ..utils.profiling import StageTimings
from .results import ResultSink, WindowResult

_US_PER_MIN = 60_000_000


def _iso(us: int) -> str:
    return str(np.datetime64(int(us), "us"))


def _gauge_inflight(lane: str, n: int) -> None:
    from ..obs.metrics import pipeline_inflight

    pipeline_inflight().set(n, lane=lane)


class TableRCA:
    def __init__(self, config: MicroRankConfig = MicroRankConfig()):
        from ..rank_backends.jax_tpu import validate_tiebreak

        self.config = config
        self.log = get_logger("microrank_tpu.pipeline.table")
        validate_tiebreak(config.spectrum)
        self.slo_vocab = None
        self.baseline = None
        self.policy_resolution = None   # set by fit_baseline
        self._thresh = None       # mu + k*sigma f32, set by fit_baseline
        self._remap_cache = None  # (id(table), svc-op -> SLO vocab remap)
        self._mesh = None
        if config.runtime.mesh_shape is not None:
            from ..parallel.mesh import SHARD_AXIS, WINDOW_AXIS, make_mesh

            shape = tuple(config.runtime.mesh_shape)
            if len(shape) == 1:  # pure graph parallelism
                shape = (1, shape[0])
            # A windows axis > 1 is only usable by run(batch_windows=
            # True), which ranks all anomalous windows in one sharded
            # dispatch; per-window dispatch checks this at rank time.
            self._mesh = make_mesh(shape, (WINDOW_AXIS, SHARD_AXIS))
            self.log.info("ranking on a %s mesh", self._mesh.devices.shape)
            # device_checks now covers the mesh path too: sharded
            # dispatches route through rank_windows_sharded_checked[_
            # traced] (parallel.sharded_rank), the checkify epilogue
            # over the sharded outputs.
            if config.runtime.kernel not in ("auto",) + SHARD_KERNELS:
                self.log.warning(
                    "kernel=%r is not shard-capable; the sharded path "
                    "auto-selects packed or csr instead (different "
                    "summation tree, same math)",
                    config.runtime.kernel,
                )

    def _resolve_shard_kernel(self, graphs) -> str:
        """Kernel for a sharded dispatch (the shared policy —
        parallel.sharded_rank.resolve_shard_kernel — so the table lane
        and the dispatch router cannot disagree)."""
        from ..parallel.sharded_rank import resolve_shard_kernel

        return resolve_shard_kernel(
            graphs, self._mesh, self.config.runtime, self.log
        )

    def _stage_sharded(self, graphs, kernel: str):
        """Sharded staging via the shared recipe
        (parallel.sharded_rank.stage_sharded)."""
        from ..parallel.sharded_rank import stage_sharded

        return stage_sharded(graphs, self._mesh, kernel)

    def fit_baseline(self, normal_table) -> None:
        from ..detect.detector import _thresholds
        from ..scenarios.policy import apply_tuned_policy

        if self.config.ingest.enabled:
            # Value-level admission on the interned table (the native
            # twin of the pandas ladder): a poisoned normal dump must
            # not poison the SLO floor.
            from ..ingest import admit_table

            normal_table, _ = admit_table(
                normal_table, self.config.ingest, source="table:normal"
            )
        self.slo_vocab, self.baseline = compute_slo_from_table(
            normal_table, stat=self.config.detector.slo_stat
        )
        # Tuned-policy resolution (the shared lane seam). The native
        # table exposes span count and the fitted vocab gives op
        # cardinality; trace-kind dedup is not cheaply measurable here,
        # so the profile takes the conservative "low" dedup bucket.
        self.config, self.policy_resolution = apply_tuned_policy(
            self.config,
            lane="table",
            counts=(
                int(getattr(normal_table, "n_spans", 0) or 0),
                len(self.slo_vocab),
                None,
            ),
        )
        self._thresh = _thresholds(self.baseline, self.config.detector)
        self._remap_cache = None
        self.log.info(
            "fitted SLO baseline (native lane): %d operations",
            len(self.slo_vocab),
        )

    def _detect_window(self, table, w0: int, w1: int):
        """One window's detection via the shared seam
        (graph.table_ops.detect_window_partition — fused C++ scan with a
        numpy fallback), with the SLO remap cached per run. Returns
        (mask, nrm, abn, n_window, row_range) — the candidate row slice
        makes per-window work O(window) on time-sorted tables.
        """
        from ..graph.table_ops import detect_window_partition

        cfg = self.config
        # Keyed by id() — valid because run() clears the cache on exit,
        # and the table is alive for the whole run (id reuse is
        # impossible while the key's referent is alive). A strong table
        # reference here would pin ~GB-scale columns on the TableRCA
        # instance after run() returns.
        if self._remap_cache is None or self._remap_cache[0] != id(table):
            self._remap_cache = (
                id(table),
                np.ascontiguousarray(
                    self.slo_vocab.encode(table.svc_op_names),
                    dtype=np.int32,
                ),
            )
        from ..utils.guards import contract_checks

        # validate_numerics arms the @contract on the DetectBatch build
        # (graph.table_ops.detect_batch_from_table) like it does on the
        # rank entry points.
        with contract_checks(cfg.runtime.validate_numerics):
            return detect_window_partition(
                table,
                w0,
                w1,
                self.slo_vocab,
                self.baseline,
                cfg.detector,
                remap=self._remap_cache[1],
                thresh=self._thresh,
                pad_policy=cfg.runtime.pad_policy,
                min_pad=cfg.runtime.min_pad,
                with_range=True,
            )

    def prepare_rank(
        self, table, mask, nrm_codes, abn_codes, row_range=None
    ):
        """Host half of a window rank: build the graph (pure host compute,
        no PJRT calls). Returns (graph, op_names, kernel) for
        ``launch_rank`` — the seam the async pipeline splits at."""
        from ..graph.build import aux_for_kernel

        cfg = self.config
        # Shard-capable kernels: packed/packed_bf16 (trace-sharded MXU
        # bitmap matvecs, ONE psum per iteration — the fastest), csr and
        # coo (entry-sharded, two psums). Explicit requests are honored;
        # "auto" (and non-shardable kernels, which __init__ warned about)
        # resolve like the single-device policy: packed within the dense
        # budget, csr past it.
        if self._mesh is not None:
            k = cfg.runtime.kernel
            shard_kernel = k if k in SHARD_KERNELS else "auto"
            build_aux = aux_for_kernel(shard_kernel, sharded=True)
        else:
            shard_kernel = None
            build_aux = aux_for_kernel(cfg.runtime.kernel)
        graph, op_names, _, _ = build_window_graph_from_table(
            table,
            mask,
            nrm_codes,
            abn_codes,
            pad_policy=cfg.runtime.pad_policy,
            min_pad=cfg.runtime.min_pad,
            aux=build_aux,
            dense_budget_bytes=cfg.runtime.dense_budget_bytes,
            collapse=cfg.runtime.collapse_kinds,
            row_range=row_range,
            kind_dedup_threshold=cfg.runtime.kind_dedup_threshold,
        )
        if self._mesh is not None:
            if int(self._mesh.devices.shape[0]) != 1:
                raise ValueError(
                    "per-window dispatch needs a (1, N) / (N,) mesh; a "
                    "windows axis > 1 only applies to "
                    "run(batch_windows=True)"
                )
            if shard_kernel == "auto":
                shard_kernel = self._resolve_shard_kernel([graph])
        else:
            shard_kernel = cfg.runtime.kernel
            if shard_kernel == "auto":
                shard_kernel = choose_kernel(
                    graph,
                    cfg.runtime.dense_budget_bytes,
                    cfg.runtime.prefer_bf16,
                )
        from ..obs.metrics import record_kind_dedup

        record_kind_dedup(kind_dedup_ratio(graph))
        return graph, op_names, shard_kernel

    def _conv_enabled(self) -> bool:
        """Whether dispatches carry the device convergence trace. The
        checkify program has a residual-traced twin
        (rank_window_checked_traced), so device_checks no longer
        disables it."""
        return bool(self.config.runtime.convergence_trace)

    def _apply_conv(self, result, conv) -> None:
        """Fold a fetched convergence summary into the WindowResult and
        the per-kernel registry metrics."""
        result.apply_convergence(conv)
        if conv:
            from ..obs.metrics import record_convergence

            record_convergence(
                result.kernel or "auto",
                conv["iterations"],
                conv["final_residual"]
                if conv["final_residual"] is not None
                else float("nan"),
            )

    @staticmethod
    def _conv_summary(residuals, n_iters):
        """{iterations, final_residual, residuals} from FETCHED arrays
        ([2, I] or a row thereof) — host-side, post-device_get only."""
        res = np.asarray(
            residuals,
            dtype=np.float64,  # mrlint: disable=R2(host-side summary of an already-fetched trace; never re-enters a jnp expression)
        )
        n = int(n_iters)
        joint = res.max(axis=0)[:n]
        return {
            "iterations": n,
            "final_residual": float(joint[-1]) if n else None,
            "residuals": [float(x) for x in joint],
        }

    def launch_rank(self, graph, op_names, kernel):
        """Device half of a window rank: stage the graph (device_put /
        global_put) and dispatch the jitted program — with the
        convergence trace in the output tuple when
        runtime.convergence_trace is on. Latency-bound PJRT calls only —
        safe to run on a staging worker thread. Returns opaque handles
        ``(device_outputs, op_names)`` (arrays still in flight — jax
        dispatch is async) to pass to ``finalize_rank``."""
        cfg = self.config
        conv = self._conv_enabled()
        from ..utils.guards import contract_checks

        # validate_numerics also arms the trace-time @contract checks on
        # the rank entry points (analysis.contracts).
        with contract_checks(cfg.runtime.validate_numerics):
            if self._mesh is not None:
                from ..parallel.sharded_rank import resolve_sharded_rank_fn

                batched = self._stage_sharded([graph], kernel)
                fn = resolve_sharded_rank_fn(
                    conv, cfg.runtime.device_checks
                )
                batch_outs = fn(
                    batched, cfg.pagerank, cfg.spectrum, self._mesh, kernel
                )
                outs = tuple(o[0] for o in batch_outs)
            else:
                from ..rank_backends.blob import stage_rank_window
                from ..rank_backends.jax_tpu import device_subset

                outs = stage_rank_window(
                    device_subset(graph, kernel),
                    cfg.pagerank,
                    cfg.spectrum,
                    kernel,
                    cfg.runtime.blob_staging,
                    checked=cfg.runtime.device_checks,
                    conv_trace=conv,
                )
        return outs, op_names

    def dispatch_rank(
        self, table, mask, nrm_codes, abn_codes, row_range=None
    ):
        """Build one window's graph and dispatch its device rank program.

        Returns opaque handles (device arrays still in flight — jax
        dispatch is async) to pass to ``finalize_rank``. The host is free
        to build the next window while the device executes this one.
        """
        return self.launch_rank(
            *self.prepare_rank(table, mask, nrm_codes, abn_codes, row_range)
        )

    def _assign_topk(self, result, op_names, ti_row, ts_row, n, label):
        """The one top-k -> WindowResult.ranking assignment (shared by
        the chunked, batched and bulk lanes): slice by n_valid, map
        through the op vocab, validate, zip."""
        names = [op_names[int(i)] for i in ti_row[:n]]
        scores = [float(s) for s in ts_row[:n]]
        if self.config.runtime.validate_numerics:
            from ..utils.guards import assert_finite_scores

            assert_finite_scores(scores, label)
        result.ranking = list(zip(names, scores))

    def finalize_rank_many(self, handles_list):
        """Force MANY dispatched ranks' results to host in ONE batched
        ``jax.device_get`` — per-buffer (and per-window) fetches each pay
        a full RPC round trip on tunneled-TPU runtimes (~78-110 ms apiece
        measured), so never convert device scalars/arrays piecemeal on
        this path, and prefer joining several windows per call
        (fetch_mode="bulk"). The convergence trace rides the same fetch.
        Multi-host runs route through fetch_replicated (allgather of any
        process-spanning shards). Returns [(names, scores, conv), ...]
        in input order; ``conv`` is the _conv_summary dict or None."""
        from ..parallel.distributed import fetch_replicated

        fetched = fetch_replicated(tuple(h[0] for h in handles_list))
        out = []
        for h, outs in zip(handles_list, fetched):
            op_names = h[1]
            top_idx, top_scores, n_valid = outs[:3]
            n = int(n_valid)
            names = [op_names[int(i)] for i in top_idx[:n]]
            scores = [float(s) for s in top_scores[:n]]
            if self.config.runtime.validate_numerics:
                from ..utils.guards import assert_finite_scores

                assert_finite_scores(scores, "TableRCA.rank_window")
            conv = (
                self._conv_summary(outs[3], outs[4])
                if len(outs) > 3
                else None
            )
            out.append((names, scores, conv))
        return out

    def finalize_rank(self, handles):
        """Force a dispatched rank's results to host (blocks if needed).
        Returns (names, scores, conv-summary-or-None)."""
        return self.finalize_rank_many([handles])[0]

    def rank_window(self, table, mask, nrm_codes, abn_codes):
        """Rank one window given its row mask and trace-code partitions;
        returns (names, scores)."""
        names, scores, _ = self.finalize_rank(
            self.dispatch_rank(table, mask, nrm_codes, abn_codes)
        )
        return names, scores

    def run(
        self,
        table,
        out_dir=None,
        sink: Optional[ResultSink] = None,
        batch_windows: bool = False,
        resume: bool = False,
        end_us: Optional[int] = None,
        complete_only: bool = False,
    ) -> List[WindowResult]:
        """Slide over the table; RCA every anomalous window.

        ``end_us`` bounds the window loop (default: the table's last
        span end); ``complete_only`` skips a final window that would
        extend past that bound instead of ranking it partially — the
        follow/tail mode's closure rule (pipeline.follow), where the
        bound is the ingest horizon and a half-filled window must wait
        for the next poll.

        ``batch_windows=True`` runs two-phase: detection decides the
        window advance rule (it alone does — ranking never feeds back into
        the loop), all anomalous windows' graphs are then stacked over one
        leading axis and ranked in a single vmapped device call
        (BASELINE.json config 4: batched multi-window spectrum). The
        table-global pod vocabulary makes the stacked graphs name-stable.

        Otherwise the loop is pipelined up to
        ``runtime.pipeline_depth`` device programs deep: a window's rank
        is dispatched asynchronously and only forced once the next
        window's host work is done, so graph build overlaps device
        execution. Results are emitted to the sink strictly in window
        order either way.

        ``resume`` (needs ``out_dir``): restart from the persisted
        window cursor. The cursor records the NEXT window start and only
        advances when a window's result has actually been emitted — a
        crash mid-pipeline re-runs the inflight windows instead of
        dropping them.
        """
        from pathlib import Path

        from .checkpoint import WindowCursor

        cfg = self.config
        if self.baseline is None:
            raise RuntimeError("call fit_baseline() before run()")
        from ..analysis.mrsan import configure_sanitizers
        from ..obs.spans import configure_tracer
        from ..utils.guards import claim_device_owner

        configure_tracer(cfg.obs)  # fresh span ring per run
        configure_sanitizers(cfg)  # mrsan arm/disarm + reset
        # The table lane drives the device from the calling thread; the
        # async stage/fetch executors are authorized delegates (their
        # single-width PJRT calls are ordered by construction).
        claim_device_owner("table-lane")
        if cfg.ingest.enabled:
            # Admission on the interned table (values + budgets; the
            # native loader already settled parse/linkage): rejected
            # rows land in the dead-letter store next to the results.
            from ..ingest import admit_table, configure_quarantine

            configure_quarantine(cfg.ingest, default_dir=out_dir)
            table, _rej = admit_table(
                table, cfg.ingest, source="table"
            )
        if sink is None and out_dir is not None:
            sink = ResultSink(
                out_dir, overwrite_csv=cfg.compat.overwrite_results
            )
        cursor = (
            WindowCursor(Path(out_dir) / "cursor.json")
            if out_dir is not None
            else None
        )
        journal = None
        if out_dir is not None and cfg.runtime.telemetry:
            from ..obs import JOURNAL_NAME, RunJournal, set_current_journal

            journal = RunJournal(Path(out_dir) / JOURNAL_NAME)
            set_current_journal(journal)
            journal.run_start(
                pipeline="table",
                kernel=cfg.runtime.kernel,
                pad_policy=cfg.runtime.pad_policy,
                collapse_kinds=cfg.runtime.collapse_kinds,
                pipeline_depth=cfg.runtime.pipeline_depth,
                fetch_mode=cfg.runtime.fetch_mode,
                batch_windows=bool(batch_windows),
                mesh=(
                    list(cfg.runtime.mesh_shape)
                    if cfg.runtime.mesh_shape
                    else None
                ),
            )
        if table.n_spans == 0:
            return []

        detect_us = int(cfg.window.detect_minutes * _US_PER_MIN)
        skip_us = int(cfg.window.skip_minutes * _US_PER_MIN)
        depth = max(1, int(cfg.runtime.pipeline_depth))
        current = int(table.start_us.min())
        end = int(table.end_us.max())
        if end_us is not None:
            end = min(end, int(end_us))
        if resume and cursor is not None:
            saved = cursor.load()
            if saved is not None:
                current = int(
                    np.datetime64(saved, "us").astype(np.int64)
                )
                self.log.info("resuming window loop at %s", saved)

        # Async dispatch: staging (device_put + dispatch) and fetches run
        # on one worker thread each, so their RPC latency overlaps the
        # main thread's detect/build. Multi-process meshes must issue
        # collectives in program order on every rank, which worker
        # threads cannot guarantee — force synchronous there.
        async_mode = bool(cfg.runtime.async_dispatch) and not batch_windows
        if batch_windows and cfg.runtime.device_checks and self._mesh is None:
            # ADVICE r4: the single-device batched program has no
            # checkify variant — say so instead of silently dropping
            # the user's in-program checks (host-side validate_numerics
            # still applies to every window). On a mesh, batch mode DOES
            # check: _rank_pending routes through the sharded checked
            # programs.
            self.log.warning(
                "device_checks applies to per-window dispatch only; "
                "run(batch_windows=True) without a mesh ranks without "
                "checkify instrumentation"
            )
        if async_mode and jax.process_count() > 1:
            self.log.warning(
                "async_dispatch is single-process only (collective "
                "ordering); running synchronously"
            )
            async_mode = False
        if async_mode and cfg.runtime.device_checks:
            # checkify's error check is a synchronous device fetch, so
            # each checked dispatch blocks its worker thread — the
            # pipeline overlap would be silently lost. Make the trade
            # explicit: checks are a debug mode, run synchronously.
            self.log.warning(
                "device_checks forces synchronous dispatch (the "
                "in-program error check fetches device state per window)"
            )
            async_mode = False
        # Bulk fetch: defer result fetches and join up to
        # bulk_fetch_windows windows in ONE batched device_get — each
        # per-window fetch pays a full RPC round trip on tunneled
        # runtimes, and the outputs deferred are only the top-k arrays.
        bulk = cfg.runtime.fetch_mode == "bulk" and not batch_windows
        if cfg.runtime.fetch_mode not in ("stream", "bulk"):
            raise ValueError(
                f"unknown fetch_mode {cfg.runtime.fetch_mode!r}"
            )
        if bulk and jax.process_count() > 1:
            self.log.warning(
                "fetch_mode='bulk' is single-process only (collective "
                "ordering of the batched allgather); streaming instead"
            )
            bulk = False
        # Micro-batched dispatch (dispatch_batch_windows > 1): group K
        # anomalous windows into ONE stacked stage+dispatch — one
        # staging RPC per group instead of per window. Single-device,
        # single-process, unchecked only.
        chunk_n = max(1, int(cfg.runtime.dispatch_batch_windows))
        if chunk_n > 1:
            reason = None
            if batch_windows:
                reason = (
                    "batch_windows=True already ranks every anomalous "
                    "window in one dispatch"
                )
            elif self._mesh is not None:
                reason = "a mesh is configured (sharded dispatch)"
            elif jax.process_count() > 1:
                reason = "multi-process runs need per-rank ordering"
            elif cfg.runtime.device_checks:
                reason = "device_checks has no batched checkify variant"
            if reason is not None:
                self.log.warning(
                    "dispatch_batch_windows=%d ignored: %s; dispatching "
                    "per window",
                    chunk_n,
                    reason,
                )
                chunk_n = 1

        stage_pool = fetch_pool = None
        if async_mode:
            from concurrent.futures import ThreadPoolExecutor

            from ..utils.guards import authorize_device_thread

            stage_pool = ThreadPoolExecutor(
                1, "mr-stage", initializer=authorize_device_thread
            )
            if not bulk and chunk_n == 1:  # bulk/chunked join in batches
                fetch_pool = ThreadPoolExecutor(
                    1, "mr-fetch", initializer=authorize_device_thread
                )

        results: List[WindowResult] = []
        pending = []  # (result, mask, nrm, abn) for deferred batched rank
        inflight = []  # (result, handles-or-future, timings) dispatched
        finishing = []  # (result, finalize future, timings) async fetches
        emitted = 0  # results[:emitted] already sent to the sink
        next_cursor = {}  # id(result) -> post-advance window position (µs)

        def _emit(r):
            sink.emit(r)
            if journal is not None:
                journal.window(r)
            # Not in batch mode: there all ranking completes BEFORE any
            # emit, so per-window saves would be N redundant writes
            # right before cursor.clear().
            if (
                cursor is not None
                and not batch_windows
                and id(r) in next_cursor
            ):
                cursor.save(_iso(next_cursor[id(r)]))

        def _emit_ready():
            """Emit results in window order, stopping at the oldest
            still-inflight window (its ranking isn't final yet)."""
            nonlocal emitted
            if sink is None or batch_windows:
                return
            # Oldest-first: finishing < inflight < chunk_pending (built
            # but not yet dispatched groups also block emission).
            if finishing:
                stop = id(finishing[0][0])
            elif inflight:
                head = inflight[0][0]
                stop = (
                    id(head[0][0]) if isinstance(head, list) else id(head)
                )
            elif chunk_pending:
                stop = id(chunk_pending[0][0])
            else:
                stop = None
            while emitted < len(results):
                r = results[emitted]
                if id(r) == stop:
                    break
                _emit(r)
                emitted += 1

        def _set_ranking(result, timings, names, scores, conv=None):
            result.ranking = list(zip(names, scores))
            result.timings = timings.as_dict()
            self._apply_conv(result, conv)
            _emit_ready()

        def _complete_one():
            """Join the oldest async fetch and emit its window."""
            result, fut, timings = finishing.pop(0)
            with timings.stage("rank_wait"):
                names, scores, conv = fut.result()
            _set_ranking(result, timings, names, scores, conv)

        def _finalize_one():
            result, handles, timings = inflight.pop(0)
            _gauge_inflight("window", len(inflight))
            if fetch_pool is not None:
                # handles is the staging future: chain its join with the
                # fetch on the fetch worker so the device_get RPC of
                # window N overlaps the device_put of window N+1.
                fut = fetch_pool.submit(
                    lambda h=handles: self.finalize_rank(h.result())
                )
                finishing.append((result, fut, timings))
                if len(finishing) > depth:
                    _complete_one()
                return
            with timings.stage("rank_wait"):
                names, scores, conv = self.finalize_rank(handles)
            _set_ranking(result, timings, names, scores, conv)

        chunk_pending = []  # (result, graph, op_names, kernel, timings)

        def _launch_chunk(items):
            """Stage + dispatch one group of windows as a single stacked
            vmapped program (runs on the stage worker in async mode —
            PJRT calls only, the graphs are already built)."""
            from ..parallel.sharded_rank import stack_window_graphs
            from ..rank_backends.blob import stage_rank_windows_batched
            from ..rank_backends.jax_tpu import device_subset

            graphs = [g for _, g, _, _, _ in items]
            kernels = {k for _, _, _, k, _ in items}
            if len(kernels) == 1:
                kern = kernels.pop()
                stacked = stack_window_graphs(
                    [device_subset(g, kern) for g in graphs]
                )
            else:
                # Mixed per-window choices: re-resolve on the stacked
                # views (stacking already degraded mixed aux families).
                stacked = stack_window_graphs(graphs)
                kern = choose_kernel(
                    stacked,
                    max(
                        1,
                        cfg.runtime.dense_budget_bytes // len(items),
                    ),
                    cfg.runtime.prefer_bf16,
                )
                stacked = device_subset(stacked, kern)
            return stage_rank_windows_batched(
                stacked,
                cfg.pagerank,
                cfg.spectrum,
                kern,
                cfg.runtime.blob_staging,
                conv_trace=self._conv_enabled(),
            )

        def _flush_chunk():
            if not chunk_pending:
                return
            items = chunk_pending[:]
            chunk_pending.clear()
            handles = (
                stage_pool.submit(_launch_chunk, items)
                if stage_pool is not None
                else _launch_chunk(items)
            )
            inflight.append((items, handles, None))
            _gauge_inflight("chunk", len(inflight))

        def _assign_chunk(items, outs, wait_ms_per_window):
            ti, ts, nv = outs[:3]
            for b, (result, _, names, _, timings) in enumerate(items):
                self._assign_topk(
                    result, names, ti[b], ts[b], int(nv[b]),
                    "TableRCA chunked window",
                )
                result.timings = {
                    **timings.as_dict(),
                    "chunk_fetch_ms": round(wait_ms_per_window, 3),
                    "chunk_windows": len(items),
                }
                if len(outs) > 3:
                    self._apply_conv(
                        result, self._conv_summary(outs[3][b], outs[4][b])
                    )

        def _finalize_chunk_one():
            """Join the oldest dispatched group (one batched fetch)."""
            items, handles, _ = inflight.pop(0)
            _gauge_inflight("chunk", len(inflight))
            h = handles.result() if hasattr(handles, "result") else handles
            t0 = time.perf_counter()
            outs = jax.device_get(h)
            wait_ms = (time.perf_counter() - t0) * 1e3
            _assign_chunk(items, outs, wait_ms / len(items))
            _emit_ready()

        def _flush_bulk_chunks():
            """Join EVERY dispatched group in ONE batched device_get."""
            if not inflight:
                return
            entries = inflight[:]
            hs = [
                e[1].result() if hasattr(e[1], "result") else e[1]
                for e in entries
            ]
            t0 = time.perf_counter()
            fetched = jax.device_get(tuple(hs))
            wait_ms = (time.perf_counter() - t0) * 1e3
            n_total = sum(len(e[0]) for e in entries)
            for (items, _, _), outs in zip(entries, fetched):
                _assign_chunk(items, outs, wait_ms / n_total)
            inflight.clear()
            _gauge_inflight("chunk", 0)
            _emit_ready()

        def _flush_bulk():
            """Join EVERY deferred window's results in one batched fetch
            (fetch_mode="bulk"). ALL rankings are assigned before
            anything emits — ``inflight`` stays populated until then, so
            no batch-mate can reach the sink half-finished — and only
            then does one _emit_ready release the batch in window order.

            Timing (ADVICE r4): the single RPC's wall time is reported as
            a batch-level ``bulk_fetch_ms`` key amortized evenly over the
            batch (each window also records the batch size), instead of
            skewing one window's rank_wait with the whole batch's cost."""
            if not inflight:
                return
            items = inflight[:]
            handles = [
                h.result() if hasattr(h, "result") else h
                for _, h, _ in items
            ]
            t0 = time.perf_counter()
            ranked = self.finalize_rank_many(handles)
            wait_s = time.perf_counter() - t0
            for (result, _, timings), (names, scores, conv) in zip(
                items, ranked
            ):
                result.ranking = list(zip(names, scores))
                result.timings = {
                    **timings.as_dict(),
                    "bulk_fetch_ms": round(wait_s * 1e3 / len(items), 3),
                    "bulk_fetch_windows": len(items),
                }
                self._apply_conv(result, conv)
            inflight.clear()
            _gauge_inflight("window", 0)
            _emit_ready()

        loop_depth = (
            max(1, int(cfg.runtime.bulk_fetch_windows)) if bulk else depth
        )
        if chunk_n > 1:
            finalize_cb = (
                _flush_bulk_chunks if bulk else _finalize_chunk_one
            )
        else:
            finalize_cb = _flush_bulk if bulk else _finalize_one

        try:
            self._window_loop(
                table, current, end, detect_us, skip_us, loop_depth,
                batch_windows, results, pending, inflight, finishing,
                next_cursor, stage_pool, finalize_cb, _complete_one,
                _emit_ready, chunk_n, chunk_pending, _flush_chunk, bulk,
                complete_only,
            )
        finally:
            if stage_pool is not None:
                stage_pool.shutdown(wait=False, cancel_futures=True)
            if fetch_pool is not None:
                fetch_pool.shutdown(wait=False, cancel_futures=True)
            # The remap cache is keyed by id(table); drop it so the id
            # key can't alias a future table and the remap array doesn't
            # outlive the run.
            self._remap_cache = None

        if batch_windows and pending:
            self._rank_pending(table, pending)
        if batch_windows and sink is not None:
            for r in results:
                _emit(r)
        if journal is not None:
            journal.run_end(
                windows=len(results),
                ranked=sum(1 for r in results if r.ranking),
            )
        if cursor is not None:
            if end_us is not None or complete_only:
                # Bounded runs (the follow/tail mode's polls) leave the
                # cursor at the next unranked window so the next poll —
                # or a restarted process — continues from there. The
                # per-window saves above already advanced it.
                pass
            else:
                cursor.clear()
        return results

    def _window_loop(
        self, table, current, end, detect_us, skip_us, depth,
        batch_windows, results, pending, inflight, finishing,
        next_cursor, stage_pool, _finalize_one, _complete_one,
        _emit_ready, chunk_n=1, chunk_pending=None, _flush_chunk=None,
        chunk_bulk=False, complete_only=False,
    ):
        """The sliding-window detect/dispatch loop of run() (factored out
        so the worker pools shut down on any exit path).

        ``chunk_n > 1``: micro-batched dispatch — prepared windows gather
        in ``chunk_pending`` and ``_flush_chunk`` stages each full group
        as one stacked program. ``depth`` then bounds GROUPS in flight
        (stream fetches — joining by windows would fetch every group
        right after its own dispatch, losing the build/execute overlap)
        or WINDOWS in flight (``chunk_bulk``, where depth is
        bulk_fetch_windows and the join is one fetch of everything)."""
        from ..obs.metrics import record_window_outcome
        from ..obs.spans import get_tracer

        tracer = get_tracer()
        cfg = self.config
        while (
            current + detect_us <= end if complete_only else current < end
        ):
            w0, w1 = current, current + detect_us
            # One trace per window (trace_id = the window start): the
            # StageTimings ctx pins every stage span — including ones
            # completing later on the async fetch workers — to it.
            timings = StageTimings(ctx=tracer.new_trace(f"win-{_iso(w0)}"))
            result = WindowResult(start=_iso(w0), end=_iso(w1), anomaly=False)
            ranked = False

            with timings.stage("detect"):
                mask, nrm, abn, n_window, row_range = self._detect_window(
                    table, w0, w1
                )
            if n_window == 0:
                result.skipped_reason = "empty_window"
            else:
                result.anomaly = (
                    len(abn) >= cfg.detector.min_abnormal_traces
                )
                result.n_normal, result.n_abnormal = len(nrm), len(abn)
                result.n_traces = len(nrm) + len(abn)
                if result.anomaly and (len(nrm) == 0 or len(abn) == 0):
                    result.skipped_reason = "degenerate_partition"
                elif result.anomaly:
                    if cfg.compat.partition_swap:
                        nrm, abn = abn, nrm
                    ranked = True
                    if batch_windows:
                        pending.append((result, mask, nrm, abn, row_range))
                    elif chunk_n > 1:
                        with timings.stage("rank_dispatch"):
                            graph, op_names, kernel = self.prepare_rank(
                                table, mask, nrm, abn, row_range
                            )
                        result.kernel = kernel
                        result.kind_dedup = kind_dedup_ratio(graph)
                        result.queue_depth = len(inflight)
                        chunk_pending.append(
                            (result, graph, op_names, kernel, timings)
                        )
                        if len(chunk_pending) >= chunk_n:
                            _flush_chunk()
                        if chunk_bulk:
                            if sum(len(e[0]) for e in inflight) >= depth:
                                _finalize_one()
                        elif len(inflight) >= depth:
                            # Groups in flight bound by >= depth like the
                            # per-window lane — the pre-fix > let depth+1
                            # groups pile onto the device (advisor r5).
                            _finalize_one()
                    else:
                        with timings.stage("rank_dispatch"):
                            prep = self.prepare_rank(
                                table, mask, nrm, abn, row_range
                            )
                            result.kernel = prep[2]
                            result.kind_dedup = kind_dedup_ratio(prep[0])
                            if stage_pool is not None:
                                handles = stage_pool.submit(
                                    self.launch_rank, *prep
                                )
                            else:
                                handles = self.launch_rank(*prep)
                        result.queue_depth = len(inflight)
                        inflight.append((result, handles, timings))
                        _gauge_inflight("window", len(inflight))
                        if len(inflight) >= depth:
                            _finalize_one()

            record_window_outcome(
                "ranked" if ranked
                else ("skipped" if result.skipped_reason else "clean")
            )
            results.append(result)
            if not (result.anomaly and not result.skipped_reason) or batch_windows:
                result.timings = timings.as_dict()
            if ranked:
                current += skip_us
            current += detect_us
            next_cursor[id(result)] = current
            _emit_ready()

        if chunk_n > 1 and chunk_pending:
            _flush_chunk()  # dispatch the final partial group
        while inflight:
            _finalize_one()
        while finishing:
            _complete_one()
        _emit_ready()

    def _rank_pending(self, table, pending) -> None:
        """Phase 2 of batch_windows: one vmapped rank over all windows —
        sharded over the full (windows, shard) mesh when one is
        configured (the windows axis splits the batch, the shard axis
        splits each window's graph), vmapped single-device otherwise."""
        from ..parallel.sharded_rank import stack_window_graphs

        from ..graph.build import aux_for_kernel
        from ..parallel.distributed import fetch_replicated

        cfg = self.config
        if self._mesh is not None:
            k = cfg.runtime.kernel
            kernel = k if k in SHARD_KERNELS else "auto"
            w_n = int(self._mesh.devices.shape[0])
        else:
            kernel = cfg.runtime.kernel
            w_n = 1
        graphs = []
        op_names = list(table.pod_op_names)
        timings = StageTimings()
        # Concurrently-resident windows per device: the whole batch under
        # single-device vmap, ceil(B/windows-axis) on a mesh.
        per_device = -(-len(pending) // w_n)
        build_aux = aux_for_kernel(kernel, sharded=self._mesh is not None)
        with timings.stage("build"):
            for res, mask, nrm, abn, row_range in pending:
                graph, _, _, _ = build_window_graph_from_table(
                    table, mask, nrm, abn,
                    pad_policy=cfg.runtime.pad_policy,
                    min_pad=cfg.runtime.min_pad,
                    aux=build_aux,
                    dense_budget_bytes=max(
                        1, cfg.runtime.dense_budget_bytes // per_device
                    ),
                    collapse=cfg.runtime.collapse_kinds,
                    row_range=row_range,
                    kind_dedup_threshold=cfg.runtime.kind_dedup_threshold,
                )
                res.kind_dedup = kind_dedup_ratio(graph)
                graphs.append(graph)
        conv = self._conv_enabled()
        with timings.stage("rank_batched"):
            if self._mesh is not None:
                if kernel == "auto":
                    kernel = self._resolve_shard_kernel(graphs)
                # The batch must divide the windows axis: pad by
                # repeating the last window and drop the tail rows.
                n_pad = (-len(graphs)) % w_n
                batched = self._stage_sharded(
                    graphs + [graphs[-1]] * n_pad, kernel
                )
                from ..parallel.sharded_rank import (
                    resolve_sharded_rank_fn,
                )

                fn = resolve_sharded_rank_fn(
                    conv, cfg.runtime.device_checks
                )
                outs = fn(
                    batched, cfg.pagerank, cfg.spectrum, self._mesh, kernel
                )
            else:
                from ..rank_backends.blob import stage_rank_windows_batched
                from ..rank_backends.jax_tpu import device_subset

                stacked = stack_window_graphs(graphs)
                if kernel == "auto":
                    kernel = choose_kernel(
                        stacked,
                        cfg.runtime.dense_budget_bytes // per_device,
                        cfg.runtime.prefer_bf16,
                    )
                outs = stage_rank_windows_batched(
                    device_subset(stacked, kernel),
                    cfg.pagerank,
                    cfg.spectrum,
                    kernel,
                    cfg.runtime.blob_staging,
                    conv_trace=conv,
                )
            # One batched fetch: per-buffer transfers each pay an RPC
            # round trip on tunneled-TPU runtimes; the convergence
            # traces ride the same fetch.
            outs = fetch_replicated(tuple(outs))
        top_idx, top_scores, n_valid = outs[:3]
        shared = timings.as_dict()
        for b, (result, _, _, _, _) in enumerate(pending):
            result.kernel = kernel
            self._assign_topk(
                result, op_names, top_idx[b], top_scores[b],
                int(n_valid[b]), f"TableRCA batched window {b}",
            )
            result.timings = {**result.timings, **shared}
            if len(outs) > 3:
                self._apply_conv(
                    result, self._conv_summary(outs[3][b], outs[4][b])
                )


def run_rca_native(
    normal_path,
    abnormal_path,
    config: MicroRankConfig = MicroRankConfig(),
    out_dir=None,
) -> List[WindowResult]:
    """Native-lane equivalent of pipeline.run_rca: CSV paths in,
    window results out, no pandas anywhere."""
    from ..native import load_span_table

    rca = TableRCA(config)
    rca.fit_baseline(load_span_table(normal_path))
    return rca.run(load_span_table(abnormal_path), out_dir=out_dir)
