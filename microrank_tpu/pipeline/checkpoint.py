"""Checkpoint / resume (SURVEY.md §5 checkpoint row).

The reference persists nothing but its (overwritten) result.csv and
recomputes the SLO baseline from the full normal dump on every run
(online_rca.py:253). Here the expensive derived state — the SLO vocab +
stats — caches to an npz, and the sliding-window loop checkpoints its
cursor so a long replay resumes deterministically after a restart
(the analyzer itself is stateless per window, so this is all the state
there is).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from ..graph.structures import SloBaseline
from ..io.interning import Vocab


def save_slo(path, vocab: Vocab, baseline: SloBaseline) -> None:
    np.savez_compressed(
        path,
        names=np.asarray(vocab.names, dtype=object),
        mean_ms=baseline.mean_ms,
        std_ms=baseline.std_ms,
    )


def load_slo(path) -> Tuple[Vocab, SloBaseline]:
    with np.load(path, allow_pickle=True) as z:
        vocab = Vocab([str(n) for n in z["names"]])
        baseline = SloBaseline(
            mean_ms=z["mean_ms"].astype(np.float32),
            std_ms=z["std_ms"].astype(np.float32),
        )
    return vocab, baseline


class WindowCursor:
    """Persisted position of the sliding-window loop (ISO-8601 string)."""

    def __init__(self, path):
        self.path = Path(path)

    def load(self) -> Optional[str]:
        if not self.path.exists():
            return None
        try:
            return json.loads(self.path.read_text()).get("current_time")
        except (json.JSONDecodeError, OSError):
            return None

    def save(self, current_time: str) -> None:
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self.path.write_text(json.dumps({"current_time": current_time}))

    def clear(self) -> None:
        if self.path.exists():
            self.path.unlink()
