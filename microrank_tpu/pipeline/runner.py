"""The online RCA orchestrator (reference L4: online_rca.py:155-216).

Sliding-window loop over an abnormal span dump: detect -> partition ->
rank -> emit. Faithful to the reference's window arithmetic (5-minute
detection windows, +4-minute skip after an anomaly, advance +5 always)
with its failure modes fixed:

* empty windows produce a skipped record instead of the reference's bare
  ``return False`` unpack crash (anormaly_detector.py:48-50 vs
  online_rca.py:167);
* results append per window instead of overwriting (quirk #5) unless
  ``compat.overwrite_results``;
* the partition swap at the reference's orchestrator boundary (quirk #1)
  is reproduced only under ``compat.partition_swap``;
* the loop checkpoints its cursor for deterministic resume.
"""

from __future__ import annotations

from pathlib import Path
from typing import List, Optional

import pandas as pd

from ..config import MicroRankConfig
from ..detect import compute_slo
from ..io.loader import window_spans
from ..obs.metrics import record_window_outcome
from ..rank_backends import get_backend
from ..utils.logging import get_logger
from ..utils.profiling import StageTimings
from .checkpoint import WindowCursor, load_slo, save_slo
from .results import ResultSink, WindowResult


class OnlineRCA:
    def __init__(self, config: MicroRankConfig = MicroRankConfig()):
        self.config = config
        self.backend = get_backend(config)
        self.log = get_logger("microrank_tpu.pipeline")
        self.slo_vocab = None
        self.baseline = None
        self.policy_resolution = None   # set by fit_baseline

    # ------------------------------------------------------------------ SLO
    def fit_baseline(self, normal_df: pd.DataFrame, cache_path=None) -> None:
        """Compute (or load) the SLO baseline from a normal-period dump
        (reference: online_rca.py:251-253). Also the tuned-policy
        resolution point (the shared lane seam): the normal dump is the
        workload-profile witness, and the backend re-resolves so a
        policy-supplied spectrum method/kernel reaches the programs."""
        from ..scenarios.policy import apply_tuned_policy

        self.config, self.policy_resolution = apply_tuned_policy(
            self.config, lane="run", profile_frame=normal_df
        )
        if self.policy_resolution.outcome == "applied":
            self.backend = get_backend(self.config)
        if self.config.ingest.enabled:
            # A poisoned normal dump must not poison the SLO floor:
            # the baseline fits on the admitted subset only.
            from ..ingest import admit_frame

            adm = admit_frame(
                normal_df, self.config.ingest, source="run:normal"
            )
            if adm.degraded:
                self.log.warning(
                    "normal dump: %d/%d rows rejected by admission; "
                    "baseline fits on the clean subset",
                    adm.n_rejected, adm.n_input,
                )
            normal_df = adm.frame
        if cache_path is not None and Path(cache_path).exists():
            self.slo_vocab, self.baseline = load_slo(cache_path)
            self.log.info(
                "loaded SLO baseline from %s (%d ops)",
                cache_path,
                len(self.slo_vocab),
            )
            return
        self.slo_vocab, self.baseline = compute_slo(
            normal_df, stat=self.config.detector.slo_stat
        )
        self.log.info("fitted SLO baseline: %d operations", len(self.slo_vocab))
        if cache_path is not None:
            save_slo(cache_path, self.slo_vocab, self.baseline)

    # --------------------------------------------------------------- detect
    def detect_window(self, window_df: pd.DataFrame):
        """Detect + partition one window; returns (flag, normal,
        abnormal) via the shared seam (``detect.detect_partition`` —
        the same latency + error-status classification serve and the
        streaming engine run)."""
        if self.baseline is None:
            raise RuntimeError("call fit_baseline() before detection")
        from ..detect import detect_partition

        return detect_partition(
            self.config, self.slo_vocab, self.baseline, window_df
        )

    # ------------------------------------------------------------------ run
    def run(
        self,
        data: pd.DataFrame,
        out_dir=None,
        sink: Optional[ResultSink] = None,
        resume: bool = False,
    ) -> List[WindowResult]:
        """Slide over ``data`` (the abnormal dump) and RCA every anomalous
        window (reference: online_anomaly_detect_RCA, online_rca.py:155)."""
        cfg = self.config
        if cfg.ingest.enabled:
            from ..ingest import TraceClock, configure_quarantine, pre_admit_frame

            configure_quarantine(cfg.ingest, default_dir=out_dir)
            # The batch twin of the stream engine's pre-windowing gate:
            # unplaceable rows quarantine before the window loop ever
            # slices, and trace-relative clock skew repairs against the
            # first-seen registry — a displaced root span must not turn
            # into a spurious anomaly in somebody else's window.
            data, pre_rejected = pre_admit_frame(
                data, cfg.ingest, source="run",
                trace_clock=TraceClock(),
            )
            if pre_rejected:
                self.log.warning(
                    "abnormal dump: %d rows rejected before windowing "
                    "(%s)",
                    sum(pre_rejected.values()),
                    ", ".join(
                        f"{k}={v}"
                        for k, v in sorted(pre_rejected.items())
                    ),
                )
        if sink is None and out_dir is not None:
            sink = ResultSink(out_dir, overwrite_csv=cfg.compat.overwrite_results)
        cursor = (
            WindowCursor(Path(out_dir) / "cursor.json")
            if out_dir is not None
            else None
        )
        journal = None
        if out_dir is not None and cfg.runtime.telemetry:
            from ..obs import JOURNAL_NAME, RunJournal, set_current_journal

            journal = RunJournal(Path(out_dir) / JOURNAL_NAME)
            set_current_journal(journal)
            journal.run_start(
                pipeline="pandas",
                backend=self.backend.name,
                kernel=cfg.runtime.kernel,
                pad_policy=cfg.runtime.pad_policy,
            )

        detect_td = pd.Timedelta(minutes=cfg.window.detect_minutes)
        skip_td = pd.Timedelta(minutes=cfg.window.skip_minutes)
        start = data["startTime"].min()
        end = data["endTime"].max()
        current = start
        if resume and cursor is not None:
            saved = cursor.load()
            if saved is not None:
                current = pd.Timestamp(saved)
                self.log.info("resuming window loop at %s", current)

        results: List[WindowResult] = []
        while current < end:
            w_start, w_end = current, current + detect_td
            timings = StageTimings()
            result = WindowResult(start=str(w_start), end=str(w_end), anomaly=False)

            window_df = window_spans(data, w_start, w_end)
            if len(window_df) > 0 and cfg.ingest.enabled:
                # Per-window admission ladder (the shared ingest seam):
                # the clean subset detects/ranks, rejected rows are in
                # the dead-letter store, and a window mostly made of
                # garbage is refused whole (low_admission).
                from ..ingest import admit_frame

                with timings.stage("admit"):
                    adm = admit_frame(
                        window_df, cfg.ingest, source="run",
                        window_bounds=(w_start, w_end),
                        known_ops=(
                            frozenset(self.slo_vocab.names)
                            if self.slo_vocab is not None
                            else None
                        ),
                    )
                window_df = adm.frame
                result.ingest_rejected = adm.n_rejected
                result.degraded_input = adm.degraded
                if adm.degraded and journal is not None:
                    journal.emit(
                        "ingest", stage="window",
                        window_start=str(w_start),
                        **adm.journal_fields(),
                    )
                if adm.admission_ratio < cfg.ingest.min_admission_ratio:
                    result.skipped_reason = "low_admission"
                    window_df = window_df.iloc[:0]
            if len(window_df) == 0:
                if result.skipped_reason is None:
                    result.skipped_reason = "empty_window"
            else:
                with timings.stage("detect"):
                    flag, nrm, abn = self.detect_window(window_df)
                result.anomaly = flag
                result.n_normal, result.n_abnormal = len(nrm), len(abn)
                result.n_traces = len(nrm) + len(abn)
                if flag and (not nrm or not abn):
                    # Degenerate partition: skip, as the reference does
                    # (online_rca.py:176-178).
                    result.skipped_reason = "degenerate_partition"
                elif flag:
                    if cfg.compat.partition_swap:
                        # Reference quirk #1: roles inverted downstream.
                        nrm, abn = abn, nrm
                    with timings.stage("rank"):
                        top, scores = self.backend.rank_window(
                            window_df, nrm, abn
                        )
                    result.ranking = list(zip(top, scores))
                    result.apply_convergence(
                        getattr(self.backend, "last_convergence", None)
                    )
                    self.log.info(
                        "window %s: anomaly (%d/%d abnormal), top-1 %s",
                        w_start,
                        result.n_abnormal,
                        result.n_traces,
                        top[0] if top else "-",
                    )

            result.timings = timings.as_dict()
            results.append(result)
            record_window_outcome(
                "ranked" if result.ranking
                else ("skipped" if result.skipped_reason else "clean")
            )
            if sink is not None:
                sink.emit(result)
            if journal is not None:
                journal.window(result)

            if result.anomaly and result.ranking:
                current = current + skip_td  # +4 min (online_rca.py:215)
            current = current + detect_td  # +5 min (online_rca.py:216)
            if cursor is not None:
                cursor.save(str(current))

        if journal is not None:
            journal.run_end(
                windows=len(results),
                ranked=sum(1 for r in results if r.ranking),
            )
        if cursor is not None:
            cursor.clear()
        return results


def run_rca(
    normal_df: pd.DataFrame,
    abnormal_df: pd.DataFrame,
    config: MicroRankConfig = MicroRankConfig(),
    out_dir=None,
) -> List[WindowResult]:
    """One-call equivalent of the reference's __main__
    (online_rca.py:219-255): baseline from the normal dump, RCA over the
    abnormal dump."""
    rca = OnlineRCA(config)
    rca.fit_baseline(normal_df)
    return rca.run(abnormal_df, out_dir=out_dir)
