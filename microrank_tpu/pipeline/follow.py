"""Follow/tail mode: rank windows of a GROWING trace dump as they close.

The reference's README documents a (historical) online loop over a live
Elasticsearch backend (/root/reference/README.md:40-47); its current
code — and this repo's batch mode — replay static CSV dumps. This
module makes "online RCA" literal for the file-drop deployment shape:
a collector (collect/clickhouse.py, or any exporter) appends spans to a
CSV; ``follow_table`` polls the file, ingests what's new, and ranks
every detection window that has CLOSED since the last poll, emitting
results incrementally through the normal sink.

Closure rule: a window [w0, w1) is ranked only once the ingest horizon
(the newest span START seen, minus ``grace_us`` for stragglers) passes
w1 — ``TableRCA.run(end_us=horizon, complete_only=True)``. The window
cursor (pipeline.checkpoint) persists the NEXT window start across
polls AND process restarts, so a crashed follower resumes exactly where
it stopped — the same at-least-once semantics as batch resume.

Ingest cost per poll: the batch loop's ``load_span_table`` re-parses
the grown file WITH THE SIDECAR CACHE OFF — a write racing the parse
could pin a sidecar whose recorded (mtime, size) matches the appended
file but whose content predates the append, silently dropping the tail
forever; and rewriting a full-table .npz every poll would be a second
O(file) cost. The full re-parse is unavoidable HERE (the window cursor
re-ranks windows whose spans straddle polls, so the whole table must
exist), and fine at the minutes-scale windows this mode targets. The
STREAMING tail (stream.sources.FileTailSource), which only ever needs
the newly appended rows, uses ``TailTracker.read_appended`` instead:
a byte-offset cursor feeds the CSV parser only the header plus the
complete lines appended since the last successful parse (PR 5) —
O(appended) per poll, with rotation/truncation falling back to a full
re-read.
"""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Callable, Iterator, List, Optional

from ..utils.logging import get_logger
from .results import WindowResult

log = get_logger("microrank_tpu.pipeline.follow")


class TailTracker:
    """Shared tail-poll bookkeeping — ONE source of truth for the tail
    rules, used by the batch follow loop below and the streaming
    ``stream.sources.FileTailSource``:

    * growth detection (``size == last`` counts idle);
    * rotation/truncation (``size < last``): counted
      (``follow_rotations``), ``rotated`` flagged so callers reset
      their cursors, and the file re-reads from scratch — including the
      incremental byte cursor below;
    * parse failures (torn final line): counted
      (``follow_parse_failures``) AND counted toward ``idle_exit`` — a
      permanently corrupt tail must not starve the exit condition
      (advisor round 5);
    * ``idle_exit`` consecutive no-progress polls stop the loop
      (0 = follow forever);
    * **byte-offset incremental parse** (PR 5): ``read_appended``
      remembers the last byte offset handed to the CSV parser and
      returns only the header plus the complete lines appended since —
      each poll costs O(appended), not O(file). Rotation/truncation
      resets the cursor, so those polls still fall back to a full
      re-parse.
    """

    def __init__(self, idle_exit: int = 0):
        self.idle_exit = int(idle_exit)
        self.last_size = -1
        self.idle = 0
        self.rotated = False
        # Incremental-parse cursor: absolute byte offset already fed to
        # the parser (0 = nothing, header included), plus the cached
        # header line prepended to each appended slice.
        self.parsed_offset = 0
        self._header: Optional[bytes] = None
        self.bytes_parsed = 0   # cumulative bytes handed to the parser

    def _idle_tick(self) -> str:
        self.idle += 1
        if self.idle_exit and self.idle >= self.idle_exit:
            return "exit"
        return "idle"

    def observe_size(self, size: int) -> str:
        """Classify one poll's file size: "grew" | "idle" | "exit"."""
        from ..obs.metrics import follow_polls, follow_rotations

        follow_polls().inc()
        self.rotated = False
        if 0 <= size < self.last_size:
            log.warning(
                "follow: file shrank %d -> %d bytes "
                "(rotation/truncation); re-reading", self.last_size, size,
            )
            follow_rotations().inc()
            self.last_size = -1
            self.rotated = True
            # Incremental cursor falls back to a full re-parse.
            self.parsed_offset = 0
            self._header = None
        if size == self.last_size or size < 0:
            return self._idle_tick()
        return "grew"

    def parse_failed(self, exc) -> str:
        """One failed ingest parse: "retry" | "exit". ``last_size``
        stays unchanged so the next poll re-reads even without
        further growth."""
        from ..obs.metrics import follow_parse_failures

        log.warning("follow: ingest failed (%s); retrying", exc)
        follow_parse_failures().inc()
        return "exit" if self._idle_tick() == "exit" else "retry"

    def restore_cursor(self, offset: int, size: int, header: bytes) -> None:
        """Seed the incremental-parse cursor from a checkpoint (the
        streaming tail's ``--resume`` path): the next poll reads only
        bytes appended past ``offset``. Callers must have verified the
        file was not rotated since (stream.sources rotation signature);
        a stale cursor on a rotated file would slice mid-record."""
        self.parsed_offset = int(offset)
        self.last_size = int(size)
        self._header = header

    def force_rotation(self) -> None:
        """Reset the cursor exactly as an observed size-shrink would
        (chaos ``source_rotation`` seam): full re-read next poll."""
        from ..obs.metrics import follow_rotations

        follow_rotations().inc()
        self.last_size = -1
        self.rotated = True
        self.parsed_offset = 0
        self._header = None

    def parsed(self, size: int, offset: Optional[int] = None) -> None:
        """One successful parse at ``size`` bytes resets the idle run;
        ``offset`` (incremental mode) advances the byte cursor to the
        end of the last line actually parsed."""
        self.idle = 0
        self.last_size = size
        if offset is not None:
            self.parsed_offset = int(offset)

    def read_appended(self, path, size: int):
        """Incremental slice for the CSV parser: ``(payload, offset)``
        where ``payload`` is the header line plus every COMPLETE line
        appended since ``parsed_offset`` and ``offset`` is the absolute
        byte position the cursor should advance to once the parse
        succeeds (pass it to :meth:`parsed`). Returns ``None`` when
        only a torn partial line has been appended — the caller should
        treat the poll as no-progress and retry; the cursor does not
        move, so the bytes re-read next poll. A parse FAILURE likewise
        leaves the cursor in place (``parse_failed`` semantics are
        unchanged), re-feeding the same slice until it parses or
        idle_exit fires."""
        with open(path, "rb") as f:
            if self.parsed_offset <= 0:
                # Full (re-)read: the header is the first line.
                chunk = f.read(size)
                cut = chunk.rfind(b"\n")
                if cut < 0:
                    return None
                head_end = chunk.find(b"\n")
                self._header = chunk[: head_end + 1]
                payload = chunk[: cut + 1]
                self.bytes_parsed += len(payload)
                return payload, cut + 1
            f.seek(self.parsed_offset)
            chunk = f.read(max(0, size - self.parsed_offset))
        cut = chunk.rfind(b"\n")
        if cut < 0 or self._header is None:
            return None
        payload = self._header + chunk[: cut + 1]
        self.bytes_parsed += len(payload)
        return payload, self.parsed_offset + cut + 1


def follow_table(
    rca,
    path,
    out_dir,
    poll_seconds: float = 5.0,
    grace_us: int = 0,
    idle_exit: int = 0,
    max_polls: int = 0,
    sleep: Callable[[float], None] = time.sleep,
) -> Iterator[List[WindowResult]]:
    """Tail ``path`` (a growing traces CSV) and yield each poll's NEWLY
    ranked window results.

    ``rca`` is a fitted TableRCA (``fit_baseline`` already called);
    ``out_dir`` is REQUIRED — the window cursor lives there and is what
    makes polls (and restarts) incremental. ``idle_exit`` > 0 stops
    after that many consecutive polls without PROGRESS — no file growth
    OR a failed ingest parse both count (advisor round 5: a permanently
    torn/corrupt tail used to starve idle_exit forever, retrying without
    ever counting as idle). File rotation/truncation (``size <
    last_size``) is detected, counted (``follow_rotations``) and
    re-read from scratch. (0 = follow forever); ``max_polls`` bounds
    total polls (0 = unbounded). ``sleep`` is injectable for tests.
    """
    from ..native import load_span_table

    if out_dir is None:
        raise ValueError(
            "follow mode needs out_dir: the window cursor there is "
            "what makes polls incremental"
        )
    path = Path(path)
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    tracker = TailTracker(idle_exit=idle_exit)
    polls = 0
    while True:
        polls += 1
        size = os.path.getsize(path) if path.exists() else -1
        # Rotation note: the tracker re-reads from scratch; the window
        # cursor still guards against re-RANKING old windows, so a
        # rotated-in file that restarts the timeline simply yields
        # nothing new until it passes the cursor again.
        status = tracker.observe_size(size)
        if status != "grew":
            if status == "exit":
                log.info(
                    "follow: no progress for %d polls; exiting",
                    tracker.idle,
                )
                return
            if max_polls and polls >= max_polls:
                return
            sleep(poll_seconds)
            continue
        try:
            table = load_span_table(path, cache=False)
        except (ValueError, OSError) as exc:
            # A torn final line (the collector flushed mid-row) parses
            # as an error THIS poll and as valid data the next — retry,
            # with the failure counting toward idle_exit (tracker).
            if tracker.parse_failed(exc) == "exit":
                log.info(
                    "follow: %d polls without progress (last: parse "
                    "failure); exiting", tracker.idle,
                )
                return
            if max_polls and polls >= max_polls:
                return
            sleep(poll_seconds)
            continue
        tracker.parsed(size)
        if table.n_spans == 0:
            if max_polls and polls >= max_polls:
                return
            sleep(poll_seconds)
            continue
        horizon = int(table.start_us.max()) - int(grace_us)
        new = rca.run(
            table,
            out_dir=out_dir,
            resume=True,
            end_us=horizon,
            complete_only=True,
        )
        emitted = [r for r in new if r.ranking]
        log.info(
            "follow poll %d: %d bytes, horizon %s, %d windows scanned, "
            "%d ranked",
            polls, size, horizon, len(new), len(emitted),
        )
        yield new
        if max_polls and polls >= max_polls:
            return
        sleep(poll_seconds)


def run_follow(
    rca,
    path,
    out_dir,
    poll_seconds: float = 5.0,
    grace_us: int = 0,
    idle_exit: int = 0,
    max_polls: int = 0,
    on_results: Optional[Callable[[List[WindowResult]], None]] = None,
) -> int:
    """Drive follow_table to completion (the CLI entry): returns the
    total number of ranked windows."""
    ranked = 0
    for batch in follow_table(
        rca, path, out_dir,
        poll_seconds=poll_seconds,
        grace_us=grace_us,
        idle_exit=idle_exit,
        max_polls=max_polls,
    ):
        if on_results is not None:
            on_results(batch)
        ranked += sum(1 for r in batch if r.ranking)
    return ranked
