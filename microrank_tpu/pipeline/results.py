"""Result records and sinks (reference: online_rca.py:202-214).

The reference writes ``result.csv`` with mode 'w' per anomaly window, so
only the last anomaly of a run survives (SURVEY.md §2.2 quirk #5). The
default sink here appends one JSONL record per window (machine-readable,
full context: window bounds, partition sizes, timings, ranking) plus a
reference-shaped CSV; ``overwrite`` reproduces the quirk for compat runs.
"""

from __future__ import annotations

import csv
import dataclasses
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Tuple


@dataclass
class WindowResult:
    """Everything the pipeline learned about one detection window."""

    start: str
    end: str
    anomaly: bool
    n_traces: int = 0
    n_normal: int = 0
    n_abnormal: int = 0
    ranking: List[Tuple[str, float]] = field(default_factory=list)
    timings: Dict[str, float] = field(default_factory=dict)
    skipped_reason: Optional[str] = None
    # Telemetry (obs subsystem): device-side convergence trace of the
    # rank program (power-iteration steps run + final joint L-inf
    # residual), the kernel that ranked the window, and how many
    # dispatches were in flight when this one launched. None when the
    # window wasn't ranked or convergence_trace is off.
    rank_iterations: Optional[int] = None
    rank_residual: Optional[float] = None
    kernel: Optional[str] = None
    queue_depth: Optional[int] = None
    # Dispatch route the window's device program took ("vmapped" |
    # "sharded", dispatch router); None off the router paths.
    route: Optional[str] = None
    # Measured trace-kind dedup factor of the window's graph build
    # (graph.build.kind_dedup_ratio — true traces / distinct kind
    # columns; 1.0 uncollapsed, None when the window wasn't built).
    # The per-window journal twin of microrank_kind_dedup_ratio.
    kind_dedup: Optional[float] = None
    # Request-scoped fields (serve/ subsystem): the caller-supplied
    # request id and tenant, whether the response came from the
    # numpy_ref fallback after a failed device dispatch, and how many
    # windows shared this window's device dispatch (micro-batch
    # occupancy). All None/False on the offline pipelines.
    request_id: Optional[str] = None
    tenant: Optional[str] = None
    degraded: bool = False
    batch_windows: Optional[int] = None
    # Rank provenance (explain/ subsystem): the window's ExplainBundle
    # data when the caller asked for it (serve explain:true) — None
    # everywhere else; the bundle files are the durable form.
    explain: Optional[dict] = None
    # Span admission (ingest/ subsystem): rows of this window the
    # admission ladder refused (each one in the dead-letter store with
    # a reason), and whether the ranking therefore ran on a partial —
    # degraded-but-correct — clean subset of the window.
    ingest_rejected: int = 0
    degraded_input: bool = False

    def apply_convergence(self, conv: Optional[dict]) -> None:
        """Fold a convergence summary ({iterations, final_residual, ...})
        into the record (shared by every fetch lane)."""
        if conv:
            self.rank_iterations = conv.get("iterations")
            self.rank_residual = conv.get("final_residual")

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        d["ranking"] = [[n, float(s)] for n, s in self.ranking]
        return json.dumps(d)


class ResultSink:
    """Persists window results: JSONL (always append) + reference-shaped
    CSV (``level,result,rank,confidence`` — online_rca.py:212-214)."""

    def __init__(self, out_dir, overwrite_csv: bool = False):
        self.out_dir = Path(out_dir)
        self.out_dir.mkdir(parents=True, exist_ok=True)
        self.jsonl_path = self.out_dir / "windows.jsonl"
        self.csv_path = self.out_dir / "result.csv"
        self.overwrite_csv = overwrite_csv
        self._csv_initialized = False
        self.results: List[WindowResult] = []

    def emit(self, result: WindowResult) -> None:
        self.results.append(result)
        with open(self.jsonl_path, "a") as f:
            f.write(result.to_json() + "\n")
        if result.anomaly and result.ranking:
            self._write_csv(result)

    def _write_csv(self, result: WindowResult) -> None:
        if self.overwrite_csv:
            # Reference-exact shape: 4 columns, overwritten per anomaly
            # (online_rca.py:210-214).
            with open(self.csv_path, "w", newline="") as f:
                writer = csv.writer(f)
                writer.writerow(["level", "result", "rank", "confidence"])
                for rank, (service, score) in enumerate(result.ranking, 1):
                    writer.writerow(["span", service, rank, float(score)])
            return
        mode = "a" if self._csv_initialized or self.csv_path.exists() else "w"
        with open(self.csv_path, mode, newline="") as f:
            writer = csv.writer(f)
            if mode == "w":
                writer.writerow(
                    ["level", "result", "rank", "confidence", "window_start"]
                )
            for rank, (service, score) in enumerate(result.ranking, 1):
                writer.writerow(
                    ["span", service, rank, float(score), result.start]
                )
        self._csv_initialized = True
