"""Durable engine state: the versioned, checksummed ``state.ckpt``.

A crashed/restarted ``cli stream`` used to lose everything host-side:
the online SLO baselines (exp-decay moments + P^2 marker arrays), the
incident open/resolve state, the windower watermark, and the source
cursor — so a restart re-entered cold start, re-opened incidents it had
already reported, and re-read or skipped spans. The checkpoint makes
the engine crash-only: every healthy-window boundary (and the SIGTERM
drain) atomically rewrites one small JSON file under the run dir, and
``cli stream --resume`` restores it so the restarted process continues
the SAME run — zero duplicate ``incident_open`` events, no cold-start
window gating, the source picked up at its checkpointed offset.

File format (version 1)::

    {"version": 1, "ts": ..., "sha256": "<payload digest>",
     "payload": {"baseline": ..., "tracker": ..., "windower": ...,
                 "source": ..., "summary": ...}}

Fleet extensions (PR 11, additive within version 1): a fleet worker's
``tracker`` is the coordinator proxy's state (``{"type": "fleet",
"window_no", "buffered": [parked reports]}`` — single-process and
fleet checkpoints refuse to cross-restore), and ``source`` wraps the
inner cursor in the partition-filter identity (``{"type":
"partitioned", "partition_by", "n_partitions", "partitions",
"inner"}``) so a cursor taken under a different partition assignment
rejects WHOLE instead of silently resuming a different sub-stream.

The digest is over the canonical (sorted-keys) JSON of ``payload``; a
truncated, bit-flipped or hand-edited checkpoint is REJECTED
(:class:`CheckpointError`) rather than half-restored — the engine then
logs and cold-starts, which is always safe (at-least-once semantics:
the windower's restored emit cursor is what guards exactly-once window
effects, and it is only trusted when the checksum holds).

Writes go through ``utils.atomic`` (tmp + fsync + rename) with the
``checkpoint`` chaos seam fired between the durable tmp write and the
rename — the injected-crash test pins that the OLD checkpoint still
loads after a kill at that exact instant.
"""

from __future__ import annotations

import hashlib
import json
import time
from pathlib import Path

CHECKPOINT_VERSION = 1
CHECKPOINT_NAME = "state.ckpt"


class CheckpointError(RuntimeError):
    """Unreadable / corrupt / incompatible checkpoint — never half-load."""


def _digest(payload: dict) -> str:
    canon = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode("utf-8")).hexdigest()


def save_checkpoint(path, payload: dict) -> Path:
    """Atomically write ``payload`` as the engine checkpoint. May raise
    ``InjectedFault`` (chaos seam ``checkpoint``) AFTER the tmp write
    and BEFORE the rename — the caller treats that as the crash it
    simulates; the previous checkpoint is untouched."""
    from ..utils.atomic import atomic_write_json

    doc = {
        "version": CHECKPOINT_VERSION,
        "ts": time.time(),
        "sha256": _digest(payload),
        "payload": payload,
    }
    return atomic_write_json(path, doc, fault_seam="checkpoint")


def load_checkpoint(path) -> dict:
    """Read + verify a checkpoint; returns the payload dict. Raises
    :class:`CheckpointError` on any defect (missing file, torn JSON,
    wrong version, checksum mismatch)."""
    path = Path(path)
    try:
        raw = path.read_text()
    except OSError as e:
        raise CheckpointError(f"unreadable checkpoint {path}: {e}") from e
    try:
        doc = json.loads(raw)
    except ValueError as e:
        raise CheckpointError(
            f"corrupt checkpoint {path} (torn JSON): {e}"
        ) from e
    if not isinstance(doc, dict) or "payload" not in doc:
        raise CheckpointError(f"malformed checkpoint {path}")
    version = doc.get("version")
    if version != CHECKPOINT_VERSION:
        raise CheckpointError(
            f"checkpoint {path} has version {version!r}; this build "
            f"reads version {CHECKPOINT_VERSION}"
        )
    payload = doc["payload"]
    if _digest(payload) != doc.get("sha256"):
        raise CheckpointError(
            f"checkpoint {path} failed its checksum (bit rot or a "
            "non-atomic writer)"
        )
    return payload
