"""Unified fault-injection registry: one seeded, deterministic surface.

MicroRank's own evaluation is chaos injection — faults are injected
into a live system and the ranker must stay correct while the world
misbehaves (PAPER.md). The repo grew two ad-hoc knobs for that
(``ServeConfig.inject_dispatch_failures``,
``ObsConfig.inject_stage_sleep_ms``); this module replaces the pattern
with ONE registry every seam consults, so a chaos scenario is a JSON
document instead of scattered counters:

    {"seed": 7, "faults": [
        {"seam": "dispatch",    "kind": "fail",    "count": 2},
        {"seam": "build",       "kind": "fail",    "after": 1, "count": 1},
        {"seam": "source_stall","kind": "stall",   "value": 200, "count": 1},
        {"seam": "webhook",     "kind": "hang",    "value": 500, "count": 1},
        {"seam": "checkpoint",  "kind": "crash",   "after": 2, "count": 1}
    ]}

Seams (each one a point the span tracer already instruments):

* ``dispatch`` / ``serve_dispatch`` — device rank dispatch (stream /
  serve); ``fail`` raises before the router call, retried by the
  unified retry policy (chaos.retry).
* ``build`` — the build-pool graph preparation; ``fail`` raises inside
  the worker, retried there (the window is never dropped).
* ``fetch`` — the result fetch; ``nan`` poisons the attempt so the
  finite-score validation trips and the dispatch retries clean.
* ``source_stall`` / ``source_torn`` / ``source_rotation`` — the span
  source: an extra poll stall, a simulated torn tail line (parse fails
  this poll, data intact the next), a forced cursor reset (rotation).
* ``source_data`` — DATA corruption at the source (ReplaySource /
  SyntheticSource, per chunk): kinds ``corrupt_row`` (unparseable
  timestamps + negative/NaN durations), ``dup_span`` (duplicated
  rows), ``orphan`` (parent ids repointed at ghosts), ``clock_skew``
  (cross-host time shifts, half clampable half hopeless) and
  ``cardinality_bomb`` (one adversarial trace of unique op names) —
  generated deterministically by ``ingest.hostile.corrupt_frame``
  seeded from the plan seed + event number; the span-admission ladder
  (ingest/) is the defense under test. ``value`` sets the corrupted
  row fraction (or the bomb's op count).
* ``webhook`` — the incident webhook POST: ``hang`` (bounded sleep) or
  ``http_5xx``/``fail`` (raised, enqueued for the sink's retry queue).
* ``checkpoint`` — the state.ckpt writer, fired BETWEEN the durable tmp
  write and the rename: the crash the atomic protocol exists to survive.
* ``stage:<name>`` — a latency injection inside any traced span (the
  legacy ``inject_stage_sleep_ms`` knob's seam).
* ``host_kill`` — the fleet worker's per-window report point; ``kill``
  terminates the WHOLE worker process with ``os._exit`` (no drain, no
  final checkpoint — the loss a SIGKILL models; the coordinator's
  lease expiry and the worker's ``--resume`` rejoin are the recovery
  under test).
* ``heartbeat_drop`` — the worker's heartbeat loop; ``drop`` skips the
  send (the lease keeps aging — enough consecutive drops and the
  coordinator declares the host dead while it is still running).
* ``coordinator_unreachable`` — the worker->coordinator HTTP client;
  ``fail`` raises as a connection failure, driving the worker-side
  report buffering + backoff/breaker path without a real partition.

Fleet plans are usually shared by every process of the fleet (the
launcher passes one ``--chaos`` file to all workers); a spec carrying
``"host": "host1"`` fires only in the process that called
:func:`set_chaos_host` with that id, so one plan can kill exactly one
host of a three-host fleet deterministically.

Determinism: spec matching is pure event counting per seam (``after`` /
``count`` / ``every``); probabilistic specs (``prob`` < 1) draw from a
``random.Random(seed)`` stream, so the same plan over the same run
replays the same faults. The legacy knobs keep working and record
their firings through :func:`record_injection`, so every injected
fault — planned or legacy — lands in
``microrank_fault_injections_total{seam,kind}`` and the run journal.
"""

from __future__ import annotations

import json
import random
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, List, Optional

from ..utils.guards import published
from ..utils.logging import get_logger

log = get_logger("microrank_tpu.chaos")

# Kinds that unwind the seam with an InjectedFault when they fire; the
# rest either sleep here (latency kinds) or are returned to the caller
# to interpret (nan / torn_line / rotation).
_RAISING_KINDS = frozenset({"fail", "crash", "http_5xx", "exception"})
_SLEEPING_KINDS = frozenset({"latency", "stall", "hang"})


class InjectedFault(RuntimeError):
    """A fault the chaos plan injected at a seam (never a real error)."""

    def __init__(self, seam: str, kind: str = "fail"):
        super().__init__(f"chaos: injected {kind} at seam {seam!r}")
        self.seam = seam
        self.kind = kind


@dataclass
class FaultSpec:
    """One deterministic fault rule at one seam."""

    seam: str
    kind: str = "fail"
    after: int = 0          # skip this many events at the seam first
    count: int = 1          # events affected once active (-1 = forever)
    every: int = 1          # affect every k-th active event
    value: float = 0.0      # milliseconds for latency/stall/hang kinds
    prob: float = 1.0       # firing probability (seeded RNG)
    host: Optional[str] = None  # fleet scoping: fire only in this host
    _fired: int = field(default=0, repr=False)

    @classmethod
    def from_dict(cls, d: dict) -> "FaultSpec":
        known = {
            k: d[k]
            for k in ("seam", "kind", "after", "count", "every", "value",
                      "prob", "host")
            if k in d
        }
        if "seam" not in known:
            raise ValueError(f"fault spec missing 'seam': {d}")
        return cls(**known)

    def decide(self, event_no: int, rng: random.Random) -> bool:
        """Does this spec fire for the seam's ``event_no``-th event
        (0-based)? Mutates the fired counter — call once per event."""
        if event_no < self.after:
            return False
        if self.count >= 0 and self._fired >= self.count:
            return False
        if (event_no - self.after) % max(1, self.every) != 0:
            return False
        if self.prob < 1.0 and rng.random() >= self.prob:
            return False
        self._fired += 1
        return True


class FaultPlan:
    """Seeded, deterministic fault schedule over named seams."""

    def __init__(self, specs: List[FaultSpec] = None, seed: int = 0):
        from ..utils.guards import TrackedLock, register_shared

        self.specs = list(specs or [])
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._events: Dict[str, int] = {}
        # Every seam on every thread funnels through fire(): the event
        # counters are a registered mrsan shared object.
        self._lock = TrackedLock("fault_plan")
        register_shared("fault_plan", {"fault_plan"})
        self.injected: List[dict] = []  # what actually fired (tests)

    @classmethod
    def from_config(cls, chaos_config) -> Optional["FaultPlan"]:
        """Build the plan a ChaosConfig describes (inline ``faults``
        plus an optional ``plan_path`` JSON file); None when disabled."""
        if chaos_config is None or not getattr(
            chaos_config, "enabled", False
        ):
            return None
        specs = [FaultSpec.from_dict(dict(f)) for f in chaos_config.faults]
        seed = int(chaos_config.seed)
        if chaos_config.plan_path:
            data = json.loads(Path(chaos_config.plan_path).read_text())
            seed = int(data.get("seed", seed))
            specs.extend(
                FaultSpec.from_dict(f) for f in data.get("faults", [])
            )
        return cls(specs, seed=seed)

    def fire(self, seam: str) -> Optional[dict]:
        """Record one event at ``seam``; return the firing spec's action
        dict, or None. At most one spec fires per event (first match in
        plan order)."""
        from ..utils.guards import note_shared_access

        with self._lock:
            note_shared_access("fault_plan")
            n = self._events.get(seam, 0)
            self._events[seam] = n + 1
            for spec in self.specs:
                if spec.host is not None and spec.host != _chaos_host:
                    continue
                if spec.seam == seam and spec.decide(n, self._rng):
                    action = {
                        "seam": seam,
                        "kind": spec.kind,
                        "value": spec.value,
                        "event": n,
                    }
                    self.injected.append(action)
                    return action
        return None


# ------------------------------------------------------- process state

_plan: Optional[FaultPlan] = None
_journal = None
_journal_lock = threading.Lock()
_chaos_host: Optional[str] = None


def set_chaos_host(host_id: Optional[str]) -> None:
    """Declare which fleet host THIS process is, so host-scoped fault
    specs (``"host": "host1"``) can target one process of a fleet that
    shares a single plan file. None (the default) matches no scoped
    spec; unscoped specs fire everywhere regardless. Set once at
    process start, before any engine thread exists — the lock-free
    publish is intentional (mrlint R10's ``published`` seam)."""
    global _chaos_host
    _chaos_host = published(host_id)


def configure_chaos(config) -> Optional[FaultPlan]:
    """Install the process fault plan from a MicroRankConfig (fresh
    counters each call — one plan per run). Called by the stream engine
    and the serve service at start; a config without chaos clears it."""
    global _plan
    # Installed at run entry before worker/scheduler threads spin up;
    # seam threads read the binding lock-free by design (the plan
    # object itself synchronizes its counters) — mrlint R10's
    # ``published`` seam.
    _plan = published(FaultPlan.from_config(getattr(config, "chaos", None)))
    if _plan is not None and _plan.specs:
        log.warning(
            "chaos armed: %d fault spec(s), seed %d — this run WILL "
            "misbehave on purpose", len(_plan.specs), _plan.seed,
        )
    return _plan


def get_fault_plan() -> Optional[FaultPlan]:
    return _plan


def set_chaos_journal(journal) -> None:
    """Attach a RunJournal so every injected fault becomes a
    ``fault_injected`` event next to the windows it disturbed."""
    global _journal
    with _journal_lock:
        _journal = journal


def record_injection(seam: str, kind: str, value: float = 0.0) -> None:
    """Count one injected fault (metrics + journal) — the shared
    recording surface planned faults AND the legacy knobs go through."""
    from ..obs.metrics import record_fault_injection

    record_fault_injection(seam, kind)
    with _journal_lock:
        j = _journal
    if j is not None:
        try:
            j.emit("fault_injected", seam=seam, kind=kind, value=value)
        except Exception:  # noqa: BLE001 - chaos must not add real faults
            pass


def maybe_inject(
    seam: str, sleep: Callable[[float], None] = time.sleep
) -> Optional[dict]:
    """The one call every seam makes. Counts one event at ``seam``
    against the installed plan; when a spec fires it is recorded
    (metrics + journal) and then, by kind:

    * ``fail``/``crash``/``http_5xx`` — raise :class:`InjectedFault`;
    * ``latency``/``stall``/``hang`` — sleep ``value`` ms, return the
      action;
    * anything else (``nan``, ``torn_line``, ``rotation``) — return the
      action for the caller to interpret.

    No plan installed: a dict lookup and return, nothing else.
    """
    plan = _plan
    if plan is None:
        return None
    action = plan.fire(seam)
    if action is None:
        return None
    kind = action["kind"]
    record_injection(seam, kind, value=action.get("value", 0.0))
    log.warning("chaos: injecting %s at %s (event %d)",
                kind, seam, action["event"])
    if kind in _RAISING_KINDS:
        raise InjectedFault(seam, kind)
    if kind in _SLEEPING_KINDS and action.get("value", 0.0) > 0:
        sleep(float(action["value"]) / 1e3)
    return action
