"""Crash-only machinery: durable checkpoints, fault injection, retries.

Three pieces with one theme — the always-on engine must survive the
same chaos MicroRank's own evaluation methodology injects into the
systems it watches:

* ``checkpoint`` — the versioned, checksummed, atomically-written
  ``state.ckpt`` that makes ``cli stream --resume`` continue a crashed
  run instead of cold-starting it;
* ``faults`` — the seeded deterministic ``FaultPlan`` registry every
  seam consults (``--chaos PLAN.json``), replacing the ad-hoc
  injection knobs;
* ``retry`` — the one retry policy (exponential backoff + jitter +
  per-seam circuit breaker) behind every retried seam.
"""

from .checkpoint import (
    CHECKPOINT_NAME,
    CheckpointError,
    load_checkpoint,
    save_checkpoint,
)
from .faults import (
    FaultPlan,
    FaultSpec,
    InjectedFault,
    configure_chaos,
    get_fault_plan,
    maybe_inject,
    record_injection,
    set_chaos_host,
    set_chaos_journal,
)
from .retry import (
    BUILD_POLICY,
    DISPATCH_POLICY,
    WEBHOOK_POLICY,
    BreakerOpen,
    CircuitBreaker,
    RetryPolicy,
    get_breaker,
    record_attempt,
    reset_breakers,
    retry_call,
)

__all__ = [
    "BUILD_POLICY",
    "BreakerOpen",
    "CHECKPOINT_NAME",
    "CheckpointError",
    "CircuitBreaker",
    "DISPATCH_POLICY",
    "FaultPlan",
    "FaultSpec",
    "InjectedFault",
    "RetryPolicy",
    "WEBHOOK_POLICY",
    "configure_chaos",
    "get_breaker",
    "get_fault_plan",
    "load_checkpoint",
    "maybe_inject",
    "record_attempt",
    "record_injection",
    "reset_breakers",
    "retry_call",
    "save_checkpoint",
    "set_chaos_host",
    "set_chaos_journal",
]
