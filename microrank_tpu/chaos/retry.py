"""One retry policy for every seam: backoff + jitter + circuit breaker.

Before this module the repo had three divergent retry behaviors grown
independently: the serve batcher's bare one-shot dispatch retry, the
webhook sink's fire-and-forget (one bounded attempt, then the incident
notification silently vanished), and the tail source's parse-retry
loop with its own idle accounting. They now share one policy object
and one metrics surface:

* exponential backoff with full jitter (``base * 2^(attempt-1)`` capped
  at ``max_delay``, scaled by a uniform jitter draw) — retries from
  many seams never synchronize into a thundering herd;
* a per-seam circuit breaker: ``breaker_threshold`` CONSECUTIVE
  failures open it, further calls fail fast (``BreakerOpen``) until
  ``breaker_reset_s`` elapses, then a half-open probe either closes it
  (success) or re-opens it (failure). A seam that is definitively down
  costs one timeout per reset window instead of one per call;
* telemetry: ``microrank_retry_attempts_total{seam}`` counts RE-tries
  (attempt >= 2 — a healthy seam exposes the counter at zero),
  ``microrank_retry_exhausted_total{seam}`` counts giving up, and
  ``microrank_breaker_state{seam}`` gauges 0=closed / 1=open /
  2=half-open.

``retry_call(seam, fn)`` is the whole API for callers; tests inject
``sleep``/``clock`` for determinism.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from ..utils.logging import get_logger

log = get_logger("microrank_tpu.chaos.retry")


class BreakerOpen(RuntimeError):
    """Fast-fail: the seam's circuit breaker is open."""

    def __init__(self, seam: str, retry_in: float):
        super().__init__(
            f"circuit breaker open for seam {seam!r} "
            f"(half-open probe in {retry_in:.1f}s)"
        )
        self.seam = seam
        self.retry_in = retry_in


@dataclass(frozen=True)
class RetryPolicy:
    """Backoff + breaker knobs for one seam."""

    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    jitter: float = 0.5            # delay *= 1 + U(0, jitter)
    breaker_threshold: int = 8     # consecutive failures that open it
    breaker_reset_s: float = 30.0  # open -> half-open after this long
    half_open_probes: int = 1      # concurrent probes allowed half-open

    def delay(self, attempt: int, rng: random.Random) -> float:
        """Backoff before retry ``attempt`` (the attempt just failed
        was ``attempt``; 1-based)."""
        d = min(
            self.max_delay_s,
            self.base_delay_s * (2.0 ** max(0, attempt - 1)),
        )
        return d * (1.0 + self.jitter * rng.random())


# The per-seam defaults: the serve dispatch seam keeps the historical
# "one retry then degrade" shape (the numpy_ref fallback is the real
# answer there); the stream dispatch seam retries harder — a stream
# window has no fallback path, so dropping it costs an incident's
# evidence, and a coalesced burst can absorb several injected faults
# in ONE dispatch. Host-side seams are cheap and retry harder still.
DISPATCH_POLICY = RetryPolicy(
    max_attempts=2, base_delay_s=0.02, breaker_threshold=16
)
STREAM_DISPATCH_POLICY = RetryPolicy(
    max_attempts=3, base_delay_s=0.02, breaker_threshold=16
)
BUILD_POLICY = RetryPolicy(max_attempts=3, base_delay_s=0.01)
WEBHOOK_POLICY = RetryPolicy(
    max_attempts=4, base_delay_s=0.25, max_delay_s=10.0,
    breaker_threshold=6, breaker_reset_s=15.0,
)
DEFAULT_POLICY = RetryPolicy()

_BREAKER_STATES = {"closed": 0.0, "open": 1.0, "half_open": 2.0}


class CircuitBreaker:
    """Closed -> open after N consecutive failures -> half-open probe
    after the reset window -> closed on probe success."""

    def __init__(
        self,
        seam: str,
        policy: RetryPolicy,
        clock: Callable[[], float] = time.monotonic,
    ):
        from ..utils.guards import TrackedLock, register_shared

        self.seam = seam
        self.policy = policy
        self.clock = clock
        self.state = "closed"
        self.failures = 0              # consecutive
        self.opened_at = 0.0
        self._probes = 0
        # Retries from any thread feed one breaker per seam: the state
        # machine is a registered mrsan shared object.
        self._lock = TrackedLock("retry_breaker")
        register_shared("retry_breaker", {"retry_breaker"})
        self._gauge()

    def _gauge(self) -> None:
        from ..obs.metrics import record_breaker_state

        record_breaker_state(self.seam, _BREAKER_STATES[self.state])

    def allow(self) -> bool:
        """May a call proceed right now? Transitions open -> half-open
        when the reset window elapsed (the caller becomes the probe)."""
        from ..utils.guards import note_shared_access

        with self._lock:
            note_shared_access("retry_breaker")
            if self.state == "closed":
                return True
            if self.state == "open":
                if (
                    self.clock() - self.opened_at
                    < self.policy.breaker_reset_s
                ):
                    return False
                self.state = "half_open"
                self._probes = 0
                self._gauge()
                log.info("breaker %s: open -> half-open", self.seam)
            # half-open: admit a bounded number of probes.
            if self._probes < max(1, self.policy.half_open_probes):
                self._probes += 1
                return True
            return False

    def retry_in(self) -> float:
        with self._lock:
            if self.state != "open":
                return 0.0
            return max(
                0.0,
                self.policy.breaker_reset_s
                - (self.clock() - self.opened_at),
            )

    def record_success(self) -> None:
        with self._lock:
            if self.state != "closed":
                log.info("breaker %s: %s -> closed", self.seam, self.state)
            self.state = "closed"
            self.failures = 0
            self._gauge()

    def record_failure(self) -> None:
        with self._lock:
            self.failures += 1
            if self.state == "half_open" or (
                self.state == "closed"
                and self.failures >= self.policy.breaker_threshold
            ):
                self.state = "open"
                self.opened_at = self.clock()
                self._gauge()
                log.warning(
                    "breaker %s: OPEN after %d consecutive failures "
                    "(half-open probe in %.1fs)",
                    self.seam, self.failures,
                    self.policy.breaker_reset_s,
                )


_breakers: Dict[str, CircuitBreaker] = {}
_breakers_lock = threading.Lock()


def get_breaker(
    seam: str, policy: RetryPolicy = DEFAULT_POLICY
) -> CircuitBreaker:
    with _breakers_lock:
        br = _breakers.get(seam)
        if br is None:
            br = _breakers[seam] = CircuitBreaker(seam, policy)
        return br


def reset_breakers() -> None:
    """Drop all breaker state (tests; a fresh run starts closed)."""
    with _breakers_lock:
        _breakers.clear()


def record_attempt(seam: str) -> None:
    """Count one retry attempt at a seam that manages its own loop (the
    tail source's parse-retry goes through here so every retry in the
    process shares one counter)."""
    from ..obs.metrics import record_retry

    record_retry(seam)


def retry_call(
    seam: str,
    fn: Callable,
    policy: Optional[RetryPolicy] = None,
    sleep: Callable[[float], None] = time.sleep,
    rng: Optional[random.Random] = None,
    on_retry: Optional[Callable] = None,
):
    """Run ``fn()`` under the seam's unified retry policy.

    Raises ``BreakerOpen`` without calling ``fn`` when the breaker is
    open; otherwise retries up to ``max_attempts`` with jittered
    backoff, feeding the breaker a success/failure per attempt. The
    last failure re-raises after
    ``microrank_retry_exhausted_total{seam}`` is counted.
    """
    from ..obs.metrics import record_retry, record_retry_exhausted

    policy = policy or DEFAULT_POLICY
    rng = rng or random
    breaker = get_breaker(seam, policy)
    if not breaker.allow():
        raise BreakerOpen(seam, breaker.retry_in())
    attempts = max(1, int(policy.max_attempts))
    for attempt in range(1, attempts + 1):
        if attempt > 1:
            record_retry(seam)
        try:
            out = fn()
        except BreakerOpen:
            raise
        except Exception as e:  # noqa: BLE001 - the policy decides
            breaker.record_failure()
            if attempt >= attempts or not breaker.allow():
                record_retry_exhausted(seam)
                raise
            delay = policy.delay(attempt, rng)
            if on_retry is not None:
                on_retry(attempt, e, delay)
            log.warning(
                "%s attempt %d/%d failed (%s); retrying in %.0f ms",
                seam, attempt, attempts, e, delay * 1e3,
            )
            if delay > 0:
                sleep(delay)
            continue
        breaker.record_success()
        return out
    raise AssertionError("unreachable")  # pragma: no cover
