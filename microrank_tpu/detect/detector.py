"""Vectorized SLO-deviation anomaly detector (reference components C4-C6).

The reference loops in Python over traces and their operations
(anormaly_detector.py:56-73). Here the whole window is three segment
reductions over the span arrays:

    expected[t] = sum over spans s in t of (mu + k*sigma)[op(s)]
    real[t]     = max over spans s in t of duration(s) / 1000
    abnormal[t] = real[t] > expected[t] + slack

with the reference's edge semantics preserved: operations unseen in the SLO
baseline contribute 0 (the bare ``except`` at anormaly_detector.py:66-67),
and traces whose max span duration is <= 0 are dropped entirely
(preprocess_data.py:116-117).

Both a numpy implementation (host, oracle) and a jax implementation
(jit/vmap-able, used by the device pipeline) are provided; they agree
bit-for-bit on float32 inputs.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import numpy as np

from ..config import DetectorConfig
from ..graph.structures import DetectBatch, SloBaseline
from ..io.schema import US_PER_MS


class DetectResult(NamedTuple):
    """Per-trace verdicts on the window-local trace axis."""

    abnormal: np.ndarray  # bool[T] trace exceeded its expected duration
    valid: np.ndarray     # bool[T] trace has positive duration (kept)
    flag: np.ndarray      # bool scalar: window is anomalous
    expected_ms: np.ndarray  # float32[T]
    real_ms: np.ndarray      # float32[T]


def _thresholds(baseline: SloBaseline, cfg: DetectorConfig) -> np.ndarray:
    return baseline.mean_ms + np.float32(cfg.k_sigma) * baseline.std_ms


def detect_numpy(
    batch: DetectBatch, baseline: SloBaseline, cfg: DetectorConfig
) -> DetectResult:
    n_traces = int(batch.n_traces)
    n_spans = int(batch.n_spans)
    op = batch.op[:n_spans]
    trace = batch.trace[:n_spans]
    dur = batch.duration_us[:n_spans].astype(np.float32)

    thresh = _thresholds(baseline, cfg)
    contrib = np.where(op >= 0, thresh[np.clip(op, 0, None)], np.float32(0.0))
    expected = np.bincount(trace, weights=contrib, minlength=n_traces).astype(
        np.float32
    )
    real_us = np.full(n_traces, -np.inf, dtype=np.float32)
    np.maximum.at(real_us, trace, dur)
    real = (real_us / np.float32(US_PER_MS)).astype(np.float32)

    valid = real > 0
    abnormal = valid & (real > expected + np.float32(cfg.slack_ms))
    flag = np.asarray(abnormal.sum() >= cfg.min_abnormal_traces)
    return DetectResult(abnormal, valid, flag, expected, real)


def detect_jax(
    batch, thresh, n_traces_pad: int, cfg: DetectorConfig
):
    """JAX twin of ``detect_numpy``; fully shape-static, jittable.

    ``thresh`` is the precomputed ``mu + k*sigma`` float32 array (padding
    the SLO vocab with one trailing slot is the caller's concern);
    ``n_traces_pad`` is the static padded trace count. Padding spans carry
    op=-1 / duration=0 and are additionally masked by ``n_spans``.
    """
    import jax.numpy as jnp
    from jax import ops as jops

    span_live = jnp.arange(batch.op.shape[0]) < batch.n_spans
    known = (batch.op >= 0) & span_live
    contrib = jnp.where(
        known, jnp.take(thresh, jnp.clip(batch.op, 0), mode="clip"), 0.0
    )
    expected = jops.segment_sum(
        contrib, batch.trace, num_segments=n_traces_pad
    ).astype(jnp.float32)
    dur = jnp.where(span_live, batch.duration_us, -jnp.inf)
    real_us = jops.segment_max(dur, batch.trace, num_segments=n_traces_pad)
    real = (real_us / US_PER_MS).astype(jnp.float32)

    trace_live = jnp.arange(n_traces_pad) < batch.n_traces
    valid = trace_live & (real > 0)
    abnormal = valid & (real > expected + jnp.float32(cfg.slack_ms))
    flag = abnormal.sum() >= cfg.min_abnormal_traces
    return DetectResult(abnormal, valid, flag, expected, real)
