from .detector import DetectResult, detect_jax, detect_numpy
from .slo import compute_slo, slo_as_dict


def detect_partition(config, slo_vocab, baseline, window_df):
    """Detect + partition one window frame: returns
    ``(flag, normal_ids, abnormal_ids)``.

    The shared twin of ``OnlineRCA.detect_window`` used by every
    non-batch path (serve request handling, the streaming engine):
    valid traces split into abnormal (exceeded expected duration) and
    normal; invalid (non-positive duration) traces drop, matching the
    reference's edge semantics.
    """
    from ..graph import build_detect_batch
    from ..utils.guards import contract_checks

    with contract_checks(config.runtime.validate_numerics):
        batch, trace_ids = build_detect_batch(window_df, slo_vocab)
    res = detect_numpy(batch, baseline, config.detector)
    abn = [t for t, a in zip(trace_ids, res.abnormal) if a]
    nrm = [
        t
        for t, a, v in zip(trace_ids, res.abnormal, res.valid)
        if v and not a
    ]
    return bool(res.flag), nrm, abn


__all__ = [
    "DetectResult",
    "detect_jax",
    "detect_numpy",
    "detect_partition",
    "compute_slo",
    "slo_as_dict",
]
