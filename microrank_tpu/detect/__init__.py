from .detector import DetectResult, detect_jax, detect_numpy
from .slo import compute_slo, slo_as_dict

__all__ = [
    "DetectResult",
    "detect_jax",
    "detect_numpy",
    "compute_slo",
    "slo_as_dict",
]
