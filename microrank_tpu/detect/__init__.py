from .detector import DetectResult, detect_jax, detect_numpy
from .slo import compute_slo, slo_as_dict


def error_trace_ids(window_df) -> frozenset:
    """Traces carrying an error-status span (``statusCode > 0``).

    The column is optional — span frames without it (every pre-existing
    dump and the native lane) return the empty set, so the latency-only
    behavior is unchanged. Non-numeric status values count as OK.
    """
    if "statusCode" not in window_df.columns:
        return frozenset()
    import pandas as pd

    status = pd.to_numeric(
        window_df["statusCode"], errors="coerce"
    ).fillna(0)
    return frozenset(window_df.loc[status > 0, "traceID"].unique())


def detect_partition(config, slo_vocab, baseline, window_df):
    """Detect + partition one window frame: returns
    ``(flag, normal_ids, abnormal_ids)``.

    The ONE detection seam shared by the batch runner
    (``OnlineRCA.detect_window``), serve request handling, and the
    streaming engine: valid traces split into abnormal (exceeded
    expected duration, or — with ``DetectorConfig.
    error_status_abnormal`` — carrying an error-status span) and
    normal; invalid (non-positive duration) traces drop, matching the
    reference's edge semantics. The window flags anomalous once the
    abnormal partition reaches ``min_abnormal_traces``.
    """
    from ..graph import build_detect_batch
    from ..utils.guards import contract_checks

    with contract_checks(config.runtime.validate_numerics):
        batch, trace_ids = build_detect_batch(window_df, slo_vocab)
    res = detect_numpy(batch, baseline, config.detector)
    err = (
        error_trace_ids(window_df)
        if config.detector.error_status_abnormal
        else frozenset()
    )
    nrm, abn = [], []
    for t, a, v in zip(trace_ids, res.abnormal, res.valid):
        if not v:
            continue
        if a or t in err:
            abn.append(t)
        else:
            nrm.append(t)
    flag = len(abn) >= config.detector.min_abnormal_traces
    return bool(flag), nrm, abn


__all__ = [
    "DetectResult",
    "detect_jax",
    "detect_numpy",
    "detect_partition",
    "error_trace_ids",
    "compute_slo",
    "slo_as_dict",
]
