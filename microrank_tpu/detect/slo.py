"""SLO baseline: per-operation duration mean/std (reference component C3).

Reproduces ``get_operation_slo`` (/root/reference/preprocess_data.py:50-78):
population std (numpy ddof=0), microsecond durations converted to ms and
rounded to 4 decimals. The reference returns ``{op: [mean, std]}``; here the
canonical form is a ``Vocab`` plus dense float32 arrays (the device-ready
layout), with the dict view derivable for the oracle backend.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Tuple

import numpy as np
import pandas as pd

from ..graph.structures import SloBaseline
from ..io.interning import Vocab
from ..io.naming import operation_names
from ..io.schema import DEFAULT_STRIP_LAST_SEGMENT_SERVICES, US_PER_MS


def slo_quantile(stat: str) -> float:
    """Parse a percentile SLO statistic: "p90" -> 0.9, "p99.9" -> 0.999.

    Raises ValueError for anything that is not p<number in (0, 100].
    """
    if not stat.startswith("p"):
        raise ValueError(f"unknown SLO statistic {stat!r}")
    try:
        pct = float(stat[1:])
    except ValueError:
        raise ValueError(f"unknown SLO statistic {stat!r}") from None
    if not 0.0 < pct <= 100.0:
        raise ValueError(f"SLO percentile out of range: {stat!r}")
    return pct / 100.0


def compute_slo(
    span_df: pd.DataFrame,
    strip_services: FrozenSet[str] = DEFAULT_STRIP_LAST_SEGMENT_SERVICES,
    stat: str = "mean",
) -> Tuple[Vocab, SloBaseline]:
    """Compute the SLO baseline from a (long) normal-period span dump.

    ``stat="mean"`` is the reference behavior; ``stat="pNN"`` (e.g. "p90",
    "p99", "p99.9") substitutes that percentile of the duration for the
    mean — the alternative the reference left commented out
    (preprocess_data.py:72).
    """
    names = operation_names(span_df, "service", strip_services)
    dur = span_df["duration"].astype(float)
    grouped = dur.groupby(names.to_numpy())
    if stat == "mean":
        center_ms = (grouped.mean() / US_PER_MS).round(4)
    else:
        center_ms = (grouped.quantile(slo_quantile(stat)) / US_PER_MS).round(4)
    std_ms = (grouped.std(ddof=0) / US_PER_MS).round(4)
    vocab = Vocab(center_ms.index.tolist())
    baseline = SloBaseline(
        mean_ms=center_ms.to_numpy(dtype=np.float32),
        std_ms=std_ms.to_numpy(dtype=np.float32),
    )
    return vocab, baseline


def slo_as_dict(vocab: Vocab, baseline: SloBaseline) -> Dict[str, List[float]]:
    """The reference's ``{operation: [mean, std]}`` view."""
    return {
        vocab.name(i): [float(baseline.mean_ms[i]), float(baseline.std_ms[i])]
        for i in range(len(vocab))
    }
