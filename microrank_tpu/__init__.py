"""microrank_tpu — a TPU-native trace-based root cause analysis framework.

Brand-new implementation of the capabilities of MicroRank (WWW'21,
CUHK-SE-Group/MicroRank): SLO-deviation anomaly detection over distributed
traces, personalized PageRank over operation<->trace bipartite graphs, and
weighted-spectrum ranking of suspect operations — rebuilt as an idiomatic
JAX/XLA pipeline (host-side vectorized graph build -> padded COO arrays ->
one jitted device program per window, vmap-able over window batches and
shard_map-sharded over the graph's entry axis).

See SURVEY.md for the structural analysis of the reference and the layer
mapping; every module docstring cites the reference file:line it covers.
"""

from .config import (
    CompatConfig,
    DetectorConfig,
    MicroRankConfig,
    PageRankConfig,
    RuntimeConfig,
    SpectrumConfig,
    WindowConfig,
)

__version__ = "0.4.0"

__all__ = [
    "MicroRankConfig",
    "DetectorConfig",
    "PageRankConfig",
    "SpectrumConfig",
    "WindowConfig",
    "CompatConfig",
    "RuntimeConfig",
    "__version__",
]
