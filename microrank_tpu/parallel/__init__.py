from .distributed import (
    fetch_replicated,
    global_put,
    initialize_distributed,
    is_primary,
)
from .mesh import SHARD_AXIS, WINDOW_AXIS, make_mesh, single_axis_mesh
from .sharded_rank import (
    rank_windows_batched,
    rank_windows_explained_sharded,
    rank_windows_sharded,
    rank_windows_sharded_checked,
    rank_windows_sharded_checked_traced,
    resolve_sharded_rank_fn,
    stack_window_graphs,
)

__all__ = [
    "SHARD_AXIS",
    "WINDOW_AXIS",
    "make_mesh",
    "single_axis_mesh",
    "rank_windows_batched",
    "rank_windows_explained_sharded",
    "rank_windows_sharded",
    "rank_windows_sharded_checked",
    "rank_windows_sharded_checked_traced",
    "resolve_sharded_rank_fn",
    "stack_window_graphs",
    "initialize_distributed",
    "is_primary",
    "global_put",
    "fetch_replicated",
]
