"""Multi-host runtime entry (component C19 — the reference has no
distributed layer at all; SURVEY.md §5 plans `jax.distributed` + XLA
collectives over ICI/DCN).

One call makes a multi-process deployment real:

    initialize_distributed()        # before ANY other jax use
    mesh = make_mesh((w, s))        # jax.devices() now spans all hosts

Every process runs the same program; the shard_map/psum ranking code is
unchanged — XLA compiles the collectives onto ICI within a slice and DCN
across hosts. Host data becomes global arrays with ``global_put`` (each
process contributes the shards it addresses), and only process 0 should
write results (``is_primary``).

Tested two-process on CPU: tests/test_distributed.py spawns two real
processes that form one 8-device mesh and must rank identically to the
single-process path.
"""

from __future__ import annotations

import os
from typing import Optional

_initialized = False


def cpu_collectives_supported() -> bool:
    """True when this jax/jaxlib can run cross-process collectives on
    the CPU backend (Gloo TCP transport + the config knob that selects
    it). Older jaxlibs hard-raise "Multiprocess computations aren't
    implemented on the CPU backend" inside any sharded program that
    spans processes — tests gate on this probe instead of failing
    unconditionally (ROADMAP open item). Probing imports no backend.
    """
    try:
        from jax._src.lib import xla_extension
    except ImportError:
        return False
    return hasattr(xla_extension, "make_gloo_tcp_collectives")


def _enable_cpu_collectives() -> None:
    """Select Gloo CPU collectives BEFORE the CPU client initializes
    (the choice is baked into client creation). No-op on accelerator
    runtimes — their ICI/DCN collectives need no plumbing — and on
    jaxlibs without the knob."""
    import jax

    platforms = os.environ.get("JAX_PLATFORMS") or ""
    try:
        platforms = platforms or (jax.config.jax_platforms or "")
    except AttributeError:  # pragma: no cover - very old jax
        pass
    if "cpu" not in platforms.lower().split(","):
        return
    if not cpu_collectives_supported():
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # pragma: no cover - knob absent on this jax
        pass


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Idempotent `jax.distributed.initialize` wrapper.

    Must run before any other jax API touches a backend. Arguments
    default from the environment:

    * ``MICRORANK_COORDINATOR``   — "host:port" of process 0
    * ``MICRORANK_NUM_PROCESSES`` — world size
    * ``MICRORANK_PROCESS_ID``    — this process's rank

    With none of the three supplied (args or env), this is a no-op
    returning False — single-process runs never pay for it. With only
    ``MICRORANK_COORDINATOR`` set, jax's own cluster auto-detection
    fills the rest (TPU pods, SLURM, etc.). Returns True when a
    multi-process runtime is active after the call.
    """
    global _initialized
    import jax

    coordinator_address = _resolve_coordinator(coordinator_address)
    if num_processes is None and "MICRORANK_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["MICRORANK_NUM_PROCESSES"])
    if process_id is None and "MICRORANK_PROCESS_ID" in os.environ:
        process_id = int(os.environ["MICRORANK_PROCESS_ID"])

    if _initialized:
        return jax.process_count() > 1
    if coordinator_address is None:
        if num_processes is not None or process_id is not None:
            # Partial config (e.g. a leftover MICRORANK_NUM_PROCESSES):
            # keep the documented graceful fallback instead of letting
            # jax.distributed.initialize raise on a missing coordinator.
            from ..utils.logging import get_logger

            get_logger("microrank_tpu.parallel").warning(
                "distributed config incomplete (num_processes/process_id "
                "set but no coordinator address); running single-process"
            )
        return False

    # CPU runtimes need the Gloo collectives selected before the client
    # exists, or every cross-process psum raises "Multiprocess
    # computations aren't implemented on the CPU backend".
    _enable_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    return jax.process_count() > 1


def _resolve_coordinator(
    coordinator_address: Optional[str],
) -> Optional[str]:
    """The one place the coordinator address is resolved: explicit
    argument, else ``MICRORANK_COORDINATOR``."""
    if coordinator_address is not None:
        return coordinator_address
    return os.environ.get("MICRORANK_COORDINATOR")


def coordinator_configured(
    coordinator_address: Optional[str] = None,
) -> bool:
    """True when ``initialize_distributed`` would see a coordinator
    address, so callers can tell "initialized but single-process world"
    apart from "never configured"."""
    return _resolve_coordinator(coordinator_address) is not None


def is_primary() -> bool:
    """True on the process that should write results (rank 0)."""
    import jax

    return jax.process_index() == 0


def global_put(tree, mesh, specs):
    """Replicated host data -> global jax.Arrays over a (possibly
    multi-process) mesh.

    Every process is expected to hold the SAME full host arrays (the
    deterministic graph build makes this natural: each host ingests the
    same window and builds the same arrays); each contributes exactly
    the shards its local devices address via
    ``jax.make_array_from_callback``. Single-process meshes work too —
    this is then equivalent to a sharded ``jax.device_put``.

    ``tree``/``specs`` are matching pytrees (e.g. a stacked WindowGraph
    and the PartitionSpec tree from ``sharded_rank._partition_specs``).
    """
    import jax
    import numpy as np
    from jax.sharding import NamedSharding

    def put(x, spec):
        x = np.asarray(x)
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(
            x.shape, sharding, lambda idx: x[idx]
        )

    return jax.tree.map(put, tree, specs)


def fetch_replicated(tree):
    """Device results -> host numpy on every process.

    Arrays sharded across processes (e.g. ranking outputs split over the
    ``windows`` axis) are allgathered so every process sees the full
    value; fully-addressable arrays (replicated outputs, or any
    single-process array) are plain device_gets — process_allgather
    would wrongly STACK a replicated array once per process.
    """
    import jax

    if jax.process_count() == 1:
        return jax.device_get(tree)
    from jax.experimental import multihost_utils

    def fetch(x):
        if getattr(x, "is_fully_addressable", True):
            return jax.device_get(x)
        return multihost_utils.process_allgather(x, tiled=True)

    return jax.tree.map(fetch, tree)
